"""Per-launch device ledger: the device observatory's substrate.

The verify/hash spine's metrics say how many signatures a backend
verified and how long the calls took; the height ledger says which
consensus phase dominated a height; neither answers the question every
"reseed on real silicon" caveat in BENCH_hotpath.json leaves open:
**for one device launch, where did the time and the capacity go?** The
`LaunchLedger` answers it with ONE structured record per launch,
assembled at the seams that already exist — no new plumbing through
the verifier stack:

* `DispatchQueue` launch/finalize (`services/dispatch.py`) opens the
  record on the worker thread and closes it at the consumer's join,
  which is where the handle lifecycle yields the stage split:
  `queue_wait_s` (submit -> launch start), `host_prep_s` (lane prep +
  kernel dispatch on the worker), `in_flight_s` (kernel enqueued ->
  consumer reaches finalize — the window the pipeline hides), and
  `finalize_s` (materialization blocking the consumer);
* `VerifyCoalescer` flush (`services/batcher.py`) tags the launch with
  its consumer mix, the rows the `VerifiedSigCache` withheld, and the
  exemplar trace context of the merged requests;
* the executing backends (`services/verifier.py`, `services/hasher.py`,
  `parallel/mesh.py`, `ops/merkle_kernel.py`) annotate what only they
  know: backend, mesh width, requested vs padded rows (the
  `ops/padding.py` bucket geometry, so occupancy and padding-waste %
  fall straight out), host->device transfer bytes including the
  sharded-table `device_put`, and compile-cache hit/miss with compile
  seconds for `_STEP_CACHE` misses.

Assembly is thread-ambient (`begin`/`annotate`/`observe`/`commit`):
the dispatch worker opens a record, deep code annotates whatever is
ambient, and exactly one commit lands per launch — the resilient and
coalescing wrappers around a backend never double-count because nested
annotation joins the open record instead of minting a new one.
Synchronous device calls (no dispatch queue) open an implicit record
at their first annotation and commit it at the backend's observe;
host-library micro-calls (single votes, tiny merkle roots) are not
launches and record nothing unless they execute inside a dispatch
handle (the breaker-fallback case, recorded as the degraded launch it
is).

Storage follows `telemetry/heightlog.py`: a bounded in-memory ring
plus an optional JSONL file under the data dir (compacted in place),
served live via `dump_telemetry?launches=N` (`telemetry/views.py`
"launches" view), embedded in flight-recorder dumps, and merged across
nodes by `tools/device_report.py` into the per-kind waterfall that
names the top waste source.

Like the registry and FLIGHT, the ledger is process-global (the
verifier/hasher stacks and their dispatch queues are process
singletons); multi-node-in-process harnesses see one interleaved
ledger tagged with the last-attached node id — documented
approximation, same as the flight recorder.

`TENDERMINT_TPU_LAUNCHLOG=0` disables recording entirely (the bench
overhead guard measures the difference; it must stay within 3%).
"""

from __future__ import annotations

import json
import os
import threading
import time

DEFAULT_CAPACITY = 1024

# launch kinds the ledger (and the tendermint_launch_rows metric) knows
KINDS = ("verify", "hash", "tables", "leaf_hashes")

_REG_LOCK = threading.Lock()
_DUMP_SEQ = 0


class LaunchLedger:
    """Bounded ring of per-launch records + optional JSONL persistence."""

    def __init__(
        self,
        path: str | None = None,
        capacity: int = DEFAULT_CAPACITY,
        node_id: str = "",
    ) -> None:
        self.capacity = max(1, capacity)
        self.node_id = node_id
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._fh = None
        self._count = 0
        self._closed = False
        self.path: str | None = None
        # wall time of the last successfully committed launch — the
        # `/health` "device" section's staleness signal
        self._last_success_t: float | None = None
        if path:
            self.attach(path, node_id)

    # -- wiring (node boot) ------------------------------------------------

    def attach(self, path: str, node_id: str = "") -> None:
        """Point the ledger at a JSONL file under a node's data dir and
        adopt that node's id for new records (process-global ledger:
        last attach wins, like FLIGHT.set_dump_dir)."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            self.path = path
            if node_id:
                self.node_id = node_id
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                for rec in self._load_file():
                    self._ring.append(rec)
                self._ring = self._ring[-self.capacity :]
                self._count = len(self._ring)
                self._fh = open(path, "a", encoding="utf-8")
            except OSError:
                self._fh = None

    def _load_file(self) -> list[dict]:
        """The newest `capacity` persisted records (oldest first); torn
        final lines from a crash are skipped, not fatal."""
        out: list[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines[-self.capacity :]:
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "kind" in d:
                out.append(d)
        return out

    # -- recording ---------------------------------------------------------

    def record(self, rec: dict) -> dict:
        """Stamp and append one launch record; must never fail the
        launching caller."""
        if self.node_id and "node" not in rec:
            rec["node"] = self.node_id
        with self._lock:
            if self._closed:
                return rec
            if not rec.get("error"):
                self._last_success_t = rec.get("t", time.time())
            self._ring.append(rec)
            if len(self._ring) > self.capacity:
                del self._ring[: len(self._ring) - self.capacity]
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
                    self._fh.flush()
                    self._count += 1
                    if self._count > 2 * self.capacity:
                        self._compact_locked()
                except (OSError, ValueError):
                    pass
        return rec

    def _compact_locked(self) -> None:
        """Rewrite the file to its newest `capacity` lines via tmp +
        atomic rename (heightlog's compaction discipline)."""
        self._fh.close()
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                tail = f.readlines()[-self.capacity :]
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.writelines(tail)
            os.replace(tmp, self.path)
            self._count = len(tail)
        finally:
            self._fh = open(self.path, "a", encoding="utf-8")

    # -- reads -------------------------------------------------------------

    def recent(self, n: int | None = None, kind: str = "") -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if kind:
            recs = [r for r in recs if r.get("kind") == kind]
        if n is not None:
            recs = recs[-n:]
        return recs

    def last(self) -> dict | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def seconds_since_success(self) -> float | None:
        """Age of the last successful launch (None before any) — the
        health snapshot's "is the device still answering" signal."""
        with self._lock:
            t = self._last_success_t
        return None if t is None else max(0.0, time.time() - t)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_success_t = None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# The process-wide ledger (FLIGHT/REGISTRY conventions): the dispatch
# queues and backend singletons that produce launches are process-wide
# too, so one ledger sees every launch. `node.Node` attaches the JSONL
# path + node id at boot.
LAUNCHLOG = LaunchLedger()


def dump_all(dir: str, reason: str = "manual") -> str | None:
    """Atomically write the ledger ring as one JSON file under `dir`
    (tmp + rename; heightlog's dump discipline). Never raises."""
    global _DUMP_SEQ
    if not dir:
        return None
    try:
        os.makedirs(dir, exist_ok=True)
        with _REG_LOCK:
            _DUMP_SEQ += 1
            seq = _DUMP_SEQ
        safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)[:48]
        path = os.path.join(dir, f"launchledger-{safe}-{seq}.json")
        tmp = path + ".tmp"
        payload = {
            "reason": reason,
            "dumped_at": time.time(),
            "node": LAUNCHLOG.node_id,
            "records": LAUNCHLOG.recent(),
        }
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:
        return None


# -- ambient per-launch assembly ----------------------------------------------
#
# One record is owned by exactly one "launch context": the dispatch
# worker (begin/detach at launch, reattach/commit at the consumer's
# finalize), or — for synchronous device calls — an implicit record
# opened at the first annotation and committed by the backend's
# observe. Thread-local, so concurrent launches on different workers
# never cross.

_tls = threading.local()


def _enabled() -> bool:
    return os.environ.get("TENDERMINT_TPU_LAUNCHLOG", "1") != "0"


def current() -> dict | None:
    return getattr(_tls, "rec", None)


def begin(kind: str, queue: str = "", tags: dict | None = None) -> dict | None:
    """Open the ambient launch record for this thread (the dispatch
    worker's seam). Replaces any stale implicit record a failed
    synchronous launch left behind. `tags=None` adopts this thread's
    ambient `tag()` fields (the synchronous-launch case); the dispatch
    worker passes the tags captured on the submitting thread instead.
    Returns None when disabled."""
    if not _enabled():
        _tls.rec = None
        return None
    rec: dict = {"kind": kind, "rows": 0, "_t0": time.perf_counter()}
    if queue:
        rec["queue"] = queue
    if tags is None:
        tags = current_tags()
    if tags:
        rec.update(tags)
    _tls.rec = rec
    return rec


def detach(rec: dict) -> dict:
    """Remove the ambient record (it crosses to the consumer thread on
    the dispatch handle; `reattach` re-installs it there)."""
    if getattr(_tls, "rec", None) is rec:
        _tls.rec = None
    return rec


def reattach(rec: dict) -> None:
    _tls.rec = rec


def annotate(_additive: bool = False, **fields) -> None:
    """Merge fields into the ambient launch record; synchronous device
    launches (no dispatch queue) get an implicit record on first
    annotation, committed by the backend's `observe`. `_additive` sums
    numeric fields instead of overwriting (chunked launches)."""
    if not _enabled():
        return
    rec = getattr(_tls, "rec", None)
    if rec is None:
        rec = begin("verify")
        if rec is None:
            return
        rec["_implicit"] = True
    if _additive:
        for k, v in fields.items():
            rec[k] = rec.get(k, 0) + v
    else:
        rec.update(fields)


def add_transfer(nbytes: int) -> None:
    """Accumulate host->device transfer bytes into the ambient record
    (lane arrays, padded blocks, sharded-table `device_put`)."""
    annotate(_additive=True, transfer_bytes=int(nbytes))


def observe(kind: str, backend: str, rows: int, seconds: float) -> None:
    """The executing backend's per-call report (`_observe_verify` /
    `_observe_hash` twin). Inside a launch context it annotates the
    open record; outside one it records a standalone launch — unless
    the backend is the host library, whose synchronous micro-calls are
    not device launches."""
    if not _enabled():
        return
    rec = getattr(_tls, "rec", None)
    if rec is None:
        if backend == "host":
            return  # a host micro-call outside any launch context
        rec = begin(kind)
        if rec is None:
            return
        rec["_implicit"] = True
    if kind in ("tables", "leaf_hashes") or "kind" not in rec:
        rec["kind"] = kind
    rec["backend"] = backend
    rec["rows"] = rec.get("rows", 0) + int(rows)
    rec["device_s"] = round(rec.get("device_s", 0.0) + seconds, 6)
    if rec.pop("_implicit", None):
        commit(rec)


def commit(rec: dict, error: BaseException | None = None) -> dict:
    """Close one launch record: strip assembly-internal keys, observe
    the catalog metrics, append to the ledger. Never raises — the
    ledger must not fail the verify spine."""
    try:
        if getattr(_tls, "rec", None) is rec:
            _tls.rec = None
        t0 = rec.pop("_t0", None)
        rec.pop("_t_launch_end", None)
        rec.pop("_implicit", None)
        if error is not None:
            rec["error"] = type(error).__name__
        rec["t"] = time.time()
        if "total_s" not in rec:
            total = (
                time.perf_counter() - t0
                if t0 is not None
                else rec.get("device_s", 0.0)
            )
            rec["total_s"] = round(total, 6)
        for k in ("queue_wait_s", "host_prep_s", "in_flight_s", "finalize_s",
                  "total_s", "device_s", "compile_s", "device_put_s"):
            if k in rec:
                rec[k] = round(float(rec[k]), 6)
        _observe_metrics(rec)
        return LAUNCHLOG.record(rec)
    except Exception:
        return rec


def _observe_metrics(rec: dict) -> None:
    from tendermint_tpu.telemetry import metrics as _m

    kind = rec.get("kind", "verify")
    if kind not in KINDS:
        kind = "verify"
    rows = int(rec.get("rows", 0))
    if rows:
        _m.LAUNCH_ROWS.labels(kind=kind, state="useful").inc(rows)
    padded = int(rec.get("rows_padded", 0))
    if padded:
        _m.LAUNCH_ROWS.labels(kind=kind, state="padded").inc(padded)
    cached = int(rec.get("rows_cached", 0))
    if cached:
        _m.LAUNCH_ROWS.labels(kind=kind, state="cached").inc(cached)
    for stage in ("queue_wait", "host_prep", "in_flight", "finalize"):
        v = rec.get(stage + "_s")
        if v is not None:
            _m.LAUNCH_STAGE_SECONDS.labels(stage=stage).observe(
                v, exemplar=rec.get("trace")
            )
    tb = rec.get("transfer_bytes")
    if tb:
        _m.LAUNCH_TRANSFER_BYTES.observe(float(tb))


class tag:
    """Submit-time annotations: fields set here ride into the NEXT
    launch handle created on this thread (the coalescer tags its flush
    with the consumer mix / cached rows before submitting) and into any
    synchronous launch executed inside the block."""

    def __init__(self, **fields) -> None:
        self._fields = fields
        self._prev: dict | None = None

    def __enter__(self):
        self._prev = getattr(_tls, "tags", None)
        merged = dict(self._prev) if self._prev else {}
        merged.update(self._fields)
        _tls.tags = merged
        return self

    def __exit__(self, *exc):
        _tls.tags = self._prev
        return False


def current_tags() -> dict | None:
    """Snapshot of the submit-time tags ambient on this thread (the
    dispatch handle captures them at construction, like the trace
    context)."""
    tags = getattr(_tls, "tags", None)
    return dict(tags) if tags else None


# -- summaries ----------------------------------------------------------------


def summarize(records: list[dict]) -> dict:
    """Per-kind rollup of a record window — the shared aggregation the
    `launches` dump view and `tools/device_report.py` both use, so a
    live dump and an offline ledger merge can never disagree."""
    kinds: dict[str, dict] = {}
    for r in records:
        kind = r.get("kind", "verify")
        agg = kinds.setdefault(
            kind,
            {
                "launches": 0,
                "errors": 0,
                "rows": 0,
                "rows_padded": 0,
                "rows_cached": 0,
                "transfer_bytes": 0,
                "compile_hits": 0,
                "compile_misses": 0,
                "compile_s": 0.0,
                "device_put_s": 0.0,
                "stages_s": {
                    "queue_wait": 0.0,
                    "host_prep": 0.0,
                    "in_flight": 0.0,
                    "finalize": 0.0,
                },
                "total_s": 0.0,
                "consumers": {},
            },
        )
        agg["launches"] += 1
        if r.get("error"):
            agg["errors"] += 1
        agg["rows"] += int(r.get("rows", 0))
        agg["rows_padded"] += int(r.get("rows_padded", 0))
        agg["rows_cached"] += int(r.get("rows_cached", 0))
        agg["transfer_bytes"] += int(r.get("transfer_bytes", 0))
        if r.get("compile") == "hit":
            agg["compile_hits"] += 1
        elif r.get("compile") == "miss":
            agg["compile_misses"] += 1
        agg["compile_s"] += float(r.get("compile_s", 0.0))
        agg["device_put_s"] += float(r.get("device_put_s", 0.0))
        for stage in agg["stages_s"]:
            agg["stages_s"][stage] += float(r.get(stage + "_s", 0.0))
        agg["total_s"] += float(r.get("total_s", 0.0))
        for consumer, n in (r.get("consumers") or {}).items():
            agg["consumers"][consumer] = agg["consumers"].get(consumer, 0) + n
    for agg in kinds.values():
        shipped = agg["rows"] + agg["rows_padded"]
        agg["occupancy_pct"] = (
            round(100.0 * agg["rows"] / shipped, 1) if shipped else None
        )
        agg["padding_waste_pct"] = (
            round(100.0 * agg["rows_padded"] / shipped, 1) if shipped else None
        )
        offered = agg["rows"] + agg["rows_cached"]
        agg["cache_withheld_pct"] = (
            round(100.0 * agg["rows_cached"] / offered, 1) if offered else None
        )
        agg["compile_s"] = round(agg["compile_s"], 6)
        agg["device_put_s"] = round(agg["device_put_s"], 6)
        agg["total_s"] = round(agg["total_s"], 6)
        agg["stages_s"] = {
            k: round(v, 6) for k, v in agg["stages_s"].items()
        }
    return kinds
