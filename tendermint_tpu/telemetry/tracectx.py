"""Cross-node trace-context propagation: the distributed half of tracing.

The PR 2 tracer records spans inside one process; this module gives a
span a *cluster-wide identity* so spans recorded on different nodes can
be stitched back into one timeline (`tools/trace_timeline.py`). A
`TraceContext` is minted head-based at the edges of the system — tx
admission (`mempool/mempool.py`) and vote/proposal creation
(`consensus/state.py`) — and then rides along two channels:

* **the wire** — an optional trailing block on the p2p frame
  (`p2p/connection.py`), codec-backward-compatible: old frames carry no
  block and decode unchanged; decode failures drop the context, never
  the frame;
* **the thread** — a thread-ambient slot (`use()` / `current()`): the
  p2p recv loop sets the decoded context around `on_receive`, reactors
  hand work to mempool/consensus on the same thread, and the consensus
  loop re-establishes the record's context while processing it, so
  gossip-out sends re-attach it without any per-call-site plumbing.

Sampling is head-based and decided once at mint: an unsampled message
carries NO context bytes on the wire and costs one thread-local read on
the hot paths. `TENDERMINT_TPU_TRACE_SAMPLE` holds the 1-in-N rate
(default 64; 0 disables minting; 1 samples everything). Breaker trips
and mesh re-meshes `boost()` a temporary sample-everything window (the
transitions are exactly when per-message attribution pays for itself),
and the nemesis harness forces sampling for the whole chaos run.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from tendermint_tpu.codec.binary import Reader, Writer

SAMPLE_ENV = "TENDERMINT_TPU_TRACE_SAMPLE"
DEFAULT_SAMPLE = 64

# wire-block version tag: a future layout bumps it and old nodes drop
# the (still well-framed) block instead of misparsing it
_WIRE_VERSION = 1

_ID_BYTES = 8


@dataclass(frozen=True)
class TraceContext:
    """Compact identity one message carries across the cluster:
    (trace_id, parent span_id, origin node_id). Immutable — hops
    re-parent via `rehop()` rather than mutating."""

    trace_id: bytes
    span_id: bytes
    origin: str

    @property
    def trace(self) -> str:
        """Hex trace id — the attr value every stitched span carries."""
        return self.trace_id.hex()

    def rehop(self) -> "TraceContext":
        """Fresh parent span id for the next hop; trace/origin stay."""
        return TraceContext(self.trace_id, os.urandom(_ID_BYTES), self.origin)

    def encode_wire(self) -> bytes:
        return (
            Writer()
            .uvarint(_WIRE_VERSION)
            .raw(self.trace_id[:_ID_BYTES].ljust(_ID_BYTES, b"\x00"))
            .raw(self.span_id[:_ID_BYTES].ljust(_ID_BYTES, b"\x00"))
            .string(self.origin)
            .build()
        )

    @classmethod
    def decode_wire(cls, r: Reader) -> "TraceContext":
        version = r.uvarint()
        if version != _WIRE_VERSION:
            raise ValueError(f"unknown trace-context version {version}")
        trace_id = r.raw(_ID_BYTES)
        span_id = r.raw(_ID_BYTES)
        origin = r.string()
        return cls(trace_id, span_id, origin)


# -- sampling -----------------------------------------------------------------

_counter = itertools.count()
_force_all = False
_boost_until = 0.0
_boost_lock = threading.Lock()


def sample_rate() -> int:
    """1-in-N mint rate (0 = tracing off). Read per mint so tests and
    operators can flip the env knob on a live process."""
    try:
        return int(os.environ.get(SAMPLE_ENV, str(DEFAULT_SAMPLE)))
    except ValueError:
        return DEFAULT_SAMPLE


def force_all(on: bool) -> None:
    """Sample everything until turned off — the nemesis harness arms
    this for chaos runs so every forensic message is attributable."""
    global _force_all
    _force_all = on


def boost(duration_s: float = 30.0) -> None:
    """Sample everything for `duration_s` — called on breaker trips and
    mesh re-meshes, the moments a dashboard reader will want per-message
    attribution for."""
    global _boost_until
    with _boost_lock:
        _boost_until = max(_boost_until, time.monotonic() + duration_s)


def sampling_forced() -> bool:
    return _force_all or time.monotonic() < _boost_until


def mint(origin: str = "") -> TraceContext | None:
    """Head-based sampling decision + context creation. Returns None
    when this message is not sampled — callers then attach nothing and
    pay nothing downstream."""
    if not sampling_forced():
        rate = sample_rate()
        if rate <= 0:
            return None
        if rate > 1 and next(_counter) % rate:
            return None
    from tendermint_tpu.telemetry import metrics as _metrics

    _metrics.TRACE_SAMPLED.inc()
    return TraceContext(os.urandom(_ID_BYTES), os.urandom(_ID_BYTES), origin)


# -- thread-ambient propagation ----------------------------------------------

_tls = threading.local()


def current() -> TraceContext | None:
    """The context ambient on this thread (None = untraced work)."""
    return getattr(_tls, "ctx", None)


@contextmanager
def use(ctx: TraceContext | None):
    """Install `ctx` as this thread's ambient context for the scope
    (None explicitly clears, so a traced record can never leak its
    context onto the next untraced one)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev
