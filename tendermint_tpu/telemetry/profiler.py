"""Contention observatory: whole-node sampling wall-clock profiler.

Every perf PR since the mesh work carries the same caveat — host-side
scaling is GIL-flat — and the multi-process refactor (ROADMAP item 4)
cannot be staged until someone *measures* where host threads burn and
where they wait. This module is that measurement: a low-overhead,
always-on-capable sampler that answers, per subsystem, "on-CPU or
blocked — and blocked on what?"

How it works, once armed:

* a background thread walks ``sys._current_frames()`` at
  ``TENDERMINT_TPU_PROFILE_HZ`` (default 29 — prime-ish, so it can't
  beat against 10 ms schedulers; ``0``/unset keeps it off, and
  ``boost()`` lights a temporary window the same way trace sampling's
  boost does);
* each thread's stack is classified into the existing subsystem
  vocabulary (consensus, ingress lane, coalescer, dispatch worker,
  p2p recv/send, statesync, rpc, abci) — by thread-name prefix first,
  innermost ``tendermint_tpu`` frame as the fallback;
* each sample is split **on-CPU vs blocked** via per-thread CPU clocks
  (``clock_gettime`` on the kernel per-thread CPUCLOCK — see
  `_thread_cpuclock_id` for why not ``pthread_getcpuclockid``): a
  thread that
  advanced its CPU clock by ≥ half the wall interval was running,
  anything else was waiting — on a lock, on I/O, or on the GIL. This
  is the direct GIL-pressure signal: a *runnable* thread that can't
  get CPU shows up blocked with reason ``other``;
* blocked samples get a best-effort reason from the innermost frame
  (``threading.py`` wait/acquire → ``lock``; selector/socket frames →
  ``io``; everything else → ``other``);
* samples aggregate into bounded per-subsystem counters and a bounded
  collapsed-stack table (flamegraph format — ``collapsed()`` emits
  ``root;frame;frame;[state] count`` lines).

Arming the profiler also arms the lock-contention timers grown into
the PR 10 ranked locks (`utils/lockrank.py` ``set_timing``): acquire
waits and holds then flow into ``tendermint_lock_wait_seconds{lock}``
/ ``tendermint_lock_hold_seconds{lock}`` with per-site attribution.
``dump_telemetry?profile=1`` serves ``snapshot()`` + the lock view +
the unified queue waits; ``tools/contention_report.py`` turns them
into the per-subsystem on-CPU/blocked waterfall.

Overhead is bench-guarded: `tools/bench_hotpath.py` ``profiler_overhead``
holds the dedup replay within 3% at the default 29 Hz with lock timing
armed (floor in tools/bench_floors.json).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from tendermint_tpu.utils import lockrank
from tendermint_tpu.utils.lockrank import ranked_lock

HZ_ENV = "TENDERMINT_TPU_PROFILE_HZ"
DEFAULT_HZ = 29.0

# classification vocabulary — the fixed low-cardinality subsystem set
# (`tendermint_profile_samples_total{subsystem=}`)
SUBSYSTEMS = (
    "consensus",
    "ingress",
    "coalescer",
    "dispatch",
    "p2p_recv",
    "p2p_send",
    "statesync",
    "rpc",
    "abci",
    "main",
    "other",
)

# thread-name prefix -> subsystem, most specific first (names come from
# the package's own `threading.Thread(name=...)` sites)
_NAME_MAP: tuple[tuple[str, str], ...] = (
    ("consensus", "consensus"),  # consensus-recv / -timeout / -heartbeat
    ("gossip-", "consensus"),  # consensus reactor per-peer gossip
    ("mempool-ingress", "ingress"),
    ("mempool-bcast", "p2p_send"),
    ("verify-coalescer", "coalescer"),
    ("dispatch-", "dispatch"),
    ("warm-build", "dispatch"),
    ("mconn-recv", "p2p_recv"),
    ("mconn-", "p2p_send"),  # send + ping loops
    ("p2p-", "p2p_recv"),  # accept / handshake (inbound edge)
    ("pex-", "p2p_send"),
    ("persistent-dial", "p2p_send"),
    ("evidence-gossip", "p2p_send"),
    ("statesync", "statesync"),
    ("fastsync", "statesync"),
    ("rpc-", "rpc"),
    ("abci-", "abci"),
    ("MainThread", "main"),
)

# module-path fragment -> subsystem, scanned innermost-out when the
# thread name doesn't classify (HTTP handler threads, bare Thread-N)
_MODULE_MAP: tuple[tuple[str, str], ...] = (
    ("/mempool/ingress", "ingress"),
    ("/mempool/", "ingress"),
    ("/services/batcher", "coalescer"),
    ("/services/dispatch", "dispatch"),
    ("/services/verifier", "dispatch"),
    ("/services/hasher", "dispatch"),
    ("/ops/", "dispatch"),
    ("/parallel/", "dispatch"),
    ("/consensus/", "consensus"),
    ("/statesync/", "statesync"),
    ("/blockchain/", "statesync"),
    ("/rpc/", "rpc"),
    ("/abci/", "abci"),
    ("/p2p/", "p2p_recv"),
)

_STACK_DEPTH = 24
_ON_CPU_FRACTION = 0.5  # CPU-clock advance / wall interval threshold


def classify_thread(name: str, frame=None) -> str:
    """Subsystem for one thread: name prefix first, innermost
    `tendermint_tpu` frame as the fallback, `other` when neither
    answers."""
    for prefix, sub in _NAME_MAP:
        if name.startswith(prefix):
            return sub
    f = frame
    while f is not None:
        fn = f.f_code.co_filename
        if "tendermint_tpu" in fn:
            for frag, sub in _MODULE_MAP:
                if frag in fn:
                    return sub
        f = f.f_back
    return "other"


def blocked_reason(frame) -> str:
    """Best-effort wait reason from the innermost frames: `lock` for
    threading-module waits (Condition/Event/queue all funnel through
    them), `io` for selector/socket-shaped frames, `other` for
    everything else — including runnable-but-GIL-starved, which no
    stack can show."""
    f = frame
    depth = 0
    while f is not None and depth < 4:
        fn = f.f_code.co_filename.rsplit("/", 1)[-1]
        name = f.f_code.co_name
        if fn == "threading.py" and name in (
            "wait",
            "acquire",
            "wait_for",
            "_wait_for_tstate_lock",
        ):
            return "lock"
        # an instrumented ranked-lock acquire is a lock wait by
        # definition (plain Lock.acquire is a builtin and invisible)
        if fn == "lockrank.py" and name in (
            "acquire",
            "__enter__",
            "_acquire_restore",
        ):
            return "lock"
        if fn == "selectors.py" or name in ("select", "poll", "accept"):
            return "io"
        if name in ("recv", "_recv_exact", "recv_into", "readinto", "read"):
            return "io"
        if name == "sleep" or name.endswith("_sleep"):
            return "sleep"
        f = f.f_back
        depth += 1
    return "other"


def _frame_stack(frame, depth: int = _STACK_DEPTH) -> tuple[str, ...]:
    """`file.py:func` frames, OUTERMOST first (flamegraph root order),
    innermost `depth` frames kept."""
    out: list[str] = []
    f = frame
    while f is not None and len(out) < depth:
        code = f.f_code
        out.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
        f = f.f_back
    out.reverse()
    return tuple(out)


def collapse(subsystem: str, stack: tuple[str, ...], state: str) -> str:
    """One collapsed-stack key: subsystem as the root frame, the wait
    state as a leaf pseudo-frame — `flamegraph.pl` renders it as-is."""
    return ";".join((subsystem,) + stack + (f"[{state}]",))


def _thread_cpuclock_id(native_id: int) -> int:
    """Linux MAKE_THREAD_CPUCLOCK(tid, CPUCLOCK_SCHED): the clockid
    `clock_gettime` resolves THROUGH THE KERNEL, which validates the
    tid (a dead thread returns EINVAL). Deliberately NOT
    `pthread_getcpuclockid` — that dereferences the pthread struct,
    which is freed the moment a detached CPython thread exits, and a
    sampled thread can exit between the frame snapshot and this call."""
    return ((~native_id) << 3) | 6


def _cpu_clock(thread) -> float | None:
    """`thread`'s CPU clock (seconds), or None when unreadable (no
    native_id — foreign/exited thread — or a non-Linux platform)."""
    tid = getattr(thread, "native_id", None)
    if tid is None:
        return None
    try:
        return time.clock_gettime(_thread_cpuclock_id(tid))
    except (OSError, OverflowError, ValueError):
        return None


def _probe_cpu_clocks() -> bool:
    """Can this platform read another thread's CPU clock the safe way?
    Probed once on our own thread at import."""
    try:
        time.clock_gettime(_thread_cpuclock_id(threading.get_native_id()))
        return True
    except (AttributeError, OSError, OverflowError, ValueError):
        return False


_CPU_CLOCKS = _probe_cpu_clocks()


class ContentionProfiler:
    """The process-global sampler (`PROFILER` below, mirroring the
    FLIGHT/TRACER singletons). Bounded: per-(subsystem,state) counters,
    a capped collapsed-stack table (overflow lands in one `(truncated)`
    bucket), and a capped per-thread table — a nemesis run can churn
    thousands of short-lived threads without growing this."""

    MAX_STACKS = 4096
    MAX_THREADS = 256

    def __init__(self, hz: float | None = None) -> None:
        self._lock = ranked_lock("telemetry.profiler")
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._started = False
        self._boost_until = 0.0
        self._hz = hz
        # ident -> (wall_t, cpu_t) baseline for the on-CPU split
        self._prev: dict[int, tuple[float, float]] = {}
        self._counts: dict[tuple[str, str, str], int] = {}
        self._stacks: dict[str, int] = {}
        self._threads: dict[str, dict] = {}
        self._samples = 0
        self._ticks = 0
        self._truncated = 0

    # -- arming --------------------------------------------------------------

    def _env_hz(self) -> float:
        try:
            return float(os.environ.get(HZ_ENV, "0") or "0")
        except ValueError:
            return 0.0

    def hz(self) -> float:
        if self._hz is not None and self._hz > 0:
            return self._hz
        env = self._env_hz()
        return env if env > 0 else DEFAULT_HZ

    def _armed(self) -> bool:
        return self._started or time.monotonic() < self._boost_until

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and self._armed()

    def start(self, hz: float | None = None) -> None:
        """Arm continuously (until `stop()`); also arms the ranked-lock
        contention timers. Idempotent."""
        with self._lock:
            if hz is not None:
                self._hz = hz
            self._started = True
            self._ensure_thread_locked()
        lockrank.set_timing(True)

    def boost(self, duration_s: float = 30.0, hz: float | None = None) -> None:
        """Sample for `duration_s` then auto-disarm — the profiler twin
        of trace sampling's boost window."""
        with self._lock:
            if hz is not None:
                self._hz = hz
            self._boost_until = max(
                self._boost_until, time.monotonic() + duration_s
            )
            self._ensure_thread_locked()
        lockrank.set_timing(True)

    def stop(self) -> None:
        with self._lock:
            self._started = False
            self._boost_until = 0.0
        lockrank.set_timing(False)
        self._wake.set()

    def _ensure_thread_locked(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._wake.clear()
        self._thread = threading.Thread(
            target=self._run, name="tpu-profiler", daemon=True
        )
        self._thread.start()

    # -- sampling ------------------------------------------------------------

    def _run(self) -> None:
        while True:
            if not self._armed():
                # boost expired (or stop() raced us): disarm the lock
                # timers too, unless a restart re-armed meanwhile
                with self._lock:
                    if not self._armed():
                        self._thread = None
                        lockrank.set_timing(False)
                        return
            t0 = time.perf_counter()
            try:
                self._sample_once(t0)
            except Exception:
                # the profiler must never take the node down; a torn
                # frame walk on a dying thread just skips one tick
                pass
            elapsed = time.perf_counter() - t0
            from tendermint_tpu.telemetry import metrics as _m

            _m.PROFILE_TICK_SECONDS.observe(elapsed)
            self._wake.wait(max(0.001, 1.0 / self.hz() - elapsed))

    def _sample_once(self, now: float) -> None:
        from tendermint_tpu.telemetry import metrics as _m

        frames = sys._current_frames()
        threads = {t.ident: t for t in threading.enumerate()}
        me = threading.get_ident()
        merged: list[tuple[str, str, str, str, tuple[str, ...]]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            t = threads.get(ident)
            name = t.name if t is not None else f"tid-{ident}"
            sub = classify_thread(name, frame)
            reason = blocked_reason(frame)
            cpu = _cpu_clock(t) if _CPU_CLOCKS else None
            prev = self._prev.get(ident)
            if cpu is not None:
                self._prev[ident] = (now, cpu)
            if cpu is not None and prev is not None:
                dt, dcpu = now - prev[0], cpu - prev[1]
                on_cpu = dt > 0 and (dcpu / dt) >= _ON_CPU_FRACTION
            elif cpu is not None:
                continue  # first sight: no baseline yet, skip one tick
            else:
                # no per-thread CPU clocks on this platform: fall back
                # to the stack heuristic alone
                on_cpu = reason == "other"
            state = "on_cpu" if on_cpu else "blocked"
            wait = "" if on_cpu else reason
            merged.append((name, sub, state, wait, _frame_stack(frame)))
        # prune baselines of exited threads so the table stays bounded
        if len(self._prev) > 4 * max(1, len(frames)):
            live = set(frames)
            self._prev = {
                k: v for k, v in self._prev.items() if k in live
            }
        with self._lock:
            self._ticks += 1
            for name, sub, state, wait, stack in merged:
                self._samples += 1
                key = (sub, state, wait)
                self._counts[key] = self._counts.get(key, 0) + 1
                line = collapse(
                    sub,
                    stack,
                    state if state == "on_cpu" else f"blocked:{wait}",
                )
                if line in self._stacks or len(self._stacks) < self.MAX_STACKS:
                    self._stacks[line] = self._stacks.get(line, 0) + 1
                else:
                    self._truncated += 1
                th = self._threads.get(name)
                if th is None:
                    if len(self._threads) >= self.MAX_THREADS:
                        continue
                    th = self._threads[name] = {
                        "subsystem": sub,
                        "samples": 0,
                        "on_cpu": 0,
                    }
                th["samples"] += 1
                if state == "on_cpu":
                    th["on_cpu"] += 1
        for name, sub, state, wait, _stack in merged:
            _m.PROFILE_SAMPLES.labels(
                subsystem=sub, state=state, wait=wait or "none"
            ).inc()

    # -- reads ---------------------------------------------------------------

    def snapshot(self, top_stacks: int = 20) -> dict:
        """Aggregate view: per-subsystem on-CPU/blocked splits with
        wait reasons, a bounded per-thread table (thread-name
        cardinality ⇒ dump-only, docs/OBSERVABILITY.md), and the
        hottest collapsed stacks."""
        with self._lock:
            subsystems: dict[str, dict] = {}
            for (sub, state, wait), n in self._counts.items():
                row = subsystems.setdefault(
                    sub, {"on_cpu": 0, "blocked": 0, "blocked_by": {}}
                )
                if state == "on_cpu":
                    row["on_cpu"] += n
                else:
                    row["blocked"] += n
                    row["blocked_by"][wait] = (
                        row["blocked_by"].get(wait, 0) + n
                    )
            stacks = sorted(
                self._stacks.items(), key=lambda kv: kv[1], reverse=True
            )[: max(0, top_stacks)]
            return {
                "armed": self._armed(),
                "hz": self.hz(),
                "cpu_clock": _CPU_CLOCKS,
                "ticks": self._ticks,
                "samples": self._samples,
                "truncated_stacks": self._truncated,
                "subsystems": subsystems,
                "threads": dict(self._threads),
                "top_stacks": [
                    {"stack": line, "count": n} for line, n in stacks
                ],
            }

    def collapsed(self) -> list[str]:
        """Flamegraph lines, `stack count` — pipe into flamegraph.pl or
        speedscope. Deterministic order (count desc, then lexical)."""
        with self._lock:
            items = list(self._stacks.items())
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        return [f"{line} {n}" for line, n in items]

    def reset(self) -> None:
        with self._lock:
            self._prev.clear()
            self._counts.clear()
            self._stacks.clear()
            self._threads.clear()
            self._samples = 0
            self._ticks = 0
            self._truncated = 0


PROFILER = ContentionProfiler()


def maybe_start_env() -> bool:
    """Start the global profiler when `TENDERMINT_TPU_PROFILE_HZ` > 0
    (node start calls this); returns whether it is running."""
    try:
        hz = float(os.environ.get(HZ_ENV, "0") or "0")
    except ValueError:
        return PROFILER.running()
    if hz > 0:
        PROFILER.start(hz=hz)
    return PROFILER.running()
