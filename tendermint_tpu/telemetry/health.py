"""Health / readiness snapshot + rolling finality SLO.

The machine-readable signal ROADMAP item 1's read-replica fleet sits
behind: one structured dict (served as `GET /health` on the RPC
listener and as the `health` JSON-RPC method) that folds the telemetry
the node already keeps into three states:

* **ok** — serving, all checks green;
* **degraded** — serving, but something an operator should look at is
  wrong: a circuit breaker is off `closed` (device crypto degraded to
  host), the verify mesh is running on survivors, the peer count is
  below the floor, or commits have stalled past the lag ceiling;
* **not_ready** — do not route traffic here: the node is still
  fast-syncing / state-syncing, or its consensus loop halted on a
  fatal error. `GET /health` maps this to HTTP 503 so any off-the-shelf
  load balancer can act on it without parsing the body.

Everything is derived from NODE-LOCAL objects (the node's own breaker
snapshots, its switch's peer count, its HeightLedger) — never from the
process-global registry, so the multi-node-in-process harnesses get
per-node answers.

The **finality SLO** section evaluates the rolling window of
commit-to-commit gaps from the height ledger against a p99 target and
reports error-budget burn (breaches / allowed breaches). It is
deliberately *reported, not folded into the status*: an SLO breach is
an alerting decision, and a load balancer yanking a replica because the
whole chain was slow would make the incident worse, not better.

An exhausted error budget additionally **arms the trace-sampling
boost window** (`telemetry/tracectx.boost()`) — the same reflex a
breaker trip or mesh re-mesh has: the moment the chain is visibly slow
is exactly when an operator wants per-message attribution, so the next
`TENDERMINT_TPU_SLO_BOOST_S` seconds sample every trace context. The
status itself still doesn't change (see above).

The **device** section (device observatory, telemetry/launchlog.py) is
reported under the same discipline: mesh width active/total, a
compile-in-progress flag, and seconds since the last successful device
launch — operator signals, never folded into the routing status.

The **pipeline** section (cross-height pipelined consensus,
consensus/state.py) follows suit: whether height H's apply is in
flight under H+1's voting right now, join-barrier stall counts, and
the apply overlap won — reported, never folded.

Knobs (env):
  TENDERMINT_TPU_FINALITY_SLO_P99_S  p99 finality target, seconds (1.0)
  TENDERMINT_TPU_SLO_WINDOW          heights in the rolling window (64)
  TENDERMINT_TPU_SLO_BUDGET          allowed breach fraction (0.01)
  TENDERMINT_TPU_SLO_BOOST_S         trace-boost window on budget
                                     exhaustion, seconds (30; 0 off)
  TENDERMINT_TPU_HEALTH_MIN_PEERS    peer floor before degraded (1)
  TENDERMINT_TPU_HEALTH_MAX_LAG_S    commit-age ceiling, seconds (60)
  TENDERMINT_TPU_HEALTH_MAX_TIP_LAG  heights a follow-mode replica may
                                     trail the peer tip and stay ready (8)

The **serving** section (light-client layer, lightclient/reactor.py)
appears on nodes running the 0x68 serving reactor: FullCommit-cache
warmth, proof-serving lag behind the chain tip, and subscription
liveness — reported, never folded, with one deliberate exception: a
follow-mode REPLICA's readiness comes from the tip-lag rule above (a
replica serving stale heights must not take read traffic, and that IS
a routing decision).
"""

from __future__ import annotations

import math
import os
import time


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _pctl(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile over raw samples (empirical, not bucket
    interpolation — the window is small and exact)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def _breaker_check(node) -> dict:
    """Every breaker snapshot reachable from this node's verify/hash
    services (the same objects `dump_telemetry` serves): ok iff all
    report state == closed."""
    states: dict[str, str] = {}
    for name, svc in (
        ("verifier", getattr(getattr(node, "consensus", None), "verifier", None)),
        ("hasher", getattr(node, "hasher", None)),
    ):
        if svc is None or not hasattr(svc, "snapshot"):
            continue
        try:
            snap = svc.snapshot()
        except Exception:
            continue
        state = snap.get("state")
        if state is not None:
            states[name] = str(state)
    return {"ok": all(s == "closed" for s in states.values()), "states": states}


def _mesh_check(node) -> dict:
    """Sharded-mesh degradation from the verifier snapshot: active <
    total means the mesh is running on survivors (re-mesh absorbed a
    chip loss below the breaker). Nodes without a mesh are trivially
    ok."""
    svc = getattr(getattr(node, "consensus", None), "verifier", None)
    snap = {}
    if svc is not None and hasattr(svc, "snapshot"):
        try:
            snap = svc.snapshot() or {}
        except Exception:
            snap = {}
    mesh = snap.get("mesh")
    if not isinstance(mesh, dict):
        return {"ok": True, "present": False}
    active = int(mesh.get("devices_active", 0))
    total = int(mesh.get("devices_total", 0))
    return {
        "ok": active >= total,
        "present": True,
        "devices_active": active,
        "devices_total": total,
    }


def _device_section(node) -> dict:
    """The device observatory's health view: mesh width (active/total
    from the verifier snapshot — the same node-local object the mesh
    check reads), whether a compiled-step build is in flight right now,
    and the age of the last successful device launch. REPORTED, never
    folded into the status (same discipline as the finality SLO): a
    compile stall or a quiet device is an operator signal, not a
    load-balancer eviction. The compile flag and launch age read the
    process-wide mesh/step-cache and LaunchLedger — the device stack is
    a process singleton, so in multi-node-in-process harnesses they are
    shared across nodes (documented approximation)."""
    svc = getattr(getattr(node, "consensus", None), "verifier", None)
    snap = {}
    if svc is not None and hasattr(svc, "snapshot"):
        try:
            snap = svc.snapshot() or {}
        except Exception:
            snap = {}
    mesh = snap.get("mesh") if isinstance(snap.get("mesh"), dict) else {}
    out: dict = {
        "mesh_active": int(mesh.get("devices_active", 0)) if mesh else None,
        "mesh_total": int(mesh.get("devices_total", 0)) if mesh else None,
    }
    try:
        # consult the mesh module only if something already loaded it:
        # importing it here would drag the jax kernel modules into a
        # host-only node's health probe (seconds of import on first
        # touch) — and an unloaded mesh module means no compiles exist
        import sys as _sys

        _mesh = _sys.modules.get("tendermint_tpu.parallel.mesh")
        out["compile_in_progress"] = (
            _mesh.compiles_in_progress() > 0 if _mesh is not None else False
        )
    except Exception:
        out["compile_in_progress"] = False
    try:
        from tendermint_tpu.telemetry.launchlog import LAUNCHLOG

        age = LAUNCHLOG.seconds_since_success()
        out["last_launch_age_s"] = round(age, 3) if age is not None else None
    except Exception:
        out["last_launch_age_s"] = None
    return out


def _pipeline_section(consensus) -> dict:
    """Cross-height pipeline state (consensus/state.py pipelined
    finalize), REPORTED under the same never-folded discipline as the
    SLO and device sections: whether an apply is in flight right now,
    how often the join barrier actually stalled H+1 on H's apply, and
    the overlap won. A stall-heavy pipeline is a tuning signal (the
    apply dominates the height), not a routing decision."""
    out: dict = {
        "enabled": bool(getattr(consensus, "pipeline_enabled", False)),
        "apply_in_flight": getattr(consensus, "_pending_apply", None) is not None,
    }
    stats = getattr(consensus, "pipeline_stats", None)
    if isinstance(stats, dict):
        joins = stats.get("joins", 0)
        out.update(
            {
                "joins": joins,
                "stalls": stats.get("stalls", 0),
                "valset_rebuilds": stats.get("valset_rebuilds", 0),
                "last_overlap_ms": round(stats.get("last_overlap_s", 0.0) * 1e3, 3),
                "overlap_ms_mean": round(
                    stats.get("overlap_s_total", 0.0) / joins * 1e3, 3
                )
                if joins
                else None,
            }
        )
    return out


def _serving_section(node) -> dict | None:
    """Light-client serving view (lightclient/reactor.py): cache
    warmth, proof-serving lag, subscription liveness. REPORTED under
    the same never-folded discipline as the SLO/device/pipeline
    sections — replica readiness is the sync check's tip-lag rule, not
    this. None on nodes without the serving layer (harness stubs)."""
    reactor = getattr(node, "lightclient_reactor", None)
    if reactor is None or not hasattr(reactor, "serving_stats"):
        return None
    try:
        out = reactor.serving_stats()
    except Exception:
        return None
    out["replica"] = bool(getattr(node, "is_replica", False))
    return out


def _gossip_section(node) -> dict | None:
    """Gossip observatory headline (telemetry/gossiplog.py): the top
    redundant message kind and the hottest channel by bytes. REPORTED,
    never folded — over-gossip wastes bandwidth, it doesn't make a node
    unready (scenario expectations and the bench floor are where
    redundancy bounds get enforced). None without a switch (harness
    stubs) or with the rollup sampled out."""
    gossip = getattr(getattr(node, "switch", None), "gossip", None)
    if gossip is None:
        return None
    try:
        out = gossip.headline()
    except Exception:
        return None
    return out if out.get("enabled") else None


def build_health(node, ledger=None) -> dict:
    """The health snapshot for one composed node (`node.Node` or
    anything duck-typed close enough — every read is getattr-tolerant,
    so harness stubs work)."""
    target = _env_float("TENDERMINT_TPU_FINALITY_SLO_P99_S", 1.0)
    window_n = int(_env_float("TENDERMINT_TPU_SLO_WINDOW", 64))
    budget_frac = _env_float("TENDERMINT_TPU_SLO_BUDGET", 0.01)
    min_peers = int(_env_float("TENDERMINT_TPU_HEALTH_MIN_PEERS", 1))
    max_lag = _env_float("TENDERMINT_TPU_HEALTH_MAX_LAG_S", 60.0)

    consensus = getattr(node, "consensus", None)
    if ledger is None:
        ledger = getattr(node, "height_ledger", None)
    if ledger is None:
        ledger = getattr(consensus, "height_ledger", None)

    # -- readiness ---------------------------------------------------------
    bc = getattr(node, "blockchain_reactor", None)
    follow = bool(getattr(bc, "follow", False))
    catching_up = bool(getattr(bc, "fast_sync", False)) and not follow
    ss = getattr(node, "statesync_reactor", None)
    state_syncing = bool(getattr(ss, "sync", False)) and (
        getattr(ss, "restored_state", None) is None
    )
    fatal = getattr(consensus, "fatal_error", None)
    checks: dict[str, dict] = {
        "consensus": {
            "ok": fatal is None,
            "fatal": type(fatal).__name__ if fatal is not None else None,
        },
        "sync": {
            "ok": not (catching_up or state_syncing),
            "fast_sync": catching_up,
            "state_sync": state_syncing,
        },
    }
    if follow:
        # follow-mode replicas stay in fast-sync FOREVER, so readiness
        # is distance from the best-known peer tip, not the flag: a
        # replica serving heights far behind the chain must not take
        # read traffic (TENDERMINT_TPU_HEALTH_MAX_TIP_LAG heights).
        max_tip_lag = int(_env_float("TENDERMINT_TPU_HEALTH_MAX_TIP_LAG", 8))
        try:
            tip_lag = int(bc.tip_lag())
        except Exception:
            tip_lag = 0
        checks["sync"] = {
            "ok": not state_syncing and tip_lag <= max_tip_lag,
            "fast_sync": False,
            "state_sync": state_syncing,
            "follow": True,
            "tip_lag": tip_lag,
            "max_tip_lag": max_tip_lag,
        }

    # -- degradation -------------------------------------------------------
    checks["breakers"] = _breaker_check(node)
    checks["mesh"] = _mesh_check(node)
    switch = getattr(node, "switch", None)
    n_peers = switch.n_peers() if switch is not None else 0
    checks["peers"] = {"ok": n_peers >= min_peers, "count": n_peers, "min": min_peers}

    last = ledger.last() if ledger is not None else None
    lag_s = None
    if last is not None and isinstance(last.get("t_commit"), (int, float)):
        lag_s = max(0.0, time.time() - last["t_commit"])
    checks["commit_lag"] = {
        # no records yet = not enough data to call it stalled (a node
        # that is genuinely behind shows up in the sync check instead)
        "ok": lag_s is None or catching_up or lag_s <= max_lag,
        "lag_s": round(lag_s, 3) if lag_s is not None else None,
        "max_s": max_lag,
    }

    # -- finality SLO (reported, never folded into status) -----------------
    gaps = sorted(ledger.finality_window(window_n)) if ledger is not None else []
    breaches = sum(1 for g in gaps if g > target)
    budget = max(1.0, budget_frac * len(gaps)) if gaps else 1.0
    burn = breaches / budget
    slo = {
        "target_p99_s": target,
        "window": len(gaps),
        "p50_s": round(_pctl(gaps, 0.5), 6) if gaps else None,
        "p99_s": round(_pctl(gaps, 0.99), 6) if gaps else None,
        "breaches": breaches,
        "error_budget": round(budget, 3),
        "budget_burn": round(burn, 3),
        "ok": burn <= 1.0,
    }
    # budget exhausted -> light up tracing, the same reflex breaker
    # trips and mesh re-meshes have (tracectx.boost): the slow window
    # is when per-message attribution pays for itself. Reported, so an
    # operator reading the snapshot knows sampling is boosted.
    if gaps and not slo["ok"]:
        boost_s = _env_float("TENDERMINT_TPU_SLO_BOOST_S", 30.0)
        if boost_s > 0:
            from tendermint_tpu.telemetry import tracectx

            tracectx.boost(boost_s)
            slo["trace_boosted"] = True

    not_ready = not (checks["consensus"]["ok"] and checks["sync"]["ok"])
    degraded = not all(
        checks[k]["ok"] for k in ("breakers", "mesh", "peers", "commit_lag")
    )
    status = "not_ready" if not_ready else ("degraded" if degraded else "ok")
    store = getattr(node, "block_store", None)
    out = {
        "status": status,
        "ready": not not_ready,
        "node_id": getattr(node, "node_id", ""),
        "height": getattr(store, "height", 0) if store is not None else 0,
        "catching_up": catching_up or state_syncing,
        "checks": checks,
        "finality_slo": slo,
        # device observatory (reported, not folded into status — the
        # mesh *degradation* check above is what can mark degraded)
        "device": _device_section(node),
        # cross-height pipeline (reported, never folded: a stalling
        # pipeline is slower finality, which the SLO section owns)
        "pipeline": _pipeline_section(consensus),
    }
    # light-client serving layer (reported, never folded — with ONE
    # exception: the follow-mode tip-lag check above, which IS the
    # replica's readiness): FullCommit-cache warmth, proof-serving lag
    # behind the chain tip, subscription liveness.
    serving = _serving_section(node)
    if serving is not None:
        out["serving"] = serving
    # gossip observatory headline (reported, never folded): top
    # redundant kind + hottest channel — the full tables are dump-only
    # (`dump_telemetry?gossip=1`).
    gossip = _gossip_section(node)
    if gossip is not None:
        out["gossip"] = gossip
    return out
