"""Telemetry: metrics registry + span tracer + the exported catalog.

The observability layer the perf PRs are judged against: counters,
gauges, and fixed-bucket histograms (`telemetry/registry.py`) exposed
as Prometheus text on `GET /metrics` and as JSON via the
`dump_telemetry` RPC; a bounded span tracer (`telemetry/tracer.py`)
records consensus round-phase and device-dispatch timelines. The
catalog of every exported series lives in `telemetry/metrics.py`;
docs/OBSERVABILITY.md is the operator-facing index.

Everything is import-cheap and dependency-free: no client libraries,
no numpy/jax at import time, safe to import from any layer.
"""

from tendermint_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    REGISTRY,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from tendermint_tpu.telemetry.tracer import TRACER, Span, Tracer

__all__ = [
    "Counter",
    "FLIGHT",
    "FlightRecorder",
    "Gauge",
    "HeightLedger",
    "Histogram",
    "Registry",
    "REGISTRY",
    "Span",
    "SpanLog",
    "TraceContext",
    "Tracer",
    "TRACER",
    "LATENCY_BUCKETS",
    "SIZE_BUCKETS",
    "persist_spans",
]


def __getattr__(name: str):
    # spanlog/flightrec/tracectx lazily: they touch the filesystem or
    # os.urandom, and most importers only want the registry/tracer
    if name in ("SpanLog", "persist_spans"):
        from tendermint_tpu.telemetry import spanlog

        return getattr(spanlog, name)
    if name in ("FLIGHT", "FlightRecorder"):
        from tendermint_tpu.telemetry import flightrec

        return getattr(flightrec, name)
    if name == "TraceContext":
        from tendermint_tpu.telemetry import tracectx

        return tracectx.TraceContext
    if name == "HeightLedger":
        from tendermint_tpu.telemetry import heightlog

        return heightlog.HeightLedger
    raise AttributeError(name)
