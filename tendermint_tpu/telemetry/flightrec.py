"""Consensus flight recorder: a bounded black box for post-mortems.

Metrics answer "how much / how fast"; spans answer "how long"; neither
answers "what exactly happened, in order, right before it went wrong".
The flight recorder is a bounded in-memory ring of structured events —
round transitions, vote-batch drains, coalescer flushes, dispatch
launches, breaker and mesh state changes — cheap enough to record
unconditionally, that **atomically dumps to the data dir** when
something breaks: a consensus invariant/persistence failure halts the
loop, a nemesis invariant trips, or an operator sends `SIGUSR2`.

Like the registry and tracer it is process-global (one node per
production process); the multi-node-in-process harnesses see all nodes'
events interleaved, which is exactly what their forensics want —
events carry height/round, and `tools/trace_timeline.py` merges dumps
with span logs into one per-height timeline.

Dumps are tmp-file + `os.replace` atomic: a crash mid-dump leaves
either the previous dump or the new one, never a torn file.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """Bounded, thread-safe event ring with atomic JSON dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._events: "deque[dict]" = deque(maxlen=max(1, capacity))
        self._dump_dir: str | None = None
        self._node_id = ""
        self._dump_seq = 0

    # -- wiring (node boot / harness) --------------------------------------

    def set_dump_dir(self, path: str) -> None:
        self._dump_dir = path

    def set_node_id(self, node_id: str) -> None:
        self._node_id = node_id

    # -- recording ----------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one structured event; must never fail the caller."""
        try:
            evt = {"t": time.time(), "kind": kind}
            evt.update(fields)
            with self._lock:
                self._events.append(evt)
        except Exception:
            pass

    def recent(
        self, n: int | None = None, kind: str = "", height: int | None = None
    ) -> list[dict]:
        with self._lock:
            events = list(self._events)
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        if height is not None:
            events = [e for e in events if e.get("height") == height]
        if n is not None:
            events = events[-n:]
        return events

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- dumping -------------------------------------------------------------

    def dump(self, reason: str = "manual", dir: str | None = None) -> str | None:
        """Atomically write the ring as JSON under `dir` (or the wired
        dump dir); returns the path, or None when nowhere to write.
        Never raises — a broken disk must not mask the original fault."""
        target = dir or self._dump_dir
        if not target:
            return None
        try:
            os.makedirs(target, exist_ok=True)
            # the last K height-ledger records ride every dump: the
            # post-mortem's "which heights led into this, and where did
            # their time go" (telemetry/heightlog.py; lazy import — the
            # ledger imports the metric catalog, not this module)
            try:
                from tendermint_tpu.telemetry import heightlog

                heights = heightlog.recent_records(32)
            except Exception:
                heights = []
            # the last K device-launch records ride along too: "which
            # launches led into this, and where did their time go"
            # (telemetry/launchlog.py, the device observatory)
            try:
                from tendermint_tpu.telemetry import launchlog

                launches = launchlog.LAUNCHLOG.recent(32)
            except Exception:
                launches = []
            with self._lock:
                events = list(self._events)
                self._dump_seq += 1
                seq = self._dump_seq
            safe_reason = "".join(
                c if c.isalnum() or c in "-_" else "-" for c in reason
            )[:48]
            path = os.path.join(target, f"flightrec-{safe_reason}-{seq}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "node": self._node_id,
                        "reason": reason,
                        "dumped_at": time.time(),
                        "events": events,
                        "heights": heights,
                        "launches": launches,
                    },
                    f,
                    separators=(",", ":"),
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None

    @staticmethod
    def load(path: str) -> dict:
        """Parse a dump file (the `trace_timeline` ingestion seam)."""
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)


# Process-wide recorder, mirroring REGISTRY/TRACER conventions.
FLIGHT = FlightRecorder()

_signal_installed = False


def install_signal_dump() -> bool:
    """Arm `SIGUSR2` -> `FLIGHT.dump("sigusr2")` — the operator's
    "snapshot the black box of a live node" switch. Safe to call more
    than once; returns False where signals can't be installed (non-main
    thread, platforms without SIGUSR2)."""
    global _signal_installed
    if _signal_installed:
        return True
    import signal

    if not hasattr(signal, "SIGUSR2"):
        return False
    try:
        signal.signal(signal.SIGUSR2, lambda *_args: FLIGHT.dump("sigusr2"))
    except ValueError:  # not the main thread
        return False
    _signal_installed = True
    return True
