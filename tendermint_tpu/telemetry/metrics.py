"""The metric catalog: every exported series, registered at import.

One module owns all names so the exposition stays consistent and
greppable (docs/OBSERVABILITY.md is generated from this list by hand —
keep them in sync). Naming follows the reference's Prometheus
conventions (`tendermint_consensus_height`, ...); label values are
low-cardinality by construction: `backend` ∈ {host, device, tables,
mesh}, `kind` ∈ {verify, hash, tables}, `phase` ∈ round phases, never
peer ids or heights.

Process-global like the registry: a production process runs ONE node,
so node-scoped gauges (mempool depth, p2p rates) are process gauges.
Multi-node-in-process harnesses (testing/nemesis.py) see sums across
nodes for counters — exactly what their invariants want — and
last-writer-wins for gauges, which they avoid asserting on.
"""

from __future__ import annotations

from tendermint_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
)

# -- consensus ----------------------------------------------------------------

CONSENSUS_HEIGHT = Gauge(
    "tendermint_consensus_height", "Current consensus height"
)
CONSENSUS_ROUND = Gauge(
    "tendermint_consensus_round", "Current consensus round"
)
CONSENSUS_PHASE_SECONDS = Histogram(
    "tendermint_consensus_phase_seconds",
    "Wall time spent in each round phase (propose/prevote/precommit/commit)",
    labelnames=("phase",),
    buckets=LATENCY_BUCKETS,
)
CONSENSUS_HEIGHT_SECONDS = Histogram(
    "tendermint_consensus_height_seconds",
    "Wall time from entering a height to finalizing its commit",
    buckets=LATENCY_BUCKETS,
)
CONSENSUS_COMMITS = Counter(
    "tendermint_consensus_commits_total", "Blocks finalized by this node"
)
CONSENSUS_TXS_COMMITTED = Counter(
    "tendermint_consensus_txs_committed_total", "Txs in blocks finalized by this node"
)
CONSENSUS_ROUND_SKIPS = Counter(
    "tendermint_consensus_round_skips_total",
    "Round-skip timeouts fired while starved at PREVOTE/PRECOMMIT",
    labelnames=("phase",),
)
VOTE_DRAIN_BATCH = Histogram(
    "tendermint_consensus_vote_drain_batch_size",
    "Consecutive same-(height,round,type) votes drained per receive-loop turn",
    buckets=SIZE_BUCKETS,
)

# -- finality observatory (telemetry/heightlog.py, consensus/state.py) --------
#
# `phase` is the fixed height lifecycle: new_height (commit timeout +
# waiting for round 0), propose, prevote, precommit, commit (waiting
# for the committed block, pre-apply), apply (ABCI + state update) —
# summed across rounds of one height, so the per-height phase set
# always sums to ~the commit-to-commit gap.

FINALITY_SECONDS = Histogram(
    "tendermint_finality_seconds",
    "Commit-to-commit gap: wall time between consecutive finalized "
    "commits on this node (the user-facing finality latency)",
    buckets=LATENCY_BUCKETS,
)
HEIGHT_PHASE_SECONDS = Histogram(
    "tendermint_height_phase_seconds",
    "Per-height time in each lifecycle phase (summed across rounds), "
    "from the HeightLedger record assembled at finalize",
    labelnames=("phase",),
    buckets=LATENCY_BUCKETS,
)
VOTE_ARRIVAL_SECONDS = Histogram(
    "tendermint_consensus_vote_arrival_seconds",
    "Vote timestamp to local arrival, aggregated over all peers "
    "(per-peer rollup lives in dump_telemetry; clock-skew clamped)",
    buckets=LATENCY_BUCKETS,
)
VOTE_ARRIVAL_MAX = Gauge(
    "tendermint_consensus_vote_arrival_max_seconds",
    "Worst single vote-arrival delay observed in the last finalized "
    "height (the laggard-validator signal)",
)

# -- cross-height pipeline (consensus/state.py pipelined finalize) ------------
#
# `reason` is the fixed join-barrier vocabulary: propose (proposer
# needed the applied app_hash/mempool), prevote (validate_block against
# applied state), vote_tally (H+1 vote needed the post-EndBlock
# valset), shutdown (stop() drained the pipeline), fault (the apply
# itself failed — the pipeline drains and consensus halts). A stall is
# a join that actually blocked H+1 progress; instant joins and the
# receive loop's opportunistic idle-joins (nothing queued — blocking
# delays nothing) don't count.

APPLY_OVERLAP_SECONDS = Histogram(
    "tendermint_consensus_apply_overlap_seconds",
    "Share of height H's ABCI apply + state advance that ran "
    "concurrently with H+1's NewHeight/Propose (pipelined finalize; "
    "0 on the serial path)",
    buckets=LATENCY_BUCKETS,
)
PIPELINE_STALLS = Counter(
    "tendermint_consensus_pipeline_stalls_total",
    "Join-barrier waits that actually blocked H+1 progress on H's "
    "in-flight apply, by the barrier that stalled",
    labelnames=("reason",),
)
CONSENSUS_TIMEOUT_DERIVED = Gauge(
    "tendermint_consensus_timeout_derived_seconds",
    "Current measured-latency-derived timeout per phase (clamped to "
    "the configured fixed value; absent while cold-starting on the "
    "fixed ladder)",
    labelnames=("phase",),
)

# -- device dispatch (verify / hash hot paths) --------------------------------

VERIFY_BATCH_SIZE = Histogram(
    "tendermint_verify_batch_size",
    "ed25519 signatures per verify call, by executing backend",
    labelnames=("backend",),
    buckets=SIZE_BUCKETS,
)
VERIFY_SECONDS = Histogram(
    "tendermint_verify_seconds",
    "ed25519 verify call latency, by executing backend",
    labelnames=("backend",),
    buckets=LATENCY_BUCKETS,
)
HASH_BATCH_LEAVES = Histogram(
    "tendermint_hash_batch_leaves",
    "Merkle leaves per root build, by executing backend",
    labelnames=("backend",),
    buckets=SIZE_BUCKETS,
)
HASH_SECONDS = Histogram(
    "tendermint_hash_seconds",
    "Merkle root build latency, by executing backend",
    labelnames=("backend",),
    buckets=LATENCY_BUCKETS,
)
TABLE_CACHE = Counter(
    "tendermint_verify_table_cache_total",
    "Valset comb-table cache outcomes (hit/miss/incremental/host_fallback)",
    labelnames=("event",),
)
XLA_CACHE_ENABLED = Gauge(
    "tendermint_xla_persistent_cache_enabled",
    "1 when the persistent XLA executable cache is active",
)

# -- device observatory (telemetry/launchlog.py, tools/device_report.py) ------
#
# `kind` is the launch vocabulary (verify / hash / tables /
# leaf_hashes); `state` splits a launch's shipped rows into useful
# (requested work), padded (bucket/mesh geometry zeros — pure waste on
# device), and cached (rows the VerifiedSigCache withheld from the
# launch entirely); `stage` is the handle-lifecycle split (queue_wait /
# host_prep / in_flight / finalize). Per-launch detail (consumer mix,
# mesh width, compile attribution, exemplar trace) lives in the
# LaunchLedger records (`dump_telemetry?launches=N`), never as labels.

LAUNCH_ROWS = Counter(
    "tendermint_launch_rows",
    "Rows per device launch by disposition: useful (requested), padded "
    "(shape-bucket zeros shipped to device), cached (withheld by the "
    "verified-signature cache) — occupancy = useful / (useful + padded)",
    labelnames=("kind", "state"),
)
LAUNCH_STAGE_SECONDS = Histogram(
    "tendermint_launch_stage_seconds",
    "Per-launch stage durations from the dispatch-handle lifecycle: "
    "queue_wait (submit -> launch start), host_prep (lane prep + kernel "
    "dispatch), in_flight (enqueued on device -> consumer join), "
    "finalize (materialization blocking the consumer)",
    labelnames=("stage",),
    buckets=LATENCY_BUCKETS,
)
# byte-sized buckets: 1 KiB floor (a small lane batch) to 2 GiB (the
# 10k-valset sharded comb tables), x4 per step
TRANSFER_BUCKETS = tuple(float(1024 * 4**i) for i in range(11))
LAUNCH_TRANSFER_BYTES = Histogram(
    "tendermint_launch_transfer_bytes",
    "Host->device bytes shipped per launch (lane arrays, padded hash "
    "blocks, sharded-table device_put on placement-cache misses)",
    buckets=TRANSFER_BUCKETS,
)

# -- multi-chip verify mesh (parallel/mesh.py) --------------------------------
#
# `direction` is the re-mesh kind: "shrink" (shard fault -> survivors)
# or "restore" (re-probe brought the full mesh back) — a fixed pair.

MESH_DEVICES = Gauge(
    "tendermint_mesh_devices",
    "Devices currently active in the sharded verify/hash mesh",
)
MESH_SHARD_FAULTS = Counter(
    "tendermint_mesh_shard_faults_total",
    "Per-shard device faults observed by mesh launches",
)
MESH_REMESH = Counter(
    "tendermint_mesh_remesh_total",
    "Mesh rebuilds (shrink = onto survivors after a shard fault, "
    "restore = full mesh back after a successful re-probe)",
    labelnames=("direction",),
)
MESH_COMPILE = Counter(
    "tendermint_mesh_compile_total",
    "Compiled-step cache (_STEP_CACHE) lookups by outcome: a miss "
    "means a launch paid an XLA compile (survivor re-mesh, new "
    "program, fresh process)",
    labelnames=("result",),
)
MESH_COMPILE_SECONDS = Histogram(
    "tendermint_mesh_compile_seconds",
    "Wall time one compiled-step cache miss spent building/compiling "
    "the sharded step (the launch that pays it stalls for the duration)",
    buckets=LATENCY_BUCKETS,
)
TABLE_DEVICE_CACHE = Counter(
    "tendermint_table_device_cache_total",
    "Per-(valset, device-set) sharded-table placement cache outcomes; "
    "a miss re-ships the comb tables to device (device_put, GB-scale "
    "at large valsets)",
    labelnames=("result",),
)

# -- resilient dispatch / circuit breaker -------------------------------------

BREAKER_STATE = Gauge(
    "tendermint_breaker_state",
    "Circuit breaker state (0=closed, 1=half_open, 2=open)",
    labelnames=("kind",),
)
BREAKER_TRANSITIONS = Counter(
    "tendermint_breaker_transitions_total",
    "Breaker state transitions; to=open counts trips, to=closed recoveries",
    labelnames=("kind", "to"),
)
DISPATCH_PRIMARY = Counter(
    "tendermint_device_primary_calls_total",
    "Calls answered by the primary (device) backend",
    labelnames=("kind",),
)
DISPATCH_FALLBACK = Counter(
    "tendermint_device_fallback_calls_total",
    "Calls degraded to the host fallback",
    labelnames=("kind",),
)
DISPATCH_FAILURES = Counter(
    "tendermint_device_dispatch_failures_total",
    "Primary dispatch attempts that raised (pre-retry granularity)",
    labelnames=("kind",),
)

# -- async dispatch pipeline (services/dispatch.py) ---------------------------
#
# `queue` labels are the pipeline owners ("fastsync", "consensus",
# "default") — a fixed small set, never per-peer/per-height.

DISPATCH_INFLIGHT = Gauge(
    "tendermint_dispatch_inflight",
    "Launches submitted to a dispatch queue and not yet joined",
    labelnames=("queue",),
)
DISPATCH_QUEUE_WAIT = Histogram(
    "tendermint_dispatch_queue_wait_seconds",
    "Time a launch waited in the dispatch queue before starting",
    labelnames=("queue",),
    buckets=LATENCY_BUCKETS,
)
# Per-handle share of submit->join wall time the consumer spent doing
# other work (host prep, ABCI applies) instead of blocked in result().
# 0 = fully synchronous behavior; anything > 0 proves the overlap
# pipeline engaged (tools/bench_hotpath.py fastsync_pipeline section).
DISPATCH_OVERLAP = Histogram(
    "tendermint_dispatch_overlap_ratio",
    "Fraction of a dispatch handle's lifetime overlapped with host work",
    labelnames=("queue",),
    buckets=(0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0),
)

# -- verify coalescer + dedup cache (services/batcher.py) ---------------------
#
# `consumer` labels are the verify-request owners ("consensus",
# "fastsync", "statesync", "rpc", "mempool", "lightclient",
# "default") — a fixed small set.

VERIFY_CACHE_HITS = Counter(
    "tendermint_verify_cache_hits_total",
    "Signature triples answered from the verified-signature dedup cache",
)
VERIFY_CACHE_MISSES = Counter(
    "tendermint_verify_cache_misses_total",
    "Signature triples not in the dedup cache (dispatched for verification)",
)
VERIFY_CACHE_EVICTIONS = Counter(
    "tendermint_verify_cache_evictions_total",
    "Proven triples evicted from the dedup cache by LRU pressure",
)
BATCHER_COALESCE = Histogram(
    "tendermint_batcher_coalesce_factor",
    "Verify requests merged into one coalesced device launch",
    buckets=SIZE_BUCKETS,
)
BATCHER_FLUSH = Counter(
    "tendermint_batcher_flush_total",
    "Coalescer flushes by trigger (window/size/barrier)",
    labelnames=("reason",),
)
BATCHER_WAIT = Histogram(
    "tendermint_batcher_wait_seconds",
    "Time a verify request waited in the coalescer before its launch",
    labelnames=("consumer",),
    buckets=LATENCY_BUCKETS,
)

# -- distributed tracing (telemetry/tracectx.py, tools/trace_timeline.py) -----
#
# `stage` is a fixed per-vote lifecycle slice: "drain" (gossip arrival
# -> drained into a batch), "verify" (batch submit -> verdict join),
# "e2e" (arrival -> verdict applied). Histograms carry exemplar trace
# ids (JSON dump only) so an aggregate links back to one traced message.

TX_E2E = Histogram(
    "tendermint_tx_e2e_seconds",
    "Tx first-seen (CheckTx admission) to committed in a finalized block",
    buckets=LATENCY_BUCKETS,
)
VOTE_STAGE = Histogram(
    "tendermint_vote_stage_seconds",
    "Traced-vote lifecycle slices (drain/verify/e2e) on this node",
    labelnames=("stage",),
    buckets=LATENCY_BUCKETS,
)
TRACE_SAMPLED = Counter(
    "tendermint_trace_sampled_total",
    "Trace contexts minted (head-based sampling said yes)",
)
TRACE_PROPAGATED = Counter(
    "tendermint_trace_propagated_total",
    "p2p frames sent carrying a trace context",
)
TRACE_DROPPED = Counter(
    "tendermint_trace_dropped_total",
    "Trace contexts lost (wire decode failures, trace-table evictions)",
)

# The span-name catalog: every literal passed to TRACER.span()/.add()
# in the package must appear here (collection-time lint in
# tests/conftest.py, same discipline as the tendermint_* metric lint) —
# an uncataloged span name means a timeline query that silently matches
# nothing. The consensus round phases are recorded via an f-string over
# the fixed phase set; they are cataloged for the tooling regardless.
SPAN_CATALOG = frozenset(
    {
        "consensus.propose",
        "consensus.prevote",
        "consensus.precommit",
        "consensus.commit",
        "consensus.height",
        "lightclient.walk",
        "mempool.admission",
        "mempool.window",
        "p2p.hop",
        "scenario.run",
        "batcher.flush",
        "dispatch.launch",
        "tx.e2e",
        "vote.e2e",
    }
)

# Pre-seed the known breaker kinds, round-skip phases, and flush reasons
# so scrapes see zero-valued series before (or without) any
# instance/event — Prometheus convention: known label values start at 0,
# absence means "unknown".
for _kind in ("verify", "hash", "tables"):
    BREAKER_STATE.labels(kind=_kind).set(0)
for _phase in ("prevote", "precommit"):
    CONSENSUS_ROUND_SKIPS.labels(phase=_phase).inc(0)
for _reason in ("window", "size", "barrier"):
    BATCHER_FLUSH.labels(reason=_reason).inc(0)
for _direction in ("shrink", "restore"):
    MESH_REMESH.labels(direction=_direction).inc(0)
for _result in ("hit", "miss"):
    MESH_COMPILE.labels(result=_result).inc(0)
    TABLE_DEVICE_CACHE.labels(result=_result).inc(0)
for _kind in ("verify", "hash", "tables", "leaf_hashes"):
    for _state in ("useful", "padded", "cached"):
        LAUNCH_ROWS.labels(kind=_kind, state=_state).inc(0)
for _stage in ("queue_wait", "host_prep", "in_flight", "finalize"):
    LAUNCH_STAGE_SECONDS.labels(stage=_stage)
for _stage in ("drain", "verify", "e2e"):
    VOTE_STAGE.labels(stage=_stage)
for _phase in ("new_height", "propose", "prevote", "precommit", "commit", "apply"):
    HEIGHT_PHASE_SECONDS.labels(phase=_phase)
for _reason in ("propose", "prevote", "vote_tally", "shutdown", "fault"):
    PIPELINE_STALLS.labels(reason=_reason).inc(0)
for _phase in ("propose", "prevote", "precommit", "commit"):
    CONSENSUS_TIMEOUT_DERIVED.labels(phase=_phase)

# -- contention observatory (telemetry/profiler.py, utils/lockrank.py) --------
#
# `subsystem` is the fixed classification vocabulary the profiler maps
# thread names/stacks into (consensus, ingress, coalescer, dispatch,
# p2p_recv, p2p_send, statesync, rpc, abci, main, other); `state` is
# on_cpu or blocked; `wait` is the blocked-reason split (lock/io/sleep/
# other — "other" includes runnable-but-GIL-starved). `lock` label
# values come from the bounded utils/lockrank.py annotation vocabulary.
# All series only advance while profiling is armed
# (TENDERMINT_TPU_PROFILE_HZ > 0 or a profiler boost window).

PROFILE_SAMPLES = Counter(
    "tendermint_profile_samples_total",
    "Profiler stack samples by subsystem and on-CPU/blocked state "
    "(state=blocked carries the wait-reason split)",
    labelnames=("subsystem", "state", "wait"),
)
PROFILE_TICK_SECONDS = Histogram(
    "tendermint_profile_tick_seconds",
    "Wall time one profiler sampling pass took (self-overhead guard)",
    buckets=LATENCY_BUCKETS,
)
LOCK_WAIT_SECONDS = Histogram(
    "tendermint_lock_wait_seconds",
    "Blocking acquire-wait per annotated ranked lock (armed profiling "
    "only; per-site attribution in dump_telemetry?profile=1)",
    labelnames=("lock",),
    buckets=LATENCY_BUCKETS,
)
LOCK_HOLD_SECONDS = Histogram(
    "tendermint_lock_hold_seconds",
    "Hold duration per annotated ranked lock (armed profiling only)",
    labelnames=("lock",),
    buckets=LATENCY_BUCKETS,
)

# -- process resources (telemetry/process.py) ---------------------------------

PROCESS_RSS = Gauge(
    "tendermint_process_rss_bytes", "Resident set size of this process"
)
PROCESS_FDS = Gauge(
    "tendermint_process_open_fds", "Open file descriptors in this process"
)
PROCESS_THREADS = Gauge(
    "tendermint_process_threads", "Live Python threads in this process"
)
PROCESS_GC_PAUSE = Histogram(
    "tendermint_process_gc_pause_seconds",
    "Stop-the-world GC collection pauses (gc.callbacks timing; "
    "installed by telemetry/process.py install_gc_telemetry)",
    buckets=LATENCY_BUCKETS,
)
PROCESS_GC_COLLECTIONS = Counter(
    "tendermint_process_gc_collections_total",
    "GC collections by generation",
    labelnames=("gen",),
)

for _gen in ("0", "1", "2"):
    PROCESS_GC_COLLECTIONS.labels(gen=_gen).inc(0)

# live views cost nothing between scrapes (same discipline as the
# node-bound gauges below, but process-scoped so no node is needed)
from tendermint_tpu.telemetry import process as _process  # noqa: E402

PROCESS_RSS.set_function(_process.rss_bytes)
PROCESS_FDS.set_function(_process.open_fds)
PROCESS_THREADS.set_function(_process.thread_count)

# -- state sync ---------------------------------------------------------------

STATESYNC_CHUNKS = Counter(
    "tendermint_statesync_chunks_total",
    "Snapshot chunks received while syncing (ok/corrupt/timeout)",
    labelnames=("result",),
)
STATESYNC_CHUNKS_SERVED = Counter(
    "tendermint_statesync_chunks_served_total",
    "Snapshot chunks served to syncing peers",
)
STATESYNC_CHUNK_VERIFY_SECONDS = Histogram(
    "tendermint_statesync_chunk_verify_seconds",
    "Batched Merkle verification latency over a full snapshot chunk set",
    buckets=LATENCY_BUCKETS,
)
STATESYNC_RESTORE_SECONDS = Histogram(
    "tendermint_statesync_restore_seconds",
    "Wall time from snapshot selection to restored state (incl. chunk fetch)",
    buckets=LATENCY_BUCKETS,
)
STATESYNC_SNAPSHOT_SECONDS = Histogram(
    "tendermint_statesync_snapshot_seconds",
    "Snapshot creation latency (serialize + chunk + device tree + persist)",
    buckets=LATENCY_BUCKETS,
)
STATESYNC_SNAPSHOTS_TAKEN = Counter(
    "tendermint_statesync_snapshots_taken_total", "Snapshots created by this node"
)
STATESYNC_SNAPSHOTS_REJECTED = Counter(
    "tendermint_statesync_snapshots_rejected_total",
    "Offered snapshots rejected (trust anchoring, bad chunks, timeouts)",
)
STATESYNC_RESTORES = Counter(
    "tendermint_statesync_restores_total",
    "Snapshot restore attempts by outcome (ok/failed)",
    labelnames=("result",),
)

for _result in ("ok", "corrupt", "timeout"):
    STATESYNC_CHUNKS.labels(result=_result).inc(0)

# -- light-client serving layer (tendermint_tpu/lightclient/) -----------------
#
# `result` is the fixed walk-outcome vocabulary: ok (trust advanced to
# the target), too_much_change (bisection bottomed out — the valset
# churned faster than the source's commit density can bridge), forged
# (a candidate carried an invalid signature / impossible quorum — a
# provider offense, never a bisection trigger), trust_expired (the
# LOCAL pin outlived the trust period — operator action, not a peer
# offense), no_source (the source provider had nothing to offer —
# fetch timeout / lagging provider, environmental). Only `forged` is
# an alertable provider offense. `mode` distinguishes
# the legacy header-by-header walk (sequential — the
# InquiringCertifier baseline) from the skipping walk (bisect).
# `kind` on the proofs-served counter is the fixed query taxonomy
# (full_commit / commit / validators / tx / abci_query) — never
# heights or peer ids.

LIGHTCLIENT_BISECTIONS = Counter(
    "tendermint_lightclient_bisections_total",
    "Skipping-verification walks by outcome (ok / too_much_change / "
    "forged / trust_expired / no_source)",
    labelnames=("result",),
)
LIGHTCLIENT_WALK_SECONDS = Histogram(
    "tendermint_lightclient_walk_seconds",
    "Wall time one certifier walk took to move trust to the target "
    "height (sequential = header-by-header InquiringCertifier, "
    "bisect = batched skipping verification)",
    labelnames=("mode",),
    buckets=LATENCY_BUCKETS,
)
LIGHTCLIENT_CACHE_HITS = Counter(
    "tendermint_lightclient_cache_hits_total",
    "FullCommit lookups answered from the certified-commit cache",
)
LIGHTCLIENT_CACHE_MISSES = Counter(
    "tendermint_lightclient_cache_misses_total",
    "FullCommit lookups that missed the certified-commit cache",
)
REPLICA_PROOFS_SERVED = Counter(
    "tendermint_replica_proofs_served_total",
    "Light-client queries answered by this node's serving layer, by "
    "proof kind (p2p FullCommit channel + proof-carrying RPC routes)",
    labelnames=("kind",),
)

for _result in ("ok", "too_much_change", "forged", "trust_expired", "no_source"):
    LIGHTCLIENT_BISECTIONS.labels(result=_result).inc(0)
for _mode in ("sequential", "bisect"):
    LIGHTCLIENT_WALK_SECONDS.labels(mode=_mode)
for _kind in ("full_commit", "commit", "validators", "tx", "abci_query"):
    REPLICA_PROOFS_SERVED.labels(kind=_kind).inc(0)

# -- p2p ----------------------------------------------------------------------

P2P_SENT_BYTES = Counter(
    "tendermint_p2p_sent_bytes_total", "Frame bytes sent to peers"
)
P2P_RECV_BYTES = Counter(
    "tendermint_p2p_recv_bytes_total", "Frame bytes received from peers"
)
P2P_PEERS = Gauge("tendermint_p2p_peers", "Connected peers")
P2P_SEND_RATE = Gauge(
    "tendermint_p2p_send_rate_bytes", "Aggregate send rate over live peers, bytes/s"
)
P2P_RECV_RATE = Gauge(
    "tendermint_p2p_recv_rate_bytes", "Aggregate recv rate over live peers, bytes/s"
)
# Send-queue depth is the backpressure signal: a climbing depth means a
# peer drains slower than reactors produce. Exported as the aggregate
# sum and the worst single peer (per-peer series would be unbounded
# cardinality — peer ids churn; the max pinpoints "one slow peer"
# vs "everyone backed up" without it).
P2P_SEND_QUEUE = Gauge(
    "tendermint_p2p_send_queue_depth",
    "Frames queued for send across all peers and channels",
)
P2P_SEND_QUEUE_MAX = Gauge(
    "tendermint_p2p_send_queue_max",
    "Deepest single-peer send queue (frames)",
)
# The wait twin of the depth gauges: enqueue -> send-loop dequeue per
# frame, aggregated over all peers/channels — the p2p leg of the
# queue-wait unification (dump_telemetry?profile=1 "queues" view).
P2P_SEND_WAIT = Histogram(
    "tendermint_p2p_send_wait_seconds",
    "Time a frame waited in a peer send queue before hitting the wire",
    buckets=LATENCY_BUCKETS,
)
# Adversarial-input defense (p2p/score.py + Switch.report_misbehavior):
# `kind` is the fixed offense taxonomy (bad_frame/oversize_frame/
# bad_msg/bad_sig/bad_vote/forged_block/forged_fullcommit/
# bad_evidence/flood) — never
# peer ids (per-peer scores live in the scorer's diagnostics snapshot).
PEER_MISBEHAVIOR = Counter(
    "tendermint_p2p_peer_misbehavior_total",
    "Classified peer offenses debited against misbehavior scores",
    labelnames=("kind",),
)
PEER_BANS = Counter(
    "tendermint_p2p_peer_bans_total",
    "Peers banned for crossing the misbehavior threshold",
)

for _kind in (
    "bad_frame",
    "oversize_frame",
    "bad_msg",
    "bad_sig",
    "bad_vote",
    "forged_block",
    "forged_fullcommit",
    "bad_evidence",
    "flood",
):
    PEER_MISBEHAVIOR.labels(kind=_kind).inc(0)

# -- gossip observatory (telemetry/gossiplog.py) ------------------------------
#
# Per-channel bandwidth attribution and duplicate-delivery redundancy.
# `channel` and `kind` are the FIXED wire vocabularies below — the
# channel-id map and first-byte message tags mirrored from the reactors
# by gossiplog.py (unknown -> "other"), never peer ids or heights.
# Per-peer tables and first-seen propagation stamps are dump-only
# (`dump_telemetry?gossip=1`); tools/gossip_report.py merges them
# across nodes.

GOSSIP_CHANNELS = (
    "pex",
    "cns_state",
    "cns_data",
    "cns_vote",
    "cns_votebits",
    "mempool",
    "evidence",
    "blockchain",
    "statesync",
    "lightclient",
    "ctrl",
    "other",
)
GOSSIP_KINDS = (
    "pex_request",
    "pex_addrs",
    "new_round_step",
    "commit_step",
    "proposal",
    "proposal_pol",
    "block_part",
    "vote",
    "has_vote",
    "vote_set_maj23",
    "vote_set_bits",
    "proposal_heartbeat",
    "tx",
    "evidence_list",
    "block_request",
    "block_response",
    "no_block",
    "status_request",
    "status_response",
    "snapshots_request",
    "snapshots_response",
    "chunk_request",
    "chunk_response",
    "no_chunk",
    "commit_request",
    "commit_response",
    "fc_request",
    "fc_response",
    "fc_subscribe",
    "fc_announce",
    "ping",
    "pong",
    "other",
)
# The silent-dedup taxonomy: kinds whose duplicate deliveries used to
# vanish (VoteSet exact-dup adds, PartSet already-have parts, mempool
# dup-cache hits on re-arrival, evidence-pool re-offers).
GOSSIP_REDUNDANT_KINDS = ("vote", "block_part", "tx", "evidence")

P2P_CHANNEL_BYTES = Counter(
    "tendermint_p2p_channel_bytes_total",
    "Frame bytes by p2p channel and direction (send/recv)",
    labelnames=("channel", "dir"),
)
GOSSIP_MSGS = Counter(
    "tendermint_gossip_msgs_total",
    "Gossip messages by wire kind and direction (send/recv)",
    labelnames=("kind", "dir"),
)
GOSSIP_REDUNDANT = Counter(
    "tendermint_gossip_redundant_total",
    "Duplicate gossip deliveries dedup'd after arrival, by kind",
    labelnames=("kind",),
)
GOSSIP_REDUNDANT_BYTES = Counter(
    "tendermint_gossip_redundant_bytes_total",
    "Payload bytes of duplicate gossip deliveries, by kind",
    labelnames=("kind",),
)

for _dir in ("send", "recv"):
    for _chan in GOSSIP_CHANNELS:
        P2P_CHANNEL_BYTES.labels(channel=_chan, dir=_dir).inc(0)
    for _kind in GOSSIP_KINDS:
        GOSSIP_MSGS.labels(kind=_kind, dir=_dir).inc(0)
for _kind in GOSSIP_REDUNDANT_KINDS:
    GOSSIP_REDUNDANT.labels(kind=_kind).inc(0)
    GOSSIP_REDUNDANT_BYTES.labels(kind=_kind).inc(0)

# -- WAN link chaos + scenario engine (p2p/transport.py, testing/) ------------
#
# `result` on the link-send counter is the fixed delivery taxonomy of
# the chaos layer: delivered (immediate), delayed (rode the delivery
# wheel), dup (extra copy scheduled), dropped, partitioned. No per-link
# labels — a WAN harness runs O(n^2) links and peer-pair series would
# be unbounded; `tools/scenario_run.py` reports are per-link instead.

LINK_SENDS = Counter(
    "tendermint_link_sends_total",
    "ChaosEndpoint sends by delivery outcome (delivered / delayed / "
    "dup / dropped / partitioned)",
    labelnames=("result",),
)
LINK_DELIVERY_DELAY = Histogram(
    "tendermint_link_delivery_delay_seconds",
    "Extra latency injected per delayed delivery (propagation delay + "
    "jitter + bandwidth serialization), as scheduled on the wheel",
    buckets=LATENCY_BUCKETS,
)
LINK_BANDWIDTH_WAIT = Histogram(
    "tendermint_link_bandwidth_wait_seconds",
    "Token-bucket serialization wait per bandwidth-capped send (the "
    "queueing component of the injected delay)",
    buckets=LATENCY_BUCKETS,
)
LINK_INFLIGHT = Gauge(
    "tendermint_link_inflight_deliveries",
    "Delayed deliveries pending on the shared delivery wheel (the "
    "thread-count regression signal: one thread serves all of these)",
)
SCENARIO_RUNS = Counter(
    "tendermint_scenario_runs_total",
    "Declarative scenarios executed by ScenarioRunner, by verdict",
    labelnames=("result",),
)
SCENARIO_SECONDS = Histogram(
    "tendermint_scenario_seconds",
    "Wall time per executed scenario (build + run + report)",
    buckets=LATENCY_BUCKETS,
)

for _result in (
    "delivered", "delayed", "dup", "dropped", "partitioned", "congested",
):
    LINK_SENDS.labels(result=_result).inc(0)
for _result in ("pass", "fail"):
    SCENARIO_RUNS.labels(result=_result).inc(0)

# -- evidence -----------------------------------------------------------------

EVIDENCE_POOL_DEPTH = Gauge(
    "tendermint_evidence_pool_depth",
    "Verified misbehavior proofs pending commitment into a block",
)
EVIDENCE_COMMITTED = Counter(
    "tendermint_evidence_committed_total",
    "Evidence retired from the pool by block commitment",
)
EVIDENCE_EXPIRED = Counter(
    "tendermint_evidence_expired_total",
    "Pending evidence pruned past the ConsensusParams max-age window",
)

# -- mempool ------------------------------------------------------------------
#
# `result` outcomes are fixed: ok / rejected (app said no) / duplicate
# (dup-cache hit) / bad_sig (signed-envelope verify failed) / flushed
# (operator flush invalidated an in-flight admission). Ingress `reason`
# mirrors the coalescer's flush triggers (window/size/barrier).

MEMPOOL_SIZE = Gauge("tendermint_mempool_size", "Pending txs in the mempool")
MEMPOOL_TXS = Counter(
    "tendermint_mempool_txs_total",
    "CheckTx outcomes (ok/rejected/duplicate/bad_sig/flushed)",
    labelnames=("result",),
)
MEMPOOL_ADMISSION_SECONDS = Histogram(
    "tendermint_mempool_admission_seconds",
    "CheckTx arrival to admission verdict (ingress queue + verify window "
    "+ app check); exemplar-linked to the admitted tx's trace id",
    buckets=LATENCY_BUCKETS,
)
MEMPOOL_INGRESS_WINDOW = Histogram(
    "tendermint_mempool_ingress_window_txs",
    "Txs merged per ingress verify window",
    buckets=SIZE_BUCKETS,
)
MEMPOOL_INGRESS_FLUSH = Counter(
    "tendermint_mempool_ingress_flush_total",
    "Ingress window flushes by trigger (window/size/barrier)",
    labelnames=("reason",),
)

for _reason in ("window", "size", "barrier"):
    MEMPOOL_INGRESS_FLUSH.labels(reason=_reason).inc(0)
for _result in ("ok", "rejected", "duplicate", "bad_sig", "flushed"):
    MEMPOOL_TXS.labels(result=_result).inc(0)

# -- consensus WAL ------------------------------------------------------------

WAL_FSYNC_SECONDS = Histogram(
    "tendermint_wal_fsync_seconds",
    "Consensus WAL write+fsync latency per record",
    buckets=LATENCY_BUCKETS,
)
WAL_WRITTEN_BYTES = Counter(
    "tendermint_wal_written_bytes_total", "Framed bytes appended to the consensus WAL"
)

# -- rpc ----------------------------------------------------------------------

RPC_REQUESTS = Counter(
    "tendermint_rpc_requests_total",
    "RPC calls served, by method and outcome",
    labelnames=("method", "result"),
)
RPC_SECONDS = Histogram(
    "tendermint_rpc_request_seconds",
    "RPC handler latency by method (dispatch to result, excl. socket I/O)",
    labelnames=("method",),
    buckets=LATENCY_BUCKETS,
)


def bind_node_gauges(node) -> None:
    """Point the live-view gauges at a composed `node.Node`. Called from
    the node's start(); the callbacks read cheap in-memory state at
    scrape time only."""

    # GC pause timing rides along: a serving node always wants it, and
    # the hook is idempotent + process-lifetime cheap
    _process.install_gc_telemetry()

    P2P_PEERS.set_function(lambda: node.switch.n_peers() if node.switch else 0)
    P2P_SEND_RATE.set_function(lambda: node.switch.send_rate_total())
    P2P_RECV_RATE.set_function(lambda: node.switch.recv_rate_total())
    P2P_SEND_QUEUE.set_function(lambda: node.switch.send_queue_depth_total())
    P2P_SEND_QUEUE_MAX.set_function(lambda: node.switch.send_queue_depth_max())
    MEMPOOL_SIZE.set_function(lambda: node.mempool.size())
