"""Dependency-free metrics registry: counters, gauges, histograms.

Fills the reference's `libs/metrics` + Prometheus-client slot without
pulling a client library into the image: metric families with labels,
thread-safe updates (consensus, gossip, RPC, and dispatch threads all
write concurrently), Prometheus text exposition (format 0.0.4, served
by `GET /metrics` on the RPC listener), and a structured JSON dump
(the `dump_telemetry` RPC).

Design notes:

* One lock per family guards its children map AND their values — the
  hot paths (per-frame byte counters, per-batch histograms) touch one
  family each, so contention stays within a subsystem.
* Gauges may carry a callback (`set_function`) evaluated at collect
  time — live views (peer count, byte rates, mempool depth) cost
  nothing between scrapes.
* Histograms use fixed cumulative buckets chosen at registration;
  `quantile()` interpolates within the winning bucket, which is exactly
  as much resolution as fixed buckets can honestly give.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Sequence

# Latency buckets: 100 us floor (host verify of one sig is ~60 us) to
# 30 s (cold XLA compile territory), roughly x2.5 per step.
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# Batch-size buckets: powers of two up to the vote-drain cap / the 65k
# bench shapes.
SIZE_BUCKETS = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1024.0, 2048.0, 4096.0, 8192.0, 16384.0, 65536.0,
)


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    """Family base: name, help, label names, children keyed by label
    values. Unlabeled families expose the child API directly."""

    type_name = "untyped"

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        registry: "Registry | None" = None,
    ) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        reg = registry if registry is not None else REGISTRY
        reg.register(self)
        if not self.labelnames:
            # the no-label child exists from birth so the family always
            # exposes a sample (scrapes see zeros, not absence)
            self._default = self._make_child(())
        else:
            self._default = None

    def _make_child(self, values: tuple[str, ...]):
        child = self.CHILD(self._lock)
        self._children[values] = child
        return child

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child(values)
            return child

    def samples(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return [(k, v.snapshot()) for k, v in self._children.items()]

    def sum_total(self) -> float:
        """Sum of all children's observation sums (histograms) or
        values (counters/gauges) — the cheap read hot-path stopwatch
        consumers (telemetry/heightlog.py) take at phase boundaries,
        without building per-child bucket snapshots."""
        total = 0.0
        with self._lock:
            for c in self._children.values():
                s = getattr(c, "_sum", None)
                if s is None:
                    s = getattr(c, "_value", 0.0)
                total += s
        return float(total)

    # unlabeled convenience: family proxies to its default child
    def _child0(self):
        if self._default is None:
            raise ValueError(f"{self.name} has labels {self.labelnames}; use .labels()")
        return self._default


class _CounterChild:
    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self._value  # caller holds the family lock


class Counter(_Metric):
    type_name = "counter"
    CHILD = _CounterChild

    def inc(self, n: float = 1.0) -> None:
        self._child0().inc(n)

    @property
    def value(self) -> float:
        return self._child0().value


class _GaugeChild:
    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Callable[[], float] | None) -> None:
        """Collect-time callback; exceptions keep the last stored value
        (a scrape must never fail because a live view raced teardown)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            return self.snapshot()

    def snapshot(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                self._value = float(fn())
            except Exception:
                pass
        return self._value


class Gauge(_Metric):
    type_name = "gauge"
    CHILD = _GaugeChild

    def set(self, v: float) -> None:
        self._child0().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._child0().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._child0().dec(n)

    def set_function(self, fn: Callable[[], float] | None) -> None:
        self._child0().set_function(fn)

    @property
    def value(self) -> float:
        return self._child0().value


class _HistogramChild:
    def __init__(self, lock: threading.Lock, buckets: tuple[float, ...]) -> None:
        self._lock = lock
        self.buckets = buckets  # upper bounds, +Inf implicit
        self._counts = [0] * (len(buckets) + 1)
        self._sum = 0.0
        self._count = 0
        # last exemplar attached to an observation (a trace id): the
        # breadcrumb from an aggregate back to one concrete traced
        # request. JSON dump only — text format 0.0.4 has no exemplars.
        self._exemplar: str | None = None

    def observe(self, v: float, exemplar: str | None = None) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            if exemplar is not None:
                self._exemplar = str(exemplar)
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        # caller holds the family lock (or tolerates a torn read via .value)
        cumulative = []
        running = 0
        for c in self._counts:
            running += c
            cumulative.append(running)
        out = {
            "buckets": list(zip(list(self.buckets) + [math.inf], cumulative)),
            "sum": self._sum,
            "count": self._count,
        }
        if self._exemplar is not None:
            out["exemplar"] = self._exemplar
        return out

    @property
    def value(self) -> dict:
        with self._lock:
            return self.snapshot()

    def quantile(self, q: float) -> float:
        """Linear interpolation inside the winning bucket — standard
        Prometheus histogram_quantile() semantics."""
        snap = self.value
        if snap["count"] == 0:
            return float("nan")
        rank = q * snap["count"]
        prev_ub, prev_cum = 0.0, 0
        for ub, cum in snap["buckets"]:
            if cum >= rank:
                if ub == math.inf:
                    return prev_ub  # open-ended: best honest answer
                width = ub - prev_ub
                in_bucket = cum - prev_cum
                if in_bucket == 0:
                    return ub
                return prev_ub + width * (rank - prev_cum) / in_bucket
            prev_ub, prev_cum = ub, cum
        return prev_ub


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = LATENCY_BUCKETS,
        registry: "Registry | None" = None,
    ) -> None:
        self._buckets = tuple(sorted(float(b) for b in buckets))
        if not self._buckets:
            raise ValueError("histogram needs at least one bucket")
        super().__init__(name, help, labelnames, registry)

    def _make_child(self, values: tuple[str, ...]):
        child = _HistogramChild(self._lock, self._buckets)
        self._children[values] = child
        return child

    def observe(self, v: float, exemplar: str | None = None) -> None:
        self._child0().observe(v, exemplar=exemplar)

    def quantile(self, q: float) -> float:
        return self._child0().quantile(q)

    @property
    def value(self) -> dict:
        return self._child0().value


class Registry:
    """Named metric families; collection renders every family even when
    a labeled one has no children yet (HELP/TYPE lines make the catalog
    discoverable from a scrape of an idle node)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: "dict[str, _Metric]" = {}

    def register(self, metric: _Metric) -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(f"metric {metric.name} already registered")
            self._metrics[metric.name] = metric

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4 (`Content-Type: text/plain;
        version=0.0.4`)."""
        out: list[str] = []
        for m in self.metrics():
            out.append(f"# HELP {m.name} {_escape_help(m.help)}")
            out.append(f"# TYPE {m.name} {m.type_name}")
            for values, snap in m.samples():
                if m.type_name == "histogram":
                    for ub, cum in snap["buckets"]:
                        ls = _label_str(
                            m.labelnames + ("le",),
                            values + (_format_value(ub),),
                        )
                        out.append(f"{m.name}_bucket{ls} {cum}")
                    ls = _label_str(m.labelnames, values)
                    out.append(f"{m.name}_sum{ls} {_format_value(snap['sum'])}")
                    out.append(f"{m.name}_count{ls} {snap['count']}")
                else:
                    ls = _label_str(m.labelnames, values)
                    out.append(f"{m.name}{ls} {_format_value(snap)}")
        return "\n".join(out) + "\n"

    def to_dict(self) -> dict:
        """Structured dump for the `dump_telemetry` RPC / bench tools."""
        out: dict = {}
        for m in self.metrics():
            series = []
            for values, snap in m.samples():
                labels = dict(zip(m.labelnames, values))
                if m.type_name == "histogram":
                    entry = {
                        "labels": labels,
                        "sum": snap["sum"],
                        "count": snap["count"],
                        "buckets": [
                            ["+Inf" if ub == math.inf else ub, cum]
                            for ub, cum in snap["buckets"]
                        ],
                    }
                    if "exemplar" in snap:
                        entry["exemplar"] = snap["exemplar"]
                    series.append(entry)
                else:
                    series.append({"labels": labels, "value": snap})
            out[m.name] = {
                "type": m.type_name,
                "help": m.help,
                "series": series,
            }
        return out

    def counter_value(self, name: str, **labels) -> float:
        """Test/invariant helper: current value of a counter/gauge series
        (0.0 when the series doesn't exist yet — unobserved == zero)."""
        m = self.get(name)
        if m is None:
            return 0.0
        want = tuple(str(labels[n]) for n in m.labelnames) if labels else ()
        for values, snap in m.samples():
            if not labels and not m.labelnames:
                return float(snap)
            if values == want:
                return float(snap)
        return 0.0


# The process-wide default registry: the metric catalog
# (`telemetry/metrics.py`) registers into it at import, `/metrics`
# serves it, `dump_telemetry` dumps it.
REGISTRY = Registry()
