"""Lightweight span tracer for consensus/device-path timelines.

The in-process half of tracing: a bounded ring of completed spans
(name, wall-clock start/end, attributes) cheap enough to leave on in
production. Consensus records one span per round phase
(`consensus.propose` → `consensus.commit`, attributed with
height/round), device dispatch records verify/hash batches; the
`dump_telemetry` RPC serves the recent window so a stalled height can
be read as a timeline instead of reverse-engineered from logs.

Spans that carry a `trace` attribute (a `telemetry/tracectx.py`
trace id) are the distributed half: `tools/trace_timeline.py` merges
span logs from N nodes and stitches same-trace spans into one
cross-cluster timeline. Every span name recorded with a literal must be
registered in `telemetry/metrics.py`'s SPAN_CATALOG (collection-time
lint, same discipline as the metric catalog).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


def _snapshot_attrs(attrs: dict) -> dict:
    """Copy `attrs` tolerating concurrent writers: a traced path may
    hand its attrs dict to another thread (callers add attrs mid-span),
    and a resize during the copy raises RuntimeError — retry, and never
    let the snapshot kill the traced path."""
    for _ in range(4):
        try:
            return dict(attrs)
        except RuntimeError:
            continue
    return {}


@dataclass
class Span:
    name: str
    start: float  # time.time() epoch seconds
    end: float
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.end - self.start

    def to_dict(self) -> dict:
        # attrs are COPIED: a reader serializing the dict must never
        # observe (or publish) a later writer's mutation
        return {
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": self.duration,
            **({"attrs": dict(self.attrs)} if self.attrs else {}),
        }


class Tracer:
    """Bounded ring of completed spans; thread-safe. Optional sink
    callbacks observe every completed span (the JSONL span log persists
    them across restarts — `telemetry/spanlog.py`); multiple sinks are
    supported so multi-node-in-process harnesses can keep one span log
    per node. Sink errors are swallowed: recording must never fail the
    traced path."""

    def __init__(self, capacity: int = 1024) -> None:
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=capacity)
        self._sinks: tuple = ()

    def add_sink(self, fn) -> None:
        """Attach `fn(span)` as an additional completion sink."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks = self._sinks + (fn,)

    def remove_sink(self, fn) -> None:
        """Detach one sink; other sinks (a successor node's span log)
        stay installed. Equality, not identity: bound methods are a new
        object per attribute access, so `log.append` must still match."""
        with self._lock:
            self._sinks = tuple(s for s in self._sinks if s != fn)

    def set_sink(self, fn) -> None:
        """Legacy single-sink API: replace ALL sinks with `fn` (None
        clears)."""
        with self._lock:
            self._sinks = () if fn is None else (fn,)

    def clear_sink(self, fn) -> None:
        """Remove the sink only if `fn` is an installed one — a
        stopping node must not strip a successor's sink."""
        self.remove_sink(fn)

    def add(self, name: str, start: float, end: float, **attrs) -> Span:
        span = Span(name, start, end, attrs)
        with self._lock:
            self._spans.append(span)
            sinks = self._sinks
        for sink in sinks:
            try:
                sink(span)
            except Exception:
                pass
        return span

    @contextmanager
    def span(self, name: str, **attrs):
        """`with TRACER.span("mempool.admission", n=512): ...` — the span
        recorded on exit, errors included (attr `error` is set). The
        attrs are SNAPSHOT at completion: the yielded dict may keep
        being mutated (even from another thread) without racing the
        recorded span or its readers."""
        t0 = time.time()
        try:
            yield attrs  # callers may add attrs mid-span
        except BaseException as e:
            attrs["error"] = f"{type(e).__name__}"
            raise
        finally:
            self.add(name, t0, time.time(), **_snapshot_attrs(attrs))

    def recent(self, n: int | None = None, prefix: str = "") -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if prefix:
            spans = [s for s in spans if s.name.startswith(prefix)]
        if n is not None:
            spans = spans[-n:]
        return [s.to_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# Process-wide tracer, sized for ~2 minutes of 4-phase consensus at
# test speed plus device-path spans.
TRACER = Tracer(capacity=1024)
