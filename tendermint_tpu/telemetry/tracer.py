"""Lightweight span tracer for consensus/device-path timelines.

Not a distributed tracer — a bounded in-process ring of completed spans
(name, wall-clock start/end, attributes) cheap enough to leave on in
production. Consensus records one span per round phase
(`consensus.propose` → `consensus.commit`, attributed with
height/round), device dispatch records verify/hash batches; the
`dump_telemetry` RPC serves the recent window so a stalled height can
be read as a timeline instead of reverse-engineered from logs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start: float  # time.time() epoch seconds
    end: float
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.end - self.start

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": self.duration,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Bounded ring of completed spans; thread-safe. An optional sink
    callback observes every completed span (the JSONL span log persists
    them across restarts — `telemetry/spanlog.py`); sink errors are
    swallowed, recording must never fail the traced path."""

    def __init__(self, capacity: int = 1024) -> None:
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=capacity)
        self._sink = None

    def set_sink(self, fn) -> None:
        """Install `fn(span)` as the completion sink (None clears)."""
        self._sink = fn

    def clear_sink(self, fn) -> None:
        """Remove the sink only if `fn` is still the installed one —
        a stopping node must not strip a successor's sink."""
        if self._sink is fn:
            self._sink = None

    def add(self, name: str, start: float, end: float, **attrs) -> Span:
        span = Span(name, start, end, attrs)
        with self._lock:
            self._spans.append(span)
        sink = self._sink
        if sink is not None:
            try:
                sink(span)
            except Exception:
                pass
        return span

    @contextmanager
    def span(self, name: str, **attrs):
        """`with TRACER.span("verify.batch", n=512): ...` — the span is
        recorded on exit, errors included (attr `error` is set)."""
        t0 = time.time()
        try:
            yield attrs  # callers may add attrs mid-span
        except BaseException as e:
            attrs["error"] = f"{type(e).__name__}"
            raise
        finally:
            self.add(name, t0, time.time(), **attrs)

    def recent(self, n: int | None = None, prefix: str = "") -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if prefix:
            spans = [s for s in spans if s.name.startswith(prefix)]
        if n is not None:
            spans = spans[-n:]
        return [s.to_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# Process-wide tracer, sized for ~2 minutes of 4-phase consensus at
# test speed plus device-path spans.
TRACER = Tracer(capacity=1024)
