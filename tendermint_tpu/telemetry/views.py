"""Dump-only high-cardinality views: ONE convention, one code path.

The exported metric catalog is low-cardinality **by construction**
(docs/OBSERVABILITY.md "Label conventions"): never peer ids, heights,
thread names, or code sites. But operators still need those details —
which peer's send queue is backed up, which validator's votes lag,
which code site waits on which lock. The convention (documented in
docs/OBSERVABILITY.md "Dump-only views"):

* anything keyed by an unbounded identity (peer id, thread name, lock
  site) is served ONLY through the `dump_telemetry` JSON RPC, never as
  an exported series;
* every such view is a named builder registered HERE, so
  `rpc/core.py` has one code path instead of one ad-hoc stanza per
  view, and the convention is greppable;
* builders read node-local/process state at dump time, return `None`
  to omit themselves (stub nodes without a switch, profiler disarmed),
  and must never raise — a forensic dump can't fail because one
  subsystem is mid-teardown.

Views: `p2p` (per-peer send queues + misbehavior scores),
`vote_arrivals` (per-peer laggard rollup), `profile` (the contention
observatory: profiler snapshot + top-contended locks + the unified
queue-wait table), `launches` (the device observatory: per-launch
ledger records + per-kind rollup, behind `dump_telemetry?launches=N`).
"""

from __future__ import annotations

import math
from typing import Callable

VIEWS: dict[str, Callable] = {}


def view(name: str):
    def deco(fn):
        VIEWS[name] = fn
        return fn

    return deco


def collect(node, names) -> dict:
    """{name: built view} for every requested view that applies; an
    unknown name or a raising/None builder is silently omitted (dumps
    degrade, never fail). An entry may be `(name, kwargs)` to pass
    builder parameters (the `launches=N` window size)."""
    out = {}
    for name in names:
        kwargs = {}
        if isinstance(name, tuple):
            name, kwargs = name
        fn = VIEWS.get(name)
        if fn is None:
            continue
        try:
            val = fn(node, **kwargs)
        except Exception:
            continue
        if val is not None:
            out[name] = val
    return out


# -- the views ----------------------------------------------------------------


@view("p2p")
def _p2p_view(node) -> dict | None:
    """Per-peer send-queue depths + misbehavior scores (peer-id
    cardinality — the exported gauges only carry the sum and max)."""
    switch = getattr(node, "switch", None)
    if switch is None:
        return None
    return {
        "send_queues": switch.send_queue_depths(),
        # misbehavior scores + live bans (docs/BYZANTINE.md); absent on
        # stub switches without a scorer
        "misbehavior": (
            switch.scorer.snapshot()
            if getattr(switch, "scorer", None) is not None
            else {}
        ),
    }


@view("vote_arrivals")
def _vote_arrivals_view(node) -> dict | None:
    """Per-peer vote-arrival rollup (the laggard signal
    tools/finality_report.py consumes)."""
    arrivals = getattr(getattr(node, "consensus", None), "vote_arrivals", None)
    if arrivals is None:
        return None
    return arrivals.snapshot()


@view("gossip")
def _gossip_view(node) -> dict | None:
    """The gossip observatory (opt-in, `gossip=1`): the switch-owned
    GossipRollup snapshot — per-peer × per-channel × per-kind traffic
    tables, per-kind redundancy counters, and first-seen propagation
    stamps. Per-peer and per-height detail lives ONLY here (dump-only
    cardinality); `tools/gossip_report.py` merges dumps across nodes
    into the bandwidth waterfall + propagation matrix."""
    gossip = getattr(getattr(node, "switch", None), "gossip", None)
    if gossip is None:
        return None
    snap = gossip.snapshot()
    snap["redundancy_factor"] = gossip.redundancy_factors()
    # join the consensus node id so cross-node merges can label rows
    # even when dumps are collected from files rather than RPC
    info = getattr(getattr(node, "switch", None), "node_info", None)
    if info is not None:
        snap["node_id"] = info.node_id
        snap["moniker"] = info.moniker
    return snap


@view("launches")
def _launches_view(node, n: int = 128) -> dict:
    """The device observatory (opt-in, `launches=N`): the newest N
    LaunchLedger records — one per device launch, with backend, mesh
    width, useful/padded/cached rows, stage durations, transfer bytes,
    compile-cache disposition, consumer mix, and the exemplar trace id —
    plus the per-kind rollup `tools/device_report.py` renders. The
    ledger is process-wide (the launch-producing stacks are process
    singletons), like the `profile` view's profiler."""
    from tendermint_tpu.telemetry import launchlog

    records = launchlog.LAUNCHLOG.recent(max(1, int(n)))
    return {
        "records": records,
        "summary": launchlog.summarize(records),
    }


@view("profile")
def _profile_view(node) -> dict:
    """The contention observatory: sampler snapshot (per-subsystem
    on-CPU/blocked + per-thread table), top-contended ranked locks with
    site attribution, and every queue wait the node measures folded
    into one table (`tools/contention_report.py` input)."""
    from tendermint_tpu.telemetry.profiler import PROFILER
    from tendermint_tpu.utils import lockrank

    return {
        "profiler": PROFILER.snapshot(top_stacks=50),
        "locks": lockrank.contention_snapshot(),
        "queues": queue_wait_summary(node),
    }


# -- queue-wait unification ---------------------------------------------------


def _quantile(snap: dict, q: float) -> float:
    """histogram_quantile over one snapshot dict (the registry child's
    interpolation, usable on `samples()` output)."""
    if snap["count"] == 0:
        return float("nan")
    rank = q * snap["count"]
    prev_ub, prev_cum = 0.0, 0
    for ub, cum in snap["buckets"]:
        if cum >= rank:
            if ub == math.inf or ub == "+Inf":
                return prev_ub
            width = float(ub) - prev_ub
            in_bucket = cum - prev_cum
            if in_bucket == 0:
                return float(ub)
            return prev_ub + width * (rank - prev_cum) / in_bucket
        prev_ub, prev_cum = float(ub) if ub != "+Inf" else prev_ub, cum
    return prev_ub


def _hist_rows(name: str) -> dict[str, dict]:
    """label-tuple -> {count, total_s, p50_ms, p99_ms} for one
    histogram family ('' key for the unlabeled child)."""
    from tendermint_tpu.telemetry import REGISTRY

    fam = REGISTRY.get(name)
    if fam is None:
        return {}
    out: dict[str, dict] = {}
    for values, snap in fam.samples():
        if not isinstance(snap, dict) or snap.get("count", 0) == 0:
            continue
        key = "/".join(values) if values else ""
        out[key] = {
            "count": snap["count"],
            "total_s": round(snap["sum"], 6),
            "p50_ms": round(_quantile(snap, 0.5) * 1e3, 3),
            "p99_ms": round(_quantile(snap, 0.99) * 1e3, 3),
        }
    return out


def queue_wait_summary(node=None) -> dict:
    """Every queue wait the node already measures, one table: dispatch
    launch queues, coalescer windows (per consumer), mempool ingress
    admission, the consensus msg-queue drain, and p2p send queues —
    the subsystem keys line up with the profiler vocabulary so the
    report can join them."""
    out = {
        "dispatch": _hist_rows("tendermint_dispatch_queue_wait_seconds"),
        "coalescer": _hist_rows("tendermint_batcher_wait_seconds"),
        "ingress": _hist_rows("tendermint_mempool_admission_seconds"),
        "consensus": {
            k: v
            for k, v in _hist_rows("tendermint_vote_stage_seconds").items()
            if k == "drain"
        },
        "p2p_send": _hist_rows("tendermint_p2p_send_wait_seconds"),
    }
    # live depths complete the wait picture (a deep-but-fast queue and
    # a shallow-but-slow one read very differently)
    depths: dict[str, object] = {}
    switch = getattr(node, "switch", None)
    if switch is not None:
        try:
            depths["p2p_send_frames"] = switch.send_queue_depth_total()
        except Exception:
            pass
    mem = getattr(node, "mempool", None)
    batcher = getattr(mem, "_ingress", None)
    if batcher is not None and hasattr(batcher, "stats"):
        try:
            depths["ingress"] = batcher.stats()
        except Exception:
            pass
    verifier = getattr(getattr(node, "consensus", None), "verifier", None)
    if verifier is not None and hasattr(verifier, "stats"):
        try:
            depths["coalescer"] = verifier.stats()
        except Exception:
            pass
    if depths:
        out["depths"] = depths
    return out
