"""Span-timeline persistence: a bounded JSONL ring under the data dir.

The in-memory tracer ring (`telemetry/tracer.py`) dies with the
process, which is exactly when a post-mortem needs it — a node that
crashed mid-height restarts with an empty timeline and `dump_telemetry`
can no longer show what the final rounds looked like. `SpanLog` appends
every completed span as one JSON line to `$home/data/spans.jsonl`,
compacting in place to the newest `capacity` spans whenever the file
doubles past it (a ring with write-amplification 2, no rotation files
to manage). On boot the node replays the persisted window back into the
tracer — tagged `restored: true` — so `dump_telemetry` serves the
pre-restart timeline immediately.

Fsync is deliberately NOT called per span: spans are forensic, not
consensus-critical state (the WAL owns durability); a crash may lose
the last few lines and that is the right trade for a hot-path sink.
"""

from __future__ import annotations

import json
import os
import threading

from tendermint_tpu.telemetry.tracer import Span, Tracer

DEFAULT_CAPACITY = 4096


class SpanLog:
    """Append-only JSONL span sink with in-place compaction."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY) -> None:
        self.path = path
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._count = self._count_lines()
        self._fh = open(path, "a", encoding="utf-8")
        self._closed = False

    def _count_lines(self) -> int:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

    def load(self) -> list[dict]:
        """The newest `capacity` persisted spans (oldest first). Lines
        that fail to parse — a torn final write from a crash — are
        skipped, not fatal."""
        out: list[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines[-self.capacity :]:
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "name" in d:
                out.append(d)
        return out

    def append(self, span: Span) -> None:
        if self._closed:
            return
        line = json.dumps(span.to_dict(), separators=(",", ":"))
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            self._count += 1
            if self._count > 2 * self.capacity:
                self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the file to its newest `capacity` lines via a temp
        file + atomic rename (a crash mid-compaction leaves either the
        old ring or the new one, never a torn file)."""
        self._fh.close()
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                tail = f.readlines()[-self.capacity :]
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.writelines(tail)
            os.replace(tmp, self.path)
            self._count = len(tail)
        finally:
            self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                self._fh.close()
            except OSError:
                pass


def persist_spans(
    tracer: Tracer, path: str, capacity: int = DEFAULT_CAPACITY
) -> SpanLog:
    """Boot-time wiring: replay the persisted window into `tracer`
    (attr `restored: true` marks pre-restart spans in `dump_telemetry`)
    and THEN install the log as one of the tracer's sinks — replay must
    not re-append what the file already holds. Sinks are additive
    (`Tracer.add_sink`) so multi-node-in-process harnesses keep one
    span log per node; each log then holds the process-wide span
    stream, which `tools/trace_timeline.py` dedupes on merge."""
    log = SpanLog(path, capacity=capacity)
    for d in log.load():
        attrs = dict(d.get("attrs") or {})
        attrs.setdefault("restored", True)
        try:
            tracer.add(d["name"], float(d["start"]), float(d["end"]), **attrs)
        except Exception:
            continue
    tracer.add_sink(log.append)
    return log
