"""Process-level resource telemetry: RSS, fds, threads, GC pauses.

The whole-node observability floor under the contention observatory
(`telemetry/profiler.py`): before attributing time between subsystems,
an operator needs to know whether the *process* is healthy — resident
set growth, fd leaks, thread-count creep, and the stop-the-world GC
pauses that show up in consensus latency tails without any lock or
queue being at fault.

Exported through the catalog (`telemetry/metrics.py`):

* ``tendermint_process_rss_bytes`` / ``_open_fds`` / ``_threads`` —
  callback gauges read at scrape time only (`/proc/self` on Linux,
  `resource.getrusage` fallback elsewhere); idle cost is zero.
* ``tendermint_process_gc_pause_seconds`` +
  ``tendermint_process_gc_collections_total{gen}`` — a `gc.callbacks`
  hook stamps `perf_counter` across each collection. CPython invokes
  the callbacks on whichever thread triggered the collection, start
  and stop paired on that thread, and collections never overlap, so a
  single module-global stamp is race-free. Installed idempotently by
  ``install_gc_telemetry()`` (node start / tests), ~100 ns per
  collection when installed.
"""

from __future__ import annotations

import gc
import os
import threading
import time

_PAGE_SIZE = 4096
try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # pragma: no cover - exotic libc
    pass


def rss_bytes() -> float:
    """Resident set size. `/proc/self/statm` field 2 on Linux; the
    `ru_maxrss` high-water mark (kB) as the best-effort fallback."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return float(int(f.read().split()[1]) * _PAGE_SIZE)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
    except Exception:
        return 0.0


def open_fds() -> float:
    """Open file descriptors (sockets, WAL handles, device fds)."""
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


def thread_count() -> float:
    return float(threading.active_count())


# -- GC pause timing ----------------------------------------------------------

_installed = False
_install_lock = threading.Lock()
_gc_started_at: float | None = None


def _gc_callback(phase: str, info: dict) -> None:
    global _gc_started_at
    if phase == "start":
        _gc_started_at = time.perf_counter()
        return
    started = _gc_started_at
    _gc_started_at = None
    from tendermint_tpu.telemetry import metrics as _m

    _m.PROCESS_GC_COLLECTIONS.labels(gen=str(info.get("generation", "?"))).inc()
    if started is not None:
        _m.PROCESS_GC_PAUSE.observe(time.perf_counter() - started)


def install_gc_telemetry() -> bool:
    """Idempotently hook `gc.callbacks`; returns True when the hook is
    (now) installed. Never uninstalled — the hook is process-lifetime
    cheap and a second install is a no-op."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        gc.callbacks.append(_gc_callback)
        _installed = True
        return True
