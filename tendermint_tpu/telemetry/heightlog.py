"""Per-height critical-path ledger: one structured record per committed
height — the finality measurement substrate.

Metrics say *how long* a height took in aggregate; spans say how long
one phase took; neither answers the question the pipelined-consensus
work (ROADMAP item 3) starts from: **for height H, where did the time
go, and which stage was the bottleneck?** The `HeightLedger` answers it
with one record per height, assembled by `consensus/state.py` at
finalize from timings the node already measures (phase transitions,
the apply stopwatch, the verify/hash/coalescer/dispatch histograms —
no new per-call plumbing):

* phase-transition durations (NewHeight → Propose → Prevote →
  Precommit → Commit → Applied), each with a wait-vs-work split
  (work = device verify+hash seconds that elapsed during the phase);
* commit-to-commit gap (`finality_s`) — the user-facing number;
* the cross-height pipeline's accounting: `pipelined` (the apply ran
  as a dispatch handle under H+1's voting) and `apply_overlap_s` (the
  share of the apply that ran concurrently — subtract it from the
  phase sum when reconciling against the gap, since overlapped apply
  time did not extend the height);
* **critical-path attribution**: which of {proposal wait, slowest-vote
  gather, commit wait, coalescer flush wait, dispatch launch, ABCI
  apply, Merkle hash} dominated the height;
* the **laggard validator**: whose vote arrived latest (from the
  per-peer vote-arrival rollup below).

Storage follows `telemetry/spanlog.py`: a bounded in-memory ring plus
an optional JSONL file under the data dir, compacted in place to the
newest `capacity` records whenever it doubles past it; the persisted
tail is reloaded on boot so `/health`'s finality window and
`dump_telemetry?heights=N` survive restarts. `tools/finality_report.py`
merges N nodes' ledgers into a per-height waterfall.

Ledgers register themselves in a process-wide set (mirroring the
FLIGHT/TRACER conventions) so flight-recorder dumps can include the
last K height records of every live ledger, and `dump_all()` writes a
stand-alone forensic dump next to the flight recorder's.

Registry-derived work numbers are process-global: the
multi-node-in-process harnesses see cross-node sums in the work split
(documented approximation); the wall-clock phase durations and the
critical-path label are per-node exact.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref

DEFAULT_CAPACITY = 512
# vote-arrival delays are clamped into [0, MAX_ARRIVAL_S]: a byzantine
# validator controls its vote timestamps, and an absurd value must not
# poison the laggard attribution or the max gauge
MAX_ARRIVAL_S = 60.0

_REG_LOCK = threading.Lock()
_LEDGERS: "weakref.WeakSet[HeightLedger]" = weakref.WeakSet()
_DUMP_SEQ = 0


class HeightLedger:
    """Bounded ring of per-height records + optional JSONL persistence."""

    def __init__(
        self,
        path: str | None = None,
        capacity: int = DEFAULT_CAPACITY,
        node_id: str = "",
    ) -> None:
        self.path = path
        self.capacity = max(1, capacity)
        self.node_id = node_id
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._fh = None
        self._count = 0
        self._closed = False
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            for rec in self._load_file():
                self._ring.append(rec)
            self._ring = self._ring[-self.capacity :]
            self._count = len(self._ring)
            self._fh = open(path, "a", encoding="utf-8")
        with _REG_LOCK:
            _LEDGERS.add(self)

    def _load_file(self) -> list[dict]:
        """The newest `capacity` persisted records (oldest first); torn
        final lines from a crash are skipped, not fatal."""
        out: list[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines[-self.capacity :]:
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "height" in d:
                out.append(d)
        return out

    # -- recording -----------------------------------------------------------

    def record(self, rec: dict) -> dict:
        """Stamp and append one height record; must never fail the
        committing caller."""
        if self.node_id and "node" not in rec:
            rec["node"] = self.node_id
        with self._lock:
            if self._closed:
                return rec
            self._ring.append(rec)
            if len(self._ring) > self.capacity:
                del self._ring[: len(self._ring) - self.capacity]
            if self._fh is not None:
                try:
                    self._fh.write(
                        json.dumps(rec, separators=(",", ":")) + "\n"
                    )
                    self._fh.flush()
                    self._count += 1
                    if self._count > 2 * self.capacity:
                        self._compact_locked()
                except (OSError, ValueError):
                    pass
        return rec

    def _compact_locked(self) -> None:
        """Rewrite the file to its newest `capacity` lines via tmp +
        atomic rename (spanlog's compaction discipline)."""
        self._fh.close()
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                tail = f.readlines()[-self.capacity :]
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.writelines(tail)
            os.replace(tmp, self.path)
            self._count = len(tail)
        finally:
            self._fh = open(self.path, "a", encoding="utf-8")

    # -- reads ---------------------------------------------------------------

    def recent(self, n: int | None = None, height: int | None = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if height is not None:
            recs = [r for r in recs if r.get("height") == height]
        if n is not None:
            recs = recs[-n:]
        return recs

    def last(self) -> dict | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def finality_window(self, n: int) -> list[float]:
        """The last `n` commit-to-commit gaps (seconds) — the rolling
        window the health SLO evaluates."""
        out = [
            r["finality_s"]
            for r in self.recent(n)
            if isinstance(r.get("finality_s"), (int, float))
        ]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class VoteArrivalRollup:
    """Per-peer vote-arrival latency (vote timestamp → local arrival),
    bounded by the live peer set. Exported low-cardinality — the
    aggregate histogram + worst-peer gauge live in the metric catalog
    (`tendermint_consensus_vote_arrival_*`), per-peer detail is served
    by `dump_telemetry` only (peer-id cardinality, same discipline as
    `tendermint_p2p_send_queue_depth`)."""

    MAX_PEERS = 512

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._peers: dict[str, list] = {}  # peer_id -> [count, sum, max]

    def observe(self, peer_id: str, delay_s: float) -> None:
        with self._lock:
            st = self._peers.get(peer_id)
            if st is None:
                if len(self._peers) >= self.MAX_PEERS:
                    return  # bounded: a peer-id flood cannot grow this
                st = self._peers[peer_id] = [0, 0.0, 0.0]
            st[0] += 1
            st[1] += delay_s
            if delay_s > st[2]:
                st[2] = delay_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                pid: {
                    "count": st[0],
                    "mean_ms": round(st[1] / st[0] * 1e3, 3) if st[0] else 0.0,
                    "max_ms": round(st[2] * 1e3, 3),
                }
                for pid, st in self._peers.items()
            }

    def max_delay(self) -> float:
        with self._lock:
            return max((st[2] for st in self._peers.values()), default=0.0)


# -- stitched work totals -----------------------------------------------------


def work_totals() -> dict:
    """Process-wide device-work stopwatch totals, stitched from the
    histograms the verify spine already exports (VerifyHandle launch
    latency, VerifyCoalescer waits, dispatch-queue waits) — the ledger
    deltas these at phase boundaries instead of adding per-call
    plumbing. Sums are across label children (all backends/consumers/
    queues)."""
    from tendermint_tpu.telemetry import metrics as _m

    return {
        "verify": _m.VERIFY_SECONDS.sum_total(),
        "hash": _m.HASH_SECONDS.sum_total(),
        "coalescer": _m.BATCHER_WAIT.sum_total(),
        "dispatch": _m.DISPATCH_QUEUE_WAIT.sum_total(),
    }


# -- process-wide registry ----------------------------------------------------


def ledgers() -> list[HeightLedger]:
    with _REG_LOCK:
        return list(_LEDGERS)


def recent_records(k: int = 32) -> list[dict]:
    """The newest `k` records across every live ledger (commit-time
    order) — what flight-recorder dumps embed so a post-mortem carries
    the heights leading into the fault."""
    out: list[dict] = []
    for led in ledgers():
        out.extend(led.recent(k))
    out.sort(key=lambda r: (r.get("t_commit", 0.0), r.get("height", 0)))
    return out[-k:]


def dump_all(dir: str, reason: str = "manual") -> str | None:
    """Atomically write every live ledger's ring as one JSON file under
    `dir` (tmp + rename, flightrec's discipline); returns the path, or
    None when nothing could be written. Never raises — forensics must
    not mask the fault being dumped."""
    global _DUMP_SEQ
    if not dir:
        return None
    try:
        os.makedirs(dir, exist_ok=True)
        with _REG_LOCK:
            _DUMP_SEQ += 1
            seq = _DUMP_SEQ
        safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)[:48]
        path = os.path.join(dir, f"heightledger-{safe}-{seq}.json")
        tmp = path + ".tmp"
        payload = {
            "reason": reason,
            "dumped_at": time.time(),
            "ledgers": [
                {"node": led.node_id, "records": led.recent()}
                for led in ledgers()
                if len(led)
            ],
        }
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:
        return None
