"""Gossip observatory: per-peer/channel/kind traffic + redundancy rollups.

The fifth observatory (finality PR 11, contention PR 12, device PR 13,
tracing PR 7), pointed at the one hot path none of them measure: the
gossip network. `p2p/connection.py` counts only aggregate frame bytes,
and every duplicate vote/part/tx/evidence dedups silently — at the
committee scales ROADMAP items 3/5/6 target, that invisible over-gossip
is exactly what dominates.

House pattern, unchanged from PRs 12/13:

* **Instrument at existing seams.** One `GossipRollup` per node lives
  on its Switch; the MConnection send/recv loops call `record()` where
  frames already pass (the `on_traffic` hook `Peer` wires with the
  remote id), the consensus state's duplicate-add branches and the
  mempool/evidence dedup sites call `redundant()`, and successful
  vote/part adds call `first_seen()`. Accounting observes frames; it
  NEVER touches them — the wire format stays byte-identical (golden
  test in tests/test_gossiplog.py).
* **Dump-only cardinality.** Exported series are bounded by
  construction: `channel` and `kind` come from the fixed vocabularies
  in telemetry/metrics.py (GOSSIP_CHANNELS / GOSSIP_KINDS), never peer
  ids or heights. Per-peer tables and first-seen stamps are served
  ONLY through `dump_telemetry?gossip=1` (telemetry/views.py).
* **A report tool names the top waste source.** `tools/gossip_report.py`
  merges N nodes' dumps into the per-channel bandwidth waterfall, the
  per-kind redundancy ranking, and the region-to-region propagation
  matrix, ending in a fix-first verdict keyed to ROADMAP items 3/5/6.

Bounded tables (a byzantine peer cannot grow memory): at most
`MAX_PEERS` per-peer rows (overflow folds into a synthetic "~overflow"
row), first-seen stamps for the newest `MAX_FIRST_HEIGHTS` heights with
a per-height entry cap. Locking mirrors `heightlog.VoteArrivalRollup`:
one plain leaf mutex, held only over dict surgery, never across
callbacks.

Knob: `TENDERMINT_TPU_GOSSIPLOG=0` disables the rollup at construction
(the sampled-out configuration — the bench's off half and the interop
test's plain node). Disabled means the p2p loops get no hook at all:
zero per-frame overhead, not an early return.
"""

from __future__ import annotations

import os
import threading
import time

from tendermint_tpu.telemetry import metrics as _metrics

# -- classification -----------------------------------------------------------
#
# Channel ids and first-byte message tags are the stable constants of
# every reactor's wire vocabulary (consensus/mempool/evidence/
# blockchain/statesync/lightclient/pex). They are mirrored here as ONE
# static table so classification is bounded by construction and needs
# no import-order handshake with the reactors;
# tests/test_gossiplog.py::test_kind_table_matches_reactors cross-checks
# this table against the reactor modules' own constants, so drift fails
# tier-1 instead of silently classifying as "other".

CHANNEL_NAMES: dict[int, str] = {
    0x00: "pex",
    0x20: "cns_state",
    0x21: "cns_data",
    0x22: "cns_vote",
    0x23: "cns_votebits",
    0x30: "mempool",
    0x38: "evidence",
    0x40: "blockchain",
    0x60: "statesync",
    0x68: "lightclient",
    0xFF: "ctrl",
}

KIND_TAGS: dict[int, dict[int, str]] = {
    0x00: {0x01: "pex_request", 0x02: "pex_addrs"},
    0x20: {
        0x01: "new_round_step",
        0x02: "commit_step",
        0x07: "has_vote",
        0x08: "vote_set_maj23",
        0x20: "proposal_heartbeat",
    },
    0x21: {0x03: "proposal", 0x04: "proposal_pol", 0x05: "block_part"},
    0x22: {0x06: "vote"},
    0x23: {0x09: "vote_set_bits"},
    0x30: {0x01: "tx"},
    0x38: {0x01: "evidence_list"},
    0x40: {
        0x01: "block_request",
        0x02: "block_response",
        0x03: "no_block",
        0x04: "status_request",
        0x05: "status_response",
    },
    0x60: {
        0x01: "snapshots_request",
        0x02: "snapshots_response",
        0x03: "chunk_request",
        0x04: "chunk_response",
        0x05: "no_chunk",
        0x06: "commit_request",
        0x07: "commit_response",
    },
    0x68: {
        0x01: "fc_request",
        0x02: "fc_response",
        0x03: "fc_subscribe",
        0x04: "fc_announce",
    },
    0xFF: {0x01: "ping", 0x02: "pong"},
}


def channel_name(chan_id: int) -> str:
    return CHANNEL_NAMES.get(chan_id, "other")


def classify(chan_id: int, payload: bytes) -> str:
    """Message kind from the payload's leading uvarint tag (every
    reactor tag is a single byte < 0x80, so byte 0 IS the tag).
    Unknown channel or tag -> "other" — the labels stay bounded no
    matter what a peer sends."""
    if not payload:
        return "other"
    return KIND_TAGS.get(chan_id, {}).get(payload[0], "other")


def enabled_from_env() -> bool:
    return os.environ.get("TENDERMINT_TPU_GOSSIPLOG", "1") != "0"


# -- the rollup ---------------------------------------------------------------


class GossipRollup:
    """One node's gossip accounting: bounded per-peer traffic tables,
    per-kind redundancy counters, and first-seen propagation stamps.

    Thread-safe the VoteArrivalRollup way: one plain leaf lock over
    dict surgery only. Metric increments happen outside the lock (the
    registry counters carry their own synchronization)."""

    MAX_PEERS = 64
    # first-seen retention: the propagation map only needs the recent
    # window (cross-node merges subtract wall clocks per key), and a
    # byzantine height/round/index flood must not grow memory
    MAX_FIRST_HEIGHTS = 8
    MAX_FIRST_PER_HEIGHT = 2048
    _OVERFLOW = "~overflow"

    def __init__(self, enabled: bool | None = None) -> None:
        self.enabled = enabled_from_env() if enabled is None else bool(enabled)
        self._lock = threading.Lock()
        # peer_id -> {(channel, kind, dir): [msgs, bytes]}
        self._traffic: dict[str, dict[tuple, list]] = {}
        # kind -> [msgs, bytes]
        self._red: dict[str, list] = {}
        # height -> {(kind, round, index): wall-clock first-seen}
        self._first: dict[int, dict[tuple, float]] = {}

    # -- traffic (MConnection send/recv loops via Peer's on_traffic) -------

    def record(
        self, peer_id: str, direction: str, chan_id: int, payload: bytes,
        frame_len: int,
    ) -> None:
        if not self.enabled:
            return
        channel = channel_name(chan_id)
        kind = classify(chan_id, payload)
        _metrics.P2P_CHANNEL_BYTES.labels(channel=channel, dir=direction).inc(
            frame_len
        )
        _metrics.GOSSIP_MSGS.labels(kind=kind, dir=direction).inc()
        key = (channel, kind, direction)
        with self._lock:
            row = self._traffic.get(peer_id)
            if row is None:
                if len(self._traffic) >= self.MAX_PEERS:
                    peer_id = self._OVERFLOW
                    row = self._traffic.get(peer_id)
                if row is None:
                    row = self._traffic[peer_id] = {}
            st = row.get(key)
            if st is None:
                st = row[key] = [0, 0]
            st[0] += 1
            st[1] += frame_len

    # -- redundancy (the silent dedup sites) --------------------------------

    def redundant(self, kind: str, nbytes: int) -> None:
        """One duplicate delivery of `kind` that dedup'd silently before
        this observatory existed: a VoteSet exact-duplicate add, a
        PartSet already-have part, a mempool dup-cache hit on gossip
        re-arrival, an evidence-pool re-offer."""
        if not self.enabled:
            return
        _metrics.GOSSIP_REDUNDANT.labels(kind=kind).inc()
        _metrics.GOSSIP_REDUNDANT_BYTES.labels(kind=kind).inc(max(0, nbytes))
        with self._lock:
            st = self._red.get(kind)
            if st is None:
                st = self._red[kind] = [0, 0]
            st[0] += 1
            st[1] += max(0, nbytes)

    # -- propagation stamps (consensus add sites) ---------------------------

    def first_seen(
        self, kind: str, height: int, round_: int, index: int
    ) -> None:
        """Wall-clock stamp of the FIRST delivery of (kind, height,
        round, index) on this node; repeats are no-ops so the earliest
        stamp wins. `tools/gossip_report.py` subtracts these across
        nodes into the region-to-region propagation matrix."""
        if not self.enabled:
            return
        now = time.time()
        key = (kind, round_, index)
        with self._lock:
            per_h = self._first.get(height)
            if per_h is None:
                if len(self._first) >= self.MAX_FIRST_HEIGHTS:
                    oldest = min(self._first)
                    if height < oldest:
                        return  # older than the whole window: drop
                    del self._first[oldest]
                per_h = self._first[height] = {}
            if key in per_h or len(per_h) >= self.MAX_FIRST_PER_HEIGHT:
                return
            per_h[key] = now

    # -- read side ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The dump view (`dump_telemetry?gossip=1`): per-peer tables
        (dump-only cardinality), the channel/kind aggregates derived
        from them, redundancy counters, and the first-seen stamps keyed
        "kind/height/round/index"."""
        with self._lock:
            traffic = {
                pid: {f"{c}/{k}/{d}": list(st) for (c, k, d), st in row.items()}
                for pid, row in self._traffic.items()
            }
            red = {k: {"msgs": st[0], "bytes": st[1]} for k, st in self._red.items()}
            first = {
                f"{k}/{h}/{r}/{i}": t
                for h, per_h in self._first.items()
                for (k, r, i), t in per_h.items()
            }
            chans: dict[str, dict] = {}
            kinds: dict[str, dict] = {}
            for row in self._traffic.values():
                for (c, k, d), st in row.items():
                    ch = chans.setdefault(
                        c,
                        {"send_msgs": 0, "send_bytes": 0,
                         "recv_msgs": 0, "recv_bytes": 0},
                    )
                    ch[f"{d}_msgs"] += st[0]
                    ch[f"{d}_bytes"] += st[1]
                    kd = kinds.setdefault(
                        k,
                        {"send_msgs": 0, "send_bytes": 0,
                         "recv_msgs": 0, "recv_bytes": 0},
                    )
                    kd[f"{d}_msgs"] += st[0]
                    kd[f"{d}_bytes"] += st[1]
        return {
            "enabled": self.enabled,
            "peers": traffic,
            "channels": chans,
            "kinds": kinds,
            "redundant": red,
            "first_seen": first,
        }

    def headline(self) -> dict:
        """The two numbers `GET /health`'s gossip section reports (top
        redundant kind, hottest channel by total bytes) — cheap enough
        for a health probe, reported-never-folded like the SLO."""
        with self._lock:
            top_red = max(
                self._red.items(), key=lambda kv: kv[1][0], default=None
            )
            chan_bytes: dict[str, int] = {}
            for row in self._traffic.values():
                for (c, _k, _d), st in row.items():
                    chan_bytes[c] = chan_bytes.get(c, 0) + st[1]
            hot = max(chan_bytes.items(), key=lambda kv: kv[1], default=None)
        out: dict = {"enabled": self.enabled}
        if top_red is not None:
            out["top_redundant_kind"] = top_red[0]
            out["top_redundant_msgs"] = top_red[1][0]
            out["top_redundant_bytes"] = top_red[1][1]
        if hot is not None:
            out["hottest_channel"] = hot[0]
            out["hottest_channel_bytes"] = hot[1]
        return out

    # -- derived ------------------------------------------------------------

    def redundancy_factors(self) -> dict[str, float]:
        """delivered / useful per redundant kind: recv msgs of the kind
        divided by (recv - redundant). 1.0 = no waste; N = the net
        shipped every message N times. Kinds with no recv traffic fall
        back to counting redundant deliveries on top of the dedup'd
        adds themselves."""
        snap = self.snapshot()
        out: dict[str, float] = {}
        kind_of = {"vote": "vote", "block_part": "block_part",
                   "tx": "tx", "evidence": "evidence_list"}
        for kind, red in snap["redundant"].items():
            wire = snap["kinds"].get(kind_of.get(kind, kind), {})
            recv = wire.get("recv_msgs", 0)
            useful = recv - red["msgs"]
            if useful > 0:
                out[kind] = round(recv / useful, 3)
            elif red["msgs"]:
                out[kind] = float(red["msgs"] + 1)
        return out
