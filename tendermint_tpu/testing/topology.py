"""WAN topology model for the chaos harness.

Maps every in-process node to a REGION and shapes each directed link
(`LinkChaos` in `p2p/transport.py`) from an inter-region RTT /
bandwidth / jitter matrix, so a Nemesis net stops looking like
loopback and starts looking like production geography: 60–250 ms
round trips, asymmetric routes, jitter-induced reordering, finite
egress, and partitions that cut along regional seams instead of
arbitrary node sets.

The shape of `DEFAULT_RTT_MS` follows public inter-region latency
figures (order of magnitude, not a benchmark): coast-to-coast US
~60 ms, transatlantic ~80 ms, US→Asia ~130–220 ms, South America the
far corner. One-way delay is RTT/2; jitter defaults to 10% of RTT —
enough to reorder, not enough to look like loss.

`scale` multiplies every delay/jitter uniformly. Scenarios that must
stay cheap enough for tier-1 run the SAME matrix at scale 0.1–0.2
(the relative geometry — who is far from whom — is what the consensus
layer reacts to; the absolute numbers only change how long the test
takes and which timeout regime applies).

Apply with `Nemesis.set_topology(topo)` — the driver stores the
topology so links recreated by `restart()` re-inherit the shaping,
exactly like live partition flags.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.p2p.transport import LinkChaos

# Regions in canonical order; the matrix is indexed by this order.
REGIONS = ("us-east", "us-west", "eu-west", "ap-northeast", "sa-east")

# Inter-region round-trip times, milliseconds. Symmetric base figures;
# real asymmetry (routing detours) is expressed per-topology via
# `overrides`.
DEFAULT_RTT_MS: dict[tuple[str, str], float] = {}


def _seed_default_matrix() -> None:
    rows = {
        "us-east": {"us-east": 1, "us-west": 62, "eu-west": 78,
                    "ap-northeast": 168, "sa-east": 118},
        "us-west": {"us-west": 1, "eu-west": 132, "ap-northeast": 108,
                    "sa-east": 176},
        "eu-west": {"eu-west": 1, "ap-northeast": 222, "sa-east": 186},
        "ap-northeast": {"ap-northeast": 1, "sa-east": 256},
        "sa-east": {"sa-east": 1},
    }
    for a, row in rows.items():
        for b, rtt in row.items():
            DEFAULT_RTT_MS[(a, b)] = float(rtt)
            DEFAULT_RTT_MS[(b, a)] = float(rtt)


_seed_default_matrix()


@dataclass
class LinkProfile:
    """Directed link shape, physical units (the topology layer's
    vocabulary; `shape()` translates into LinkChaos knobs)."""

    rtt_ms: float = 0.0  # round trip; one-way delay = rtt/2
    jitter_ms: float = 0.0  # uniform [0, jitter) added per delivery
    bandwidth_mbps: float = 0.0  # 0 = uncapped
    loss: float = 0.0  # per-send drop probability


@dataclass
class WanTopology:
    """Node placement + inter-region matrix + per-link overrides.

    `placement[i]` is node i's region. Nodes beyond the placement list
    wrap around (round-robin), so one placement spec serves any fleet
    size. `overrides[(i, j)]` replaces the matrix-derived profile for
    the DIRECTED node pair i->j (asymmetric routes, one slow validator,
    a saturated egress)."""

    name: str = "wan"
    placement: list[str] = field(default_factory=lambda: list(REGIONS))
    rtt_ms: dict[tuple[str, str], float] = field(
        default_factory=lambda: dict(DEFAULT_RTT_MS)
    )
    jitter_frac: float = 0.10  # jitter = frac * RTT unless overridden
    bandwidth_mbps: float = 0.0  # uniform cap on every inter-region link
    loss: float = 0.0  # uniform inter-region loss
    scale: float = 1.0  # multiplies every delay/jitter (tier-1 affordability)
    overrides: dict[tuple[int, int], LinkProfile] = field(default_factory=dict)

    def region_of(self, i: int) -> str:
        return self.placement[i % len(self.placement)]

    def profile(self, i: int, j: int) -> LinkProfile:
        """Directed profile for node i -> node j."""
        ov = self.overrides.get((i, j))
        if ov is not None:
            return ov
        a, b = self.region_of(i), self.region_of(j)
        rtt = self.rtt_ms.get((a, b), 0.0)
        intra = a == b
        return LinkProfile(
            rtt_ms=rtt,
            jitter_ms=rtt * self.jitter_frac,
            bandwidth_mbps=0.0 if intra else self.bandwidth_mbps,
            loss=0.0 if intra else self.loss,
        )

    def shape(self, chaos: LinkChaos, i: int, j: int) -> None:
        """Write the i->j profile into a live LinkChaos (the hook
        `Nemesis.set_topology` / `_chaos_pair` calls). Partition flags
        are deliberately untouched — they belong to the fault timeline,
        not the geography."""
        p = self.profile(i, j)
        chaos.delay_s = (p.rtt_ms / 2.0 / 1000.0) * self.scale
        chaos.jitter_s = (p.jitter_ms / 1000.0) * self.scale
        chaos.bandwidth_bps = p.bandwidth_mbps * 1e6
        chaos.drop_prob = p.loss

    def region_groups(self, n_nodes: int) -> dict[str, set[int]]:
        """Node indices by region — the unit regional faults cut along."""
        groups: dict[str, set[int]] = {}
        for i in range(n_nodes):
            groups.setdefault(self.region_of(i), set()).add(i)
        return groups

    def partition_groups(self, n_nodes: int, cut: str) -> list[set[int]]:
        """Groups for `Nemesis.partition(*groups)` that isolate region
        `cut` from everyone else (a regional outage: the region keeps
        its intra-region links, loses the world)."""
        groups = self.region_groups(n_nodes)
        if cut not in groups:
            raise ValueError(f"region {cut!r} has no nodes (have {sorted(groups)})")
        inside = groups.pop(cut)
        outside = set().union(*groups.values()) if groups else set()
        return [inside, outside]

    # -- declarative form ---------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "placement": list(self.placement),
            "jitter_frac": self.jitter_frac,
            "bandwidth_mbps": self.bandwidth_mbps,
            "loss": self.loss,
            "scale": self.scale,
            "rtt_ms": {f"{a}|{b}": v for (a, b), v in sorted(self.rtt_ms.items())},
            "overrides": {
                f"{i}|{j}": {
                    "rtt_ms": p.rtt_ms,
                    "jitter_ms": p.jitter_ms,
                    "bandwidth_mbps": p.bandwidth_mbps,
                    "loss": p.loss,
                }
                for (i, j), p in sorted(self.overrides.items())
            },
        }

    @classmethod
    def from_dict(cls, spec: dict) -> "WanTopology":
        """Inverse of `to_dict` (the scenario schema's `topology`
        section — docs/SCENARIOS.md). Omitted fields keep defaults, so
        `{"placement": ["us-east", "eu-west"], "scale": 0.1}` is a
        complete topology."""
        topo = cls(
            name=spec.get("name", "wan"),
            placement=list(spec.get("placement", REGIONS)),
            jitter_frac=float(spec.get("jitter_frac", 0.10)),
            bandwidth_mbps=float(spec.get("bandwidth_mbps", 0.0)),
            loss=float(spec.get("loss", 0.0)),
            scale=float(spec.get("scale", 1.0)),
        )
        if "rtt_ms" in spec:
            topo.rtt_ms = {}
            for key, v in spec["rtt_ms"].items():
                a, b = key.split("|")
                topo.rtt_ms[(a, b)] = float(v)
                topo.rtt_ms.setdefault((b, a), float(v))
        for key, p in spec.get("overrides", {}).items():
            i, j = key.split("|")
            topo.overrides[(int(i), int(j))] = LinkProfile(
                rtt_ms=float(p.get("rtt_ms", 0.0)),
                jitter_ms=float(p.get("jitter_ms", 0.0)),
                bandwidth_mbps=float(p.get("bandwidth_mbps", 0.0)),
                loss=float(p.get("loss", 0.0)),
            )
        return topo


def uniform_topology(
    rtt_ms: float, jitter_frac: float = 0.10, scale: float = 1.0,
    name: str = "uniform",
) -> WanTopology:
    """Every node in its own synthetic region, every link the same RTT
    — the controlled-variable topology for timeout calibration."""
    return WanTopology(
        name=name,
        placement=["r0"],
        rtt_ms={("r0", "r0"): rtt_ms},
        jitter_frac=jitter_frac,
        scale=scale,
    )


def slow_validator_topology(
    slow: int,
    base_rtt_ms: float,
    slow_rtt_ms: float,
    n_nodes: int,
    jitter_frac: float = 0.10,
    scale: float = 1.0,
) -> WanTopology:
    """Uniform fabric with ONE far-away validator: every link touching
    node `slow` runs at `slow_rtt_ms` (both directions). The canonical
    adaptive-timeout probe — when `slow` proposes, the proposal crosses
    the slow path and the propose timeout must have learned to wait."""
    topo = uniform_topology(
        base_rtt_ms, jitter_frac=jitter_frac, scale=scale,
        name=f"slow-validator-{slow}",
    )
    p = LinkProfile(rtt_ms=slow_rtt_ms, jitter_ms=slow_rtt_ms * jitter_frac)
    for other in range(n_nodes):
        if other != slow:
            topo.overrides[(slow, other)] = p
            topo.overrides[(other, slow)] = p
    return topo
