"""Byzantine adversary drivers: the "B" in BFT, made executable.

Every chaos primitive in `testing/nemesis.py` is *benign-faulty* —
crashes, partitions, torn WALs, dying devices. These drivers are
actively MALICIOUS participants, plugged into the same `Nemesis`
harness, each modeling one attack class from the threat model
(docs/BYZANTINE.md):

* `Equivocator` — a validator that double-signs: for every vote its
  honest consensus loop casts, a conflicting vote (same height/round/
  type, different block) is signed with the raw key — bypassing the
  PrivValidator double-sign guard through the Signer seam, exactly what
  a compromised signer would do — and broadcast to all peers. Honest
  nodes must detect the pair (`ErrVoteConflictingVotes`), pool
  `DuplicateVoteEvidence`, gossip it on channel 0x38, and COMMIT it
  within a few heights: `wait_evidence_committed` is the invariant.
* `ConflictingProposer` — signs a second, different proposal for the
  same (height, round) and feeds it to a subset of peers. Splits the
  first-proposal race; safety (no fork) and liveness (rounds recover)
  must hold.
* `GarbageSigFlooder` — a non-validator peer hammering the victim's
  verify spine with forged-signature votes and forged signed-tx
  envelopes. The victim must score-ban the peer, and — the audit this
  PR exists for — the adversarial False verdicts must NEVER trip the
  CircuitBreaker into host crypto (a flood must not DoS the TPU fast
  path for everyone else).
* `LyingFastSyncPeer` — advertises a far-ahead height and serves forged
  blocks on the blockchain channel. The fast-syncing victim must reject
  the chain (commit verification), ban the liar, and keep syncing from
  honest peers.
* `FrameFuzzer` — speaks raw bytes on the wire: golden frames mutated
  by bit flips, length-field lies, truncation, and trailing garbage.
  Only the fuzzing peer may be disconnected; reader threads and nodes
  must survive arbitrary input.

All drivers are deterministic given their seed (mutations use a seeded
RNG; timing comes from the harness).
"""

from __future__ import annotations

import random
import threading
import time

from tendermint_tpu.consensus.reactor import (
    DATA_CHANNEL,
    STATE_CHANNEL,
    VOTE_CHANNEL,
    VOTE_SET_BITS_CHANNEL,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.p2p.connection import ChannelDescriptor, build_frame
from tendermint_tpu.p2p.peer import NodeInfo
from tendermint_tpu.p2p.switch import Reactor, Switch, connect_switches
from tendermint_tpu.testing.nemesis import InvariantViolation, Nemesis
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    Vote,
)
from tendermint_tpu.utils.log import kv, logger
import logging

_log = logger("byzantine")

# a fabricated "other block" for conflicting votes: any hash different
# from whatever the honest vote carried
_FAKE_HASH = b"\xbe\xef" * 16


class _SinkReactor(Reactor):
    """Claims channels so an attacker switch can SEND on them; inbound
    frames are dropped (adversaries don't follow protocols)."""

    def __init__(self, channels: list[int]) -> None:
        super().__init__()
        self._descs = [ChannelDescriptor(c, priority=1) for c in channels]
        self.received: list[tuple[int, bytes]] = []
        self.on_receive = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return self._descs

    def receive(self, chan_id: int, peer, payload: bytes) -> None:
        cb = self.on_receive
        if cb is not None:
            cb(chan_id, peer, payload)


def make_attacker_switch(
    chain_id: str, channels: list[int], name: str = "attacker"
) -> tuple[Switch, _SinkReactor]:
    """A bare switch an adversary drives by hand (no consensus state)."""
    sw = Switch(
        NodeInfo(node_id=f"{name}-{random.randrange(1 << 48):012x}",
                 moniker=name, chain_id=chain_id)
    )
    sink = _SinkReactor(channels)
    sw.add_reactor("sink", sink)
    sw.start()
    return sw, sink


# -- evidence invariants ------------------------------------------------------


def committed_evidence(net: Nemesis, node_idx: int) -> list[tuple[int, object]]:
    """(height, evidence) pairs committed in one node's block store."""
    store = net.nodes[node_idx].store
    out = []
    for h in range(max(1, getattr(store, "base", 1)), store.height + 1):
        block = store.load_block(h)
        if block is None:
            continue
        for ev in block.evidence:
            out.append((h, ev))
    return out


def wait_evidence_committed(
    net: Nemesis,
    address: bytes,
    nodes: list[int] | None = None,
    within_heights: int | None = None,
    timeout: float = 60.0,
) -> dict[int, int]:
    """Block until every listed node's store holds a committed
    `DuplicateVoteEvidence` naming `address`; returns {node: height}.
    `within_heights` additionally asserts commitment latency: the
    evidence must land no more than that many heights after the
    equivocation height it proves."""
    targets = list(nodes if nodes is not None else range(len(net.nodes)))
    deadline = time.monotonic() + timeout
    found: dict[int, int] = {}
    while time.monotonic() < deadline:
        if net.violations:
            raise InvariantViolation(net.violations[0])
        for i in targets:
            if i in found:
                continue
            for h, ev in committed_evidence(net, i):
                if (
                    isinstance(ev, DuplicateVoteEvidence)
                    and ev.address == address
                ):
                    if within_heights is not None and h - ev.height > within_heights:
                        raise InvariantViolation(
                            f"node{i}: evidence for height {ev.height} only "
                            f"committed at {h} (> {within_heights} heights late)"
                        )
                    found[i] = h
                    break
        if len(found) == len(targets):
            return found
    raise TimeoutError(
        f"evidence for {address.hex()[:12]} not committed on nodes "
        f"{sorted(set(targets) - set(found))} within {timeout}s "
        f"(found: {found}, heights: {net.heights()})"
    )


# -- the equivocator ----------------------------------------------------------


class Equivocator:
    """Drives one Nemesis validator node to double-sign.

    The node's consensus loop runs HONESTLY (it proposes, votes, and
    commits like everyone else); this driver watches its vote sets and,
    for every vote the node casts, raw-signs a CONFLICTING vote for a
    fabricated block and broadcasts it to all peers — the compromised-
    signer attack. The PrivValidator's HRS guard is bypassed via the
    Signer seam, which is the realistic threat: the guard lives in
    front of the key, an attacker with the key doesn't call it."""

    def __init__(self, net: Nemesis, index: int) -> None:
        self.net = net
        self.node = net.nodes[index]
        self.index = index
        priv = self.node.priv_validator
        if priv is None:
            raise ValueError(f"node{index} is not a validator")
        self._signer = priv._signer  # raw key access: no double-sign guard
        self.address = priv.address
        self._signed: set[tuple[int, int, int]] = set()
        self.equivocations = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Equivocator":
        self._thread = threading.Thread(
            target=self._run, name=f"equivocator-{self.index}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(0.02):
            try:
                self._equivocate_once()
            except Exception:
                # the adversary must not crash the harness; consensus
                # state reads race height transitions by design
                pass

    def _equivocate_once(self) -> None:
        cs = self.node.cs
        rs = cs.get_round_state()
        if rs.votes is None or rs.validators is None:
            return
        idx, _val = rs.validators.get_by_address(self.address)
        if idx < 0:
            return
        chain_id = cs.state.chain_id
        for type_, vs in (
            (VOTE_TYPE_PREVOTE, rs.votes.prevotes(rs.round)),
            (VOTE_TYPE_PRECOMMIT, rs.votes.precommits(rs.round)),
        ):
            if vs is None:
                continue
            own = vs.get_by_index(idx)
            if own is None:
                continue  # the honest half hasn't voted yet
            key = (own.height, own.round, type_)
            if key in self._signed:
                continue
            self._signed.add(key)
            # conflict = same (h, r, type), different block
            other = (
                BlockID(_FAKE_HASH, PartSetHeader.zero())
                if own.block_id.key() != BlockID(_FAKE_HASH, PartSetHeader.zero()).key()
                else BlockID.zero()
            )
            fake = Vote(
                validator_address=self.address,
                validator_index=idx,
                height=own.height,
                round=own.round,
                timestamp=own.timestamp + 1,
                type=type_,
                block_id=other,
            )
            fake = fake.with_signature(self._signer.sign(fake.sign_bytes(chain_id)))
            self.node.switch.broadcast(VOTE_CHANNEL, VoteMessage(fake).encode())
            self.equivocations += 1
            kv(
                _log,
                logging.INFO,
                "equivocated",
                node=self.index,
                height=own.height,
                round=own.round,
                type=type_,
            )


# -- the conflicting proposer -------------------------------------------------


class ConflictingProposer:
    """When its node is the round's proposer, signs a SECOND proposal
    for the same (height, round) with a fabricated parts header and
    sends it to half the peers — the split-the-proposal attack. Peers
    that adopt the fake first can never complete it (no parts exist),
    prevote nil, and the round must recover without a fork."""

    def __init__(self, net: Nemesis, index: int) -> None:
        self.net = net
        self.node = net.nodes[index]
        self.index = index
        priv = self.node.priv_validator
        if priv is None:
            raise ValueError(f"node{index} is not a validator")
        self._signer = priv._signer
        self._sent: set[tuple[int, int]] = set()
        self.conflicts = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ConflictingProposer":
        self._thread = threading.Thread(
            target=self._run, name=f"conflicting-proposer-{self.index}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(0.02):
            try:
                self._maybe_conflict()
            except Exception:
                pass

    def _maybe_conflict(self) -> None:
        cs = self.node.cs
        rs = cs.get_round_state()
        if rs.proposal is None or not cs.is_proposer():
            return
        key = (rs.height, rs.round)
        if key in self._sent:
            return
        self._sent.add(key)
        fake = Proposal(
            height=rs.height,
            round=rs.round,
            block_parts_header=PartSetHeader(total=1, hash=_FAKE_HASH),
            pol_round=-1,
            pol_block_id=BlockID.zero(),
            timestamp=rs.proposal.timestamp + 1,
        )
        fake = fake.with_signature(
            self._signer.sign(fake.sign_bytes(cs.state.chain_id))
        )
        peers = self.node.switch.peers()
        msg = ProposalMessage(fake).encode()
        for peer in peers[: max(1, len(peers) // 2)]:
            peer.try_send(DATA_CHANNEL, msg)
        self.conflicts += 1


# -- the garbage-signature flooder --------------------------------------------


class GarbageSigFlooder:
    """A connected-but-malicious non-validator peer pushing forged
    signatures into the victim: votes impersonating a real validator
    with random sigs (drains through the consensus vote-batch path) and
    signed-tx envelopes with corrupted sigs (drains through the mempool
    ingress windows). Tracks what the victim should do about it:
    `banned()` flips once the victim's scorer bans the attacker id."""

    def __init__(self, victim_node, chain_id: str, seed: int = 7) -> None:
        from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL

        self.victim = victim_node
        self._rng = random.Random(seed)
        self._mempool_channel = MEMPOOL_CHANNEL
        self.switch, self._sink = make_attacker_switch(
            chain_id,
            [STATE_CHANNEL, DATA_CHANNEL, VOTE_CHANNEL,
             VOTE_SET_BITS_CHANNEL, MEMPOOL_CHANNEL],
            name="flooder",
        )
        self.attacker_id = self.switch.node_info.node_id
        # connect_switches(victim, attacker): pb is the attacker's
        # handle for sending INTO the victim
        _pa, self._peer = connect_switches(victim_node.switch, self.switch)
        self.votes_sent = 0
        self.txs_sent = 0

    def flood_votes(self, n: int, impersonate_index: int = 0) -> int:
        """Forged-sig votes at the victim's live (height, round) so they
        reach the signature stage (structural checks pass, the batch
        verdict comes back False, the re-verify raises bad-sig)."""
        rs = self.victim.cs.get_round_state()
        if rs.validators is None:
            return 0
        val = rs.validators.validators[impersonate_index]
        sent = 0
        for _ in range(n):
            vote = Vote(
                validator_address=val.address,
                validator_index=impersonate_index,
                height=rs.height,
                round=rs.round,
                timestamp=self._rng.randrange(1 << 50),
                type=VOTE_TYPE_PREVOTE,
                block_id=BlockID.zero(),
                signature=bytes(self._rng.randrange(256) for _ in range(64)),
            )
            if not self._peer.try_send(VOTE_CHANNEL, VoteMessage(vote).encode()):
                break
            sent += 1
        self.votes_sent += sent
        return sent

    def flood_txs(self, n: int) -> int:
        """Forged signed-tx envelopes into the gossip ingress path."""
        from tendermint_tpu.mempool.ingress import SIGNED_TX_MAGIC
        from tendermint_tpu.mempool.reactor import encode_tx_message

        sent = 0
        for i in range(n):
            fake = (
                SIGNED_TX_MAGIC
                + bytes(self._rng.randrange(256) for _ in range(32))  # pubkey
                + bytes(self._rng.randrange(256) for _ in range(64))  # sig
                + b"flood-%d" % i
            )
            if not self._peer.try_send(
                self._mempool_channel, encode_tx_message(fake)
            ):
                break
            sent += 1
        self.txs_sent += sent
        return sent

    def banned(self) -> bool:
        return self.victim.switch.scorer.is_banned(self.attacker_id)

    def connected(self) -> bool:
        return any(p.id == self.attacker_id for p in self.victim.switch.peers())

    def reconnect(self) -> bool:
        """Try to reattach (a banned attacker must be REFUSED)."""
        try:
            _pa, self._peer = connect_switches(self.victim.switch, self.switch)
            return True
        except ValueError:
            return False

    def stop(self) -> None:
        self.switch.stop()


# -- the lying fast-sync peer -------------------------------------------------


class LyingFastSyncPeer:
    """Serves a forged chain on the blockchain channel: advertises a
    far-ahead height and answers block requests with self-consistent-
    looking blocks whose commits cannot verify. A fast-syncing victim
    must reject them (`forged_block` debit -> ban) without applying a
    single forged block."""

    def __init__(self, victim_switch: Switch, chain_id: str, claim_height: int = 1000) -> None:
        from tendermint_tpu.blockchain.reactor import BLOCKCHAIN_CHANNEL

        self.claim_height = claim_height
        self.chain_id = chain_id
        self.blocks_served = 0
        self._chan = BLOCKCHAIN_CHANNEL
        self.switch, self._sink = make_attacker_switch(
            chain_id, [BLOCKCHAIN_CHANNEL], name="liar"
        )
        self.attacker_id = self.switch.node_info.node_id
        self._sink.on_receive = self._serve
        self.victim_switch = victim_switch
        _pa, self._peer = connect_switches(victim_switch, self.switch)

    def _serve(self, chan_id: int, peer, payload: bytes) -> None:
        from tendermint_tpu.blockchain.reactor import decode_message, _enc

        try:
            kind, arg = decode_message(payload)
        except Exception:
            return
        if kind == "status_request":
            peer.try_send(self._chan, _enc(0x05, self.claim_height))
        elif kind == "block_request":
            peer.try_send(self._chan, _enc(0x02, self._forged_block(arg).encode()))
            self.blocks_served += 1

    def _forged_block(self, height: int):
        """A structurally valid block whose lineage cannot verify: the
        last_commit's block id never matches the predecessor the victim
        computes, so the window linkage check fails and the server is
        treated as serving a forged chain."""
        from tendermint_tpu.types.block import Block, Commit
        from tendermint_tpu.types.tx import Txs

        last_commit = Commit.empty()
        if height > 1:
            fake_vote = Vote(
                validator_address=b"\x01" * 20,
                validator_index=0,
                height=height - 1,
                round=0,
                timestamp=1,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=BlockID(_FAKE_HASH, PartSetHeader(total=1, hash=_FAKE_HASH)),
                signature=b"\x02" * 64,
            )
            last_commit = Commit(
                block_id=fake_vote.block_id, precommits=[fake_vote]
            )
        return Block.make_block(
            height=height,
            chain_id=self.chain_id,
            txs=Txs([b"forged"]),
            last_commit=last_commit,
            last_block_id=BlockID(_FAKE_HASH, PartSetHeader(total=1, hash=_FAKE_HASH)),
            time=height,
            validators_hash=_FAKE_HASH[:20],
            app_hash=b"",
        )

    def banned(self) -> bool:
        return self.victim_switch.scorer.is_banned(self.attacker_id)

    def stop(self) -> None:
        self.switch.stop()


# -- the frame fuzzer ---------------------------------------------------------


def mutate_frame(frame: bytes, rng: random.Random) -> bytes:
    """One deterministic wire mutation: bit flip, truncation, length-
    field lie, duplication, or trailing garbage — the same corpus the
    tier-1 codec fuzz test uses (`tests/test_frame_fuzz.py`)."""
    mode = rng.randrange(6)
    b = bytearray(frame)
    if mode == 0 and b:  # single bit flip
        i = rng.randrange(len(b))
        b[i] ^= 1 << rng.randrange(8)
        return bytes(b)
    if mode == 1 and len(b) > 1:  # truncate
        return bytes(b[: rng.randrange(1, len(b))])
    if mode == 2:  # trailing garbage
        return bytes(b) + bytes(rng.randrange(256) for _ in range(rng.randrange(1, 16)))
    if mode == 3:  # length-field lie: prepend a huge uvarint length
        from tendermint_tpu.codec.binary import encode_uvarint

        return encode_uvarint(rng.randrange(1, 3)) + encode_uvarint(
            1 << rng.randrange(20, 40)
        ) + bytes(b[:4])
    if mode == 4 and b:  # splice two halves reversed
        k = rng.randrange(len(b))
        return bytes(b[k:] + b[:k])
    return bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64)))  # pure noise


class FrameFuzzer:
    """Feeds mutated frames straight into a victim switch's reader as a
    registered peer. The victim will (rightly) disconnect the fuzzing
    identity on the first offense; `run()` transparently reconnects
    under fresh identities until `n_frames` have been delivered. A
    banned identity is never readmitted — `rejected_reconnects` counts
    the bans working."""

    def __init__(self, victim_switch: Switch, chain_id: str, seed: int = 1234) -> None:
        self.victim_switch = victim_switch
        self.chain_id = chain_id
        self.rng = random.Random(seed)
        self._endpoint = None
        self._identity = 0
        self.frames_sent = 0
        self.reconnects = 0
        self.rejected_reconnects = 0

    def _connect(self) -> bool:
        from tendermint_tpu.p2p.transport import pipe_pair

        ea, eb = pipe_pair()
        info = NodeInfo(
            node_id=f"fuzzer-{self._identity:06d}",
            moniker="fuzzer",
            chain_id=self.chain_id,
        )
        self._identity += 1
        try:
            self.victim_switch.add_peer_endpoint(info, ea, outbound=False)
        except ValueError:
            self.rejected_reconnects += 1
            return False
        # drain the victim's outbound gossip so its send loop never
        # blocks on us (an adversary that stops reading is just a slow
        # peer; that's not what this driver tests)
        def _drain(endpoint=eb):
            try:
                while True:
                    endpoint.recv()
            except Exception:
                pass

        threading.Thread(target=_drain, daemon=True).start()
        self._endpoint = eb
        self.reconnects += 1
        return True

    def golden_frames(self) -> list[bytes]:
        """Valid frames to mutate: a spread of real channel ids and
        payload shapes (the victim's claimed channels + unknown ones)."""
        payloads = [b"", b"\x01", b"\x06" + b"\x00" * 40, bytes(range(32))]
        frames = []
        for chan in (STATE_CHANNEL, DATA_CHANNEL, VOTE_CHANNEL, 0x51, 0x38):
            for p in payloads:
                frames.append(build_frame(chan, p))
        return frames

    def run(self, n_frames: int = 10_000) -> int:
        """Deliver `n_frames` mutated frames; returns how many were
        actually written before any final disconnect."""
        golden = self.golden_frames()
        sent = 0
        while sent < n_frames:
            if self._endpoint is None and not self._connect():
                # every fresh identity refused (unlikely: ids rotate);
                # back off and retry
                time.sleep(0.01)
                continue
            frame = mutate_frame(self.rng.choice(golden), self.rng)
            try:
                self._endpoint.send(frame)
                sent += 1
            except Exception:
                self._endpoint = None  # victim dropped us; reincarnate
        self.frames_sent += sent
        return sent

    def stop(self) -> None:
        if self._endpoint is not None:
            try:
                self._endpoint.close()
            except Exception:
                pass


# -- the forged-FullCommit server ---------------------------------------------


def forge_fullcommit(honest_fc, compromised_priv, chain_id: str):
    """A forged FullCommit at an already-committed height: a forged
    header (wrong app_hash) carrying ONE genuine precommit — the
    compromised validator double-signing the forged block — and no
    other signatures. Certification must reject it (a single validator
    can never be its own +2/3 quorum), and the genuine double-sign is
    exactly the slashable proof `extract_double_sign_evidence` mines
    out of the rejection (the PR 9 attribution pipeline on the read
    path)."""
    from dataclasses import replace as _replace

    from tendermint_tpu.certifiers.certifier import FullCommit
    from tendermint_tpu.types.block import Commit

    forged_header = _replace(honest_fc.header, app_hash=b"\xde\xad\xbe\xef" * 5)
    forged_bid = BlockID(
        forged_header.hash(),
        PartSetHeader(total=1, hash=forged_header.hash()[:20]),
    )
    vals = honest_fc.validators
    idx, _val = vals.get_by_address(compromised_priv.address)
    if idx < 0:
        raise ValueError("compromised validator not in the honest valset")
    round_ = honest_fc.commit.round()
    honest_pc = honest_fc.commit.precommits[idx]
    vote = Vote(
        validator_address=compromised_priv.address,
        validator_index=idx,
        height=honest_fc.height(),
        round=round_,
        timestamp=honest_pc.timestamp + 1 if honest_pc is not None else 1,
        type=VOTE_TYPE_PRECOMMIT,
        block_id=forged_bid,
    )
    sig = compromised_priv._signer.sign(vote.sign_bytes(chain_id))
    precommits: list = [None] * len(vals.validators)
    precommits[idx] = vote.with_signature(sig)
    return FullCommit(
        header=forged_header,
        commit=Commit(block_id=forged_bid, precommits=precommits),
        validators=vals,
    )


class ForgedCommitPusher:
    """A malicious peer pushing forged FullCommits at a subscribing
    victim on the light-client channel (0x68) — the compromised-replica
    attack. The victim's push certifier must reject the forgery
    (`forged_fullcommit` debit -> instant ban at weight 100) AND route
    the embedded genuine double-sign into its evidence pool, from where
    0x38 gossip carries it to the validators for commitment."""

    def __init__(self, victim_node, forged_fc) -> None:
        from tendermint_tpu.lightclient.reactor import (
            LIGHTCLIENT_CHANNEL,
            _enc_fc_announce,
        )

        self.forged_fc = forged_fc
        self._chan = LIGHTCLIENT_CHANNEL
        self._frame = _enc_fc_announce(forged_fc)
        self.victim_switch = victim_node.switch
        self.switch, self._sink = make_attacker_switch(
            victim_node.genesis.chain_id, [LIGHTCLIENT_CHANNEL], name="forger"
        )
        self.attacker_id = self.switch.node_info.node_id
        _pa, self._peer = connect_switches(self.victim_switch, self.switch)

    def push(self) -> None:
        self._peer.try_send(self._chan, self._frame)

    def banned(self) -> bool:
        return self.victim_switch.scorer.is_banned(self.attacker_id)

    def stop(self) -> None:
        self.switch.stop()
