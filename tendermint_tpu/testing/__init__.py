"""In-process chaos testing harnesses (network nemesis + invariants)."""

from tendermint_tpu.testing.nemesis import (
    InvariantViolation,
    Nemesis,
    NemesisNode,
)

__all__ = ["InvariantViolation", "Nemesis", "NemesisNode"]
