"""In-process chaos testing harnesses (network nemesis + invariants,
Byzantine adversary drivers)."""

from tendermint_tpu.testing.nemesis import (
    InvariantViolation,
    Nemesis,
    NemesisNode,
)
from tendermint_tpu.testing.byzantine import (
    ConflictingProposer,
    Equivocator,
    ForgedCommitPusher,
    FrameFuzzer,
    GarbageSigFlooder,
    LyingFastSyncPeer,
    forge_fullcommit,
    wait_evidence_committed,
)

__all__ = [
    "ConflictingProposer",
    "Equivocator",
    "ForgedCommitPusher",
    "FrameFuzzer",
    "GarbageSigFlooder",
    "InvariantViolation",
    "LyingFastSyncPeer",
    "Nemesis",
    "NemesisNode",
    "forge_fullcommit",
    "wait_evidence_committed",
]
