"""In-process chaos testing harnesses (network nemesis + invariants,
Byzantine adversary drivers)."""

from tendermint_tpu.testing.nemesis import (
    InvariantViolation,
    Nemesis,
    NemesisNode,
)
from tendermint_tpu.testing.byzantine import (
    ConflictingProposer,
    Equivocator,
    FrameFuzzer,
    GarbageSigFlooder,
    LyingFastSyncPeer,
    wait_evidence_committed,
)

__all__ = [
    "ConflictingProposer",
    "Equivocator",
    "FrameFuzzer",
    "GarbageSigFlooder",
    "InvariantViolation",
    "LyingFastSyncPeer",
    "Nemesis",
    "NemesisNode",
    "wait_evidence_committed",
]
