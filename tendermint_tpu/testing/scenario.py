"""Declarative chaos scenarios over the Nemesis harness.

A scenario is DATA (a JSON-able dict, schema in docs/SCENARIOS.md):
WAN topology, node fleet + roles, an optional validator-churn policy,
an optional load profile, a fault timeline keyed by committed height
or wall time, and the expectations the run must meet. `ScenarioRunner`
executes one: builds the Nemesis fleet, shapes every link from the
topology, plays the timeline while the net commits, then derives a
finality/SLO report FROM THE HEIGHT LEDGERS (the same per-height
records `tools/finality_report.py` reads — the report is what a
production SLO dashboard would show, not harness bookkeeping) and
grades it against the expectations.

Churn: `ChurnApp` rotates the validator window deterministically at
EndBlock every K heights over a standby pool (`make_genesis
n_active=`), which exercises the two hardest rotation seams end to
end — the pipelined finalize's speculated-round REBUILD when EndBlock
changes the set (`pipeline_stats["valset_rebuilds"]`, PR 14) and the
light client's bisection BRIDGING across dense rotations
(`BisectingCertifier` over a `StoreProvider`, PR 15). Both are graded
by expectations, not assumed.

`SCENARIO_LIBRARY` ships the standing suite: flash crowd, regional
outage, slow-WAN validator, churn storm, partition-during-churn, plus
tier-1-affordable variants (`slow_wan_validator`, `churn_small`).
Heavy entries carry `"slow": True` — tests mark them accordingly and
`tools/bench_hotpath.py --section scenario_finality` runs them with
committed floors.
"""

from __future__ import annotations

import logging
import threading
import time

from tendermint_tpu.testing.nemesis import (
    InvariantViolation,
    Nemesis,
    make_genesis,
)
from tendermint_tpu.testing.topology import WanTopology
from tendermint_tpu.utils.log import kv, logger

_log = logger("scenario")


def _round_skips_total() -> float:
    """Sum of the round-skip counter across its phase labels (the
    per-phase split is diagnostic; thrash detection wants the total)."""
    from tendermint_tpu.telemetry import REGISTRY

    m = REGISTRY.get("tendermint_consensus_round_skips_total")
    if m is None:
        return 0.0
    return sum(float(snap) for _values, snap in m.samples())


# ---------------------------------------------------------------------------
# churn app
# ---------------------------------------------------------------------------


class ChurnApp:
    """KVStore app that rotates the validator window at EndBlock.

    Pool of P candidate pubkeys (index-aligned with the harness privs
    from `make_genesis`), active window of A, shifted by `shift` every
    `every` heights: epoch e's window starts at `(e * shift) % P`.
    Rotation is a pure function of height, so every node's app emits
    the identical EndBlock diff — the determinism consensus requires —
    and removed validators keep running as observers until a later
    epoch re-admits them."""

    def __new__(cls, pool: list[bytes], active: int, every: int, shift: int,
                power: int = 10):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.types import Validator

        class _App(KVStoreApp):
            def _window(self, epoch: int) -> list[int]:
                start = (epoch * shift) % len(pool)
                return [(start + t) % len(pool) for t in range(active)]

            def end_block(self, height: int) -> list[Validator]:
                super().end_block(height)
                if every <= 0 or height % every != 0:
                    return []
                epoch = height // every
                old = set(self._window(epoch - 1))
                new = set(self._window(epoch))
                changes = [Validator(pub_key=pool[i], power=0) for i in sorted(old - new)]
                changes += [Validator(pub_key=pool[i], power=power) for i in sorted(new - old)]
                return changes

        return _App()


def churn_app_factory(n_vals: int, chain_id: str, active: int, every: int,
                      shift: int):
    """An `app_factory` whose pool mirrors the deterministic
    `make_genesis(n_vals, chain_id, n_active=active)` key set, so the
    app-side rotation and the harness genesis agree by construction."""
    _, privs = make_genesis(n_vals, chain_id=chain_id, n_active=active)
    pool = [p.pub_key.data for p in privs]

    def factory():
        return ChurnApp(pool, active=active, every=every, shift=shift)

    return factory


# ---------------------------------------------------------------------------
# bisection bridging probe
# ---------------------------------------------------------------------------


class StoreProvider:
    """Light-client `Provider` over a node's block store + historical
    valset index (the lightclient reactor's `_serve_from_stores` shape,
    packaged for in-harness bisection probes). Read-only; the floor
    contract is `get_by_height(h) -> newest FullCommit <= h`."""

    def __init__(self, store, state) -> None:
        self._store = store
        self._state = state

    def _full_commit(self, height: int):
        from tendermint_tpu.certifiers.certifier import FullCommit

        meta = self._store.load_block_meta(height)
        if meta is None:
            return None
        commit = self._store.load_block_commit(height)
        if commit is None:
            commit = self._store.load_seen_commit(height)
        if commit is None:
            return None
        try:
            validators = self._state.load_validators(height)
        except Exception:
            return None
        return FullCommit(header=meta.header, commit=commit, validators=validators)

    def get_by_height(self, height: int):
        for h in range(min(height, self._store.height), 0, -1):
            fc = self._full_commit(h)
            if fc is not None:
                return fc
        return None

    def latest_commit(self):
        return self.get_by_height(self._store.height)

    def store_commit(self, fc) -> None:  # read-only source
        pass


def bisect_bridge(node, chain_id: str, genesis_privs, tip: int | None = None) -> dict:
    """Walk a light client from the GENESIS valset to the node's tip
    over its own stores — the PR 15 bridging probe a churn scenario
    must survive (every epoch boundary is a valset the skip rule has to
    ladder across). Returns the walk stats; raises on a failed walk."""
    from tendermint_tpu.lightclient.bisect import BisectingCertifier
    from tendermint_tpu.state.state import load_state
    from tendermint_tpu.types import Validator, ValidatorSet

    state = load_state(node.state_db)
    genesis_vals = ValidatorSet(
        [
            Validator(address=p.address, pub_key=p.pub_key, voting_power=10)
            for p in genesis_privs
        ]
    )
    source = StoreProvider(node.store, state)
    cert = BisectingCertifier(
        chain_id, validators=genesis_vals, height=0, source=source
    )
    target = tip if tip is not None else node.store.height
    cert.verify_to_height(target)
    return {
        "verified_to": target,
        "rounds": cert.last_walk_rounds,
        "verifies": cert.last_walk_verifies,
    }


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_ACTIONS = {
    "partition", "partition_region", "heal", "crash", "restart",
    "delay", "load_rate",
}
_TOP_KEYS = {
    "name", "description", "nodes", "n_vals", "n_active", "kind",
    "topology", "churn", "config", "load", "timeline", "run", "expect",
    "slow",
}


def validate_scenario(spec: dict) -> dict:
    """Normalize + validate a declarative scenario; returns a copy with
    defaults filled in. Raises ValueError on anything the runner would
    silently misplay (unknown keys are errors, not ignored — a typo'd
    fault that never fires is a scenario that tests nothing)."""
    if not isinstance(spec, dict):
        raise ValueError("scenario must be a dict")
    unknown = set(spec) - _TOP_KEYS
    if unknown:
        raise ValueError(f"unknown scenario keys: {sorted(unknown)}")
    if not spec.get("name"):
        raise ValueError("scenario needs a name")
    out = dict(spec)
    out.setdefault("description", "")
    nodes = int(out.get("nodes", 4))
    if nodes < 1:
        raise ValueError("nodes must be >= 1")
    out["nodes"] = nodes
    out.setdefault("n_vals", nodes)
    out.setdefault("n_active", None)
    out.setdefault("kind", "core")
    if out["kind"] not in ("core", "full"):
        raise ValueError(f"kind must be core|full, got {out['kind']!r}")
    out.setdefault("topology", None)
    if out["topology"] is not None:
        WanTopology.from_dict(out["topology"])  # shape check
    churn = out.setdefault("churn", None)
    if churn is not None:
        if int(churn.get("every", 0)) < 1 or int(churn.get("shift", 0)) < 1:
            raise ValueError("churn needs every >= 1 and shift >= 1")
        if out["n_active"] is None:
            raise ValueError("churn scenarios must set n_active (the window)")
    out.setdefault("config", {})
    out.setdefault("load", None)
    if out["load"] is not None and out["kind"] != "full":
        raise ValueError("load profiles need kind=full (mempool fleet)")
    timeline = out.setdefault("timeline", [])
    for ev in timeline:
        if ev.get("action") not in _ACTIONS:
            raise ValueError(f"unknown timeline action: {ev.get('action')!r}")
        if "at_height" not in ev and "at_s" not in ev:
            raise ValueError(f"timeline event needs at_height or at_s: {ev}")
    run = out.setdefault("run", {})
    run.setdefault("target_height", 20)
    run.setdefault("timeout_s", 120.0)
    out.setdefault("expect", {})
    out.setdefault("slow", True)
    return out


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


class _LoadFeeder:
    """Background tx feeder into one full node's mempool at a live
    mutable rate (txs/s); `load_rate` timeline events retune it — the
    flash-crowd knob."""

    def __init__(self, node, rate: float, payload: int = 64) -> None:
        from tools.loadgen import TxFactory

        self._node = node
        self.rate = rate
        self._factory = TxFactory(
            payload=payload, hot_keys=8, hot_prob=0.2, dup_prob=0.0,
            signed=False, signers=0,
        )
        self._stop = threading.Event()
        self._n = 0
        self._thread = threading.Thread(
            target=self._feed_loop, name="scenario-load", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _feed_loop(self) -> None:
        while not self._stop.is_set():
            rate = max(0.0, self.rate)
            if rate <= 0:
                time.sleep(0.05)
                continue
            tx = self._factory.make(self._n)
            self._n += 1
            try:
                self._node.node.mempool.check_tx_async(tx)
            except Exception as e:  # a full mempool is load shedding, not a bug
                kv(_log, logging.DEBUG, "load tx rejected", error=type(e).__name__)
            time.sleep(1.0 / rate)


class ScenarioRunner:
    """Executes declarative scenarios and grades the reports.

    One runner per fleet home; `run()` is synchronous and returns the
    report dict (never raises for a failed EXPECTATION — `ok: False`
    with `failures` is the verdict; it does raise for a broken SAFETY
    invariant, which is a harness-level red, not a grade)."""

    def __init__(self, home: str | None = None) -> None:
        self.home = home

    # -- internals -----------------------------------------------------------

    def _build_config(self, spec: dict):
        from tendermint_tpu.testing.nemesis import NemesisNode

        cfg = NemesisNode.default_config()
        c = spec["config"]
        if "timeout_commit_ms" in c:
            cfg.timeout_commit = int(c["timeout_commit_ms"])
        if "timeout_propose_ms" in c:
            cfg.timeout_propose = int(c["timeout_propose_ms"])
        if "timeout_prevote_ms" in c:
            cfg.timeout_prevote = int(c["timeout_prevote_ms"])
        if "timeout_precommit_ms" in c:
            cfg.timeout_precommit = int(c["timeout_precommit_ms"])
        if "skip_timeout_commit" in c:
            cfg.skip_timeout_commit = bool(c["skip_timeout_commit"])
        if "adaptive_timeouts" in c:
            cfg.adaptive_timeouts = bool(c["adaptive_timeouts"])
        return cfg

    def _build_net(self, spec: dict) -> Nemesis:
        churn = spec["churn"]
        chain_id = f"scenario-{spec['name']}"
        app_factory = None
        if churn is not None:
            app_factory = churn_app_factory(
                spec["n_vals"],
                chain_id,
                active=spec["n_active"],
                every=int(churn["every"]),
                shift=int(churn["shift"]),
            )
        if spec["kind"] == "full":
            # full nodes own a complete node Config; graft the scenario's
            # consensus tuning in via the mutator (fresh object per node)
            def mutator(config):
                config.consensus = self._build_config(spec)

            node_factory = Nemesis.full_node_factory(
                app_factory=app_factory, config_mutator=mutator
            )
            net_config = None
        else:
            node_factory = Nemesis.core_node_factory(app_factory=app_factory)
            net_config = self._build_config(spec)
        return Nemesis(
            spec["nodes"],
            n_vals=spec["n_vals"],
            n_active=spec["n_active"],
            home=self.home,
            config=net_config,
            chain_id=chain_id,
            node_factory=node_factory,
        )

    @staticmethod
    def _fire(net: Nemesis, topo: WanTopology | None, feeder, ev: dict) -> None:
        action = ev["action"]
        if action == "partition":
            net.partition(*[set(g) for g in ev["groups"]])
        elif action == "partition_region":
            if topo is None:
                raise ValueError("partition_region needs a topology")
            net.partition(*topo.partition_groups(len(net.nodes), ev["region"]))
        elif action == "heal":
            net.heal()
        elif action == "crash":
            net.crash(int(ev["node"]))
        elif action == "restart":
            net.restart(int(ev["node"]))
        elif action == "delay":
            net.delay(int(ev["i"]), int(ev["j"]), float(ev["seconds"]))
        elif action == "load_rate":
            if feeder is not None:
                feeder.rate = float(ev["rate"])

    @staticmethod
    def _finality_stats(net: Nemesis, window: int = 256) -> dict:
        vals: list[float] = []
        for node in net.nodes:
            ledger = getattr(node, "height_ledger", None) or getattr(
                getattr(node, "node", None), "height_ledger", None
            )
            if ledger is not None:
                vals.extend(ledger.finality_window(window))
        vals.sort()
        if not vals:
            return {"count": 0}
        pick = lambda q: vals[min(len(vals) - 1, int(q * len(vals)))]  # noqa: E731
        return {
            "count": len(vals),
            "p50_s": pick(0.50),
            "p95_s": pick(0.95),
            "max_s": vals[-1],
        }

    # -- entry point ---------------------------------------------------------

    def run(self, spec: dict) -> dict:
        from tendermint_tpu.telemetry import TRACER
        from tendermint_tpu.telemetry import metrics as _metrics

        spec = validate_scenario(spec)
        topo = (
            WanTopology.from_dict(spec["topology"])
            if spec["topology"] is not None
            else None
        )
        net = self._build_net(spec)
        if topo is not None:
            net.set_topology(topo)
        skips0 = _round_skips_total()
        feeder = None
        report: dict = {"scenario": spec["name"], "ok": False, "failures": []}
        t0 = time.monotonic()
        warm_height = int(spec["expect"].get("warm_height", 16))
        warm_skips: float | None = None
        try:
            with TRACER.span("scenario.run", scenario=spec["name"]):
                net.start()
                if spec["load"] is not None:
                    feeder = _LoadFeeder(
                        net.nodes[0],
                        rate=float(spec["load"].get("rate", 20.0)),
                        payload=int(spec["load"].get("payload", 64)),
                    )
                    feeder.start()
                warm_skips = self._play(net, spec, topo, feeder, warm_height)
        finally:
            if feeder is not None:
                feeder.stop()
            try:
                net.stop(check=False)
            except Exception as e:
                kv(_log, logging.WARNING, "net stop", error=type(e).__name__)
        report["elapsed_s"] = round(time.monotonic() - t0, 3)
        report["heights"] = net.heights()
        report["finality"] = self._finality_stats(net)
        report["round_skips"] = (
            _round_skips_total()
            - skips0
        )
        report["round_skips_post_warm"] = (
            None
            if warm_skips is None
            else _round_skips_total()
            - warm_skips
        )
        self._collect(net, spec, topo, report)
        self._grade(net, spec, report)
        result = "pass" if report["ok"] else "fail"
        _metrics.SCENARIO_RUNS.labels(result=result).inc()
        _metrics.SCENARIO_SECONDS.observe(report["elapsed_s"])
        kv(
            _log,
            logging.INFO,
            "scenario done",
            name=spec["name"],
            ok=report["ok"],
            heights=str(report["heights"]),
            failures=len(report["failures"]),
        )
        return report

    def _play(self, net, spec, topo, feeder, warm_height: int) -> float | None:
        """Drive the timeline while the net commits toward the target;
        returns the round-skip counter snapshot taken when the fleet
        first passed `warm_height` (the post-warm baseline)."""
        target = int(spec["run"]["target_height"])
        deadline = time.monotonic() + float(spec["run"]["timeout_s"])
        pending = sorted(
            spec["timeline"],
            key=lambda ev: (ev.get("at_height", 0), ev.get("at_s", 0.0)),
        )
        t0 = time.monotonic()
        warm_skips: float | None = None
        while True:
            if net.violations:
                raise InvariantViolation(net.violations[0])
            heights = net.heights()
            top = max(heights, default=0)
            now = time.monotonic()
            if warm_skips is None and top >= warm_height:
                warm_skips = _round_skips_total()
            fired = []
            for ev in pending:
                due_h = ev.get("at_height")
                due_s = ev.get("at_s")
                if (due_h is not None and top >= due_h) or (
                    due_s is not None and now - t0 >= due_s
                ):
                    self._fire(net, topo, feeder, ev)
                    kv(_log, logging.INFO, "timeline", action=ev["action"], at=top)
                    fired.append(ev)
            for ev in fired:
                pending.remove(ev)
            running = [
                i for i, node in enumerate(net.nodes) if node.running
            ]
            if running and all(
                net.nodes[i].store.height >= target for i in running
            ):
                return warm_skips
            if now > deadline:
                net._dump_stall_forensics()  # stacks + flight recorder
                raise TimeoutError(
                    f"scenario {spec['name']}: heights {heights} did not reach "
                    f"{target} in {spec['run']['timeout_s']}s "
                    f"({len(pending)} timeline events unfired)"
                )
            time.sleep(0.05)

    def _collect(self, net, spec, topo, report: dict) -> None:
        """Post-run observations that are not pass/fail by themselves."""
        churn = spec["churn"]
        if churn is not None:
            top = max(report["heights"], default=0)
            report["epochs"] = top // int(churn["every"])
            report["valset_rebuilds"] = sum(
                getattr(node.cs, "pipeline_stats", {}).get("valset_rebuilds", 0)
                for node in net.nodes
            )
        if spec["config"].get("adaptive_timeouts"):
            derived = [
                node.cs.timeouts.propose_timeout(0)
                for node in net.nodes
                if getattr(node, "cs", None) is not None
            ]
            report["propose_timeout_s"] = {
                "min": round(min(derived), 4),
                "max": round(max(derived), 4),
            }
        if topo is not None:
            worst = 0.0
            for i in range(len(net.nodes)):
                for j in range(len(net.nodes)):
                    if i != j:
                        p = topo.profile(i, j)
                        worst = max(worst, p.rtt_ms / 2.0 / 1000.0 * topo.scale)
            report["max_one_way_delay_s"] = round(worst, 4)
        gsum = self._gossip_summary(net)
        if gsum is not None:
            report["gossip"] = gsum

    @staticmethod
    def _gossip_summary(net) -> dict | None:
        """Fleet-wide gossip observatory rollup: per-channel bytes,
        per-kind redundancy factor (delivered / useful), top redundant
        kind — the numbers the `expect.gossip` block grades and the
        scenario_run/nemesis_demo verdict tables print. None when every
        node is sampled out (TENDERMINT_TPU_GOSSIPLOG=0)."""
        chans: dict[str, int] = {}
        kinds_recv: dict[str, int] = {}
        red: dict[str, dict] = {}
        seen = False
        for node in net.nodes:
            gossip = getattr(getattr(node, "switch", None), "gossip", None)
            if gossip is None or not gossip.enabled:
                continue
            seen = True
            snap = gossip.snapshot()
            for c, st in snap["channels"].items():
                chans[c] = chans.get(c, 0) + st["send_bytes"] + st["recv_bytes"]
            for k, st in snap["kinds"].items():
                kinds_recv[k] = kinds_recv.get(k, 0) + st["recv_msgs"]
            for k, st in snap["redundant"].items():
                r = red.setdefault(k, {"msgs": 0, "bytes": 0})
                r["msgs"] += st["msgs"]
                r["bytes"] += st["bytes"]
        if not seen:
            return None
        # redundant-kind -> wire-kind join (evidence dedups per item,
        # the wire ships lists)
        kind_of = {"evidence": "evidence_list"}
        factors: dict[str, float] = {}
        for k, r in red.items():
            recv = kinds_recv.get(kind_of.get(k, k), 0)
            useful = recv - r["msgs"]
            if useful > 0:
                factors[k] = round(recv / useful, 3)
            elif r["msgs"]:
                factors[k] = float(r["msgs"] + 1)
        top = max(red.items(), key=lambda kv: kv[1]["bytes"], default=None)
        return {
            "channel_bytes": chans,
            "redundant": red,
            "redundancy_factor": factors,
            "top_redundant_kind": top[0] if top else None,
            "total_bytes": sum(chans.values()),
        }

    def _grade(self, net, spec, report: dict) -> None:
        exp = spec["expect"]
        fails = report["failures"]
        try:
            net.check_invariants()  # no-fork + commit agreement, final word
        except InvariantViolation as e:
            fails.append(f"invariant: {e}")
        min_h = exp.get("min_height", spec["run"]["target_height"])
        live = [
            h for node, h in zip(net.nodes, report["heights"]) if node.running
        ] or report["heights"]
        if min(live, default=0) < min_h:
            fails.append(f"height floor: {report['heights']} < {min_h}")
        if "max_finality_p95_s" in exp:
            p95 = report["finality"].get("p95_s")
            if p95 is None or p95 > exp["max_finality_p95_s"]:
                fails.append(
                    f"finality p95 {p95} > {exp['max_finality_p95_s']}s"
                )
        if "min_epochs" in exp and report.get("epochs", 0) < exp["min_epochs"]:
            fails.append(
                f"epochs {report.get('epochs')} < {exp['min_epochs']}"
            )
        if "min_valset_rebuilds" in exp and report.get(
            "valset_rebuilds", 0
        ) < exp["min_valset_rebuilds"]:
            fails.append(
                f"valset rebuilds {report.get('valset_rebuilds')} < "
                f"{exp['min_valset_rebuilds']} (speculation rebuild not exercised)"
            )
        if exp.get("bisection_bridges"):
            try:
                genesis_privs = net.privs[: len(net.genesis.validators)]
                report["bisection"] = bisect_bridge(
                    net.nodes[0], net.chain_id, genesis_privs
                )
            except Exception as e:
                fails.append(f"bisection bridge: {type(e).__name__}: {e}")
        if exp.get("adaptive_above_max_delay"):
            d = report.get("propose_timeout_s", {}).get("min", 0.0)
            worst = report.get("max_one_way_delay_s", 0.0)
            if d <= worst:
                fails.append(
                    f"adaptive propose timeout {d}s did not converge above "
                    f"the injected one-way delay {worst}s"
                )
        if "max_round_skips_post_warm" in exp:
            post = report.get("round_skips_post_warm")
            if post is None or post > exp["max_round_skips_post_warm"]:
                fails.append(
                    f"round skips after warmup: {post} > "
                    f"{exp['max_round_skips_post_warm']} (timeouts thrashing)"
                )
        gexp = exp.get("gossip") or {}
        if gexp:
            # bandwidth/redundancy assertions graded from the gossip
            # observatory rollups (docs/SCENARIOS.md "expect.gossip") —
            # WAN scenarios bound gossip amplification the same way they
            # bound finality
            g = report.get("gossip")
            if g is None:
                fails.append(
                    "gossip expectations set but no rollup collected "
                    "(TENDERMINT_TPU_GOSSIPLOG sampled out?)"
                )
            else:
                if gexp.get("require_counted") and g["total_bytes"] <= 0:
                    fails.append("gossip accounting counted zero bytes")
                for kind, cap in (gexp.get("max_redundancy") or {}).items():
                    got = g["redundancy_factor"].get(kind)
                    if got is not None and got > cap:
                        fails.append(
                            f"gossip redundancy {kind} {got}x > {cap}x"
                        )
                for chan, cap_mb in (
                    gexp.get("max_channel_mbytes") or {}
                ).items():
                    got_mb = g["channel_bytes"].get(chan, 0) / 1e6
                    if got_mb > cap_mb:
                        fails.append(
                            f"gossip channel {chan} "
                            f"{got_mb:.2f} MB > {cap_mb} MB"
                        )
        report["ok"] = not fails


def run_library(names: list[str] | None = None, home: str | None = None,
                include_slow: bool = True) -> list[dict]:
    """Run named scenarios (default: whole library) and return their
    reports in order."""
    reports = []
    for name, spec in SCENARIO_LIBRARY.items():
        if names is not None and name not in names:
            continue
        if not include_slow and spec.get("slow", True):
            continue
        reports.append(ScenarioRunner(home=home).run(spec))
    return reports


# ---------------------------------------------------------------------------
# the library
# ---------------------------------------------------------------------------
#
# Delays run the real inter-region geometry at `scale` (0.1–0.2): the
# relative shape — who is far from whom, how asymmetric the routes are
# — is what consensus reacts to; full-scale RTTs only stretch the wall
# clock without changing which code paths fire. Heavy entries are
# `slow`; `slow_wan_validator` and `churn_small` stay tier-1.

SCENARIO_LIBRARY: dict[str, dict] = {
    "slow_wan_validator": {
        "name": "slow_wan_validator",
        "description": (
            "Uniform fast fabric with ONE far-away validator; adaptive "
            "timeouts must learn the slow path (converge above the "
            "injected RTT) without post-warmup round skips."
        ),
        "nodes": 4,
        "kind": "core",
        "config": {
            "adaptive_timeouts": True,
            "skip_timeout_commit": True,
            "timeout_commit_ms": 20,
        },
        "topology": {
            "name": "slow-validator",
            "placement": ["r0"],
            "rtt_ms": {"r0|r0": 30.0},
            "jitter_frac": 0.10,
            "scale": 0.2,
            "overrides": {
                "3|0": {"rtt_ms": 200.0, "jitter_ms": 20.0},
                "0|3": {"rtt_ms": 200.0, "jitter_ms": 20.0},
                "3|1": {"rtt_ms": 200.0, "jitter_ms": 20.0},
                "1|3": {"rtt_ms": 200.0, "jitter_ms": 20.0},
                "3|2": {"rtt_ms": 200.0, "jitter_ms": 20.0},
                "2|3": {"rtt_ms": 200.0, "jitter_ms": 20.0},
            },
        },
        "run": {"target_height": 30, "timeout_s": 90.0},
        "expect": {
            "min_height": 30,
            "warm_height": 18,
            "adaptive_above_max_delay": True,
            "max_round_skips_post_warm": 0,
            # gossip amplification bound: a 4-peer full mesh re-gossips
            # every vote to every peer, so each node hears each vote up
            # to ~3x (n-1); 12x means the push-gossip layer is looping
            "gossip": {"require_counted": True,
                       "max_redundancy": {"vote": 12.0}},
        },
        "slow": False,
    },
    "churn_small": {
        "name": "churn_small",
        "description": (
            "25% of a 4-validator window rotates every 4 heights over a "
            "6-key pool: the speculated round must rebuild at every "
            "epoch boundary and a light client must bisect from genesis "
            "across every rotation."
        ),
        "nodes": 6,
        "n_vals": 6,
        "n_active": 4,
        "kind": "core",
        "churn": {"every": 4, "shift": 1},
        "config": {"skip_timeout_commit": True, "timeout_commit_ms": 20},
        "run": {"target_height": 16, "timeout_s": 90.0},
        "expect": {
            "min_height": 16,
            "min_epochs": 3,
            "min_valset_rebuilds": 3,
            "bisection_bridges": True,
            # churn re-gossips votes across epoch boundaries; bound the
            # amplification but leave headroom for rotation catchup
            "gossip": {"require_counted": True,
                       "max_redundancy": {"vote": 16.0}},
        },
        "slow": False,
    },
    "flash_crowd": {
        "name": "flash_crowd",
        "description": (
            "Full-node fleet on a WAN fabric under steady load hit by a "
            "6x submit burst mid-run; finality p95 must hold an SLO "
            "through the crowd."
        ),
        "nodes": 4,
        "kind": "full",
        "topology": {
            "placement": ["us-east", "us-west", "eu-west", "us-east"],
            "scale": 0.1,
        },
        # WAN-and-load-honest timeouts: the harness's 100 ms test
        # propose ladder (1 ms/round escalation) livelocks on nil
        # prevotes once burst gossip pushes proposal delivery past it —
        # the ladder can never outgrow a sustained latency shift. A
        # deployment on this fabric runs second-scale ceilings
        # (reference default: 3000 ms propose).
        "config": {
            "timeout_propose_ms": 1000,
            "timeout_prevote_ms": 300,
            "timeout_precommit_ms": 300,
        },
        "load": {"rate": 25.0, "payload": 64},
        "timeline": [
            {"at_height": 10, "action": "load_rate", "rate": 150.0},
            {"at_height": 20, "action": "load_rate", "rate": 25.0},
        ],
        "run": {"target_height": 30, "timeout_s": 180.0},
        "expect": {
            "min_height": 30,
            "max_finality_p95_s": 3.0,
            # the burst must not amplify: tx redundancy (peers cross-
            # shipping txs the dup-cache already holds) stays bounded
            # even at 6x load, and vote gossip holds the mesh bound
            "gossip": {"require_counted": True,
                       "max_redundancy": {"vote": 12.0, "tx": 30.0}},
        },
        "slow": True,
    },
    "regional_outage": {
        "name": "regional_outage",
        "description": (
            "Five regions, one validator each; eu-west drops off the "
            "planet for a window. The surviving 4/5 quorum must keep "
            "finalizing and the healed region must catch up."
        ),
        "nodes": 5,
        "kind": "core",
        "topology": {"placement": list(
            ("us-east", "us-west", "eu-west", "ap-northeast", "sa-east")
        ), "scale": 0.1},
        "timeline": [
            {"at_height": 8, "action": "partition_region", "region": "eu-west"},
            {"at_height": 16, "action": "heal"},
        ],
        "run": {"target_height": 24, "timeout_s": 180.0},
        "expect": {
            "min_height": 24,
            # the healed region replays missed votes/parts on rejoin —
            # redundancy spikes by design, but must stay finite
            "gossip": {"require_counted": True,
                       "max_redundancy": {"vote": 24.0}},
        },
        "slow": True,
    },
    "churn_storm": {
        "name": "churn_storm",
        "description": (
            "50% of a 4-validator window rotates every 3 heights over "
            "an 8-key pool — the dense-rotation stress for speculation "
            "rebuilds and bisection ladders."
        ),
        "nodes": 8,
        "n_vals": 8,
        "n_active": 4,
        "kind": "core",
        "churn": {"every": 3, "shift": 2},
        "config": {"skip_timeout_commit": True, "timeout_commit_ms": 20},
        "run": {"target_height": 18, "timeout_s": 150.0},
        "expect": {
            "min_height": 18,
            "min_epochs": 4,
            "min_valset_rebuilds": 4,
            "bisection_bridges": True,
        },
        "slow": True,
    },
    "partition_during_churn": {
        "name": "partition_during_churn",
        "description": (
            "A minority partition lands ACROSS an epoch boundary: the "
            "majority side must rotate the valset and keep committing; "
            "the healed minority must adopt the rotated set and catch "
            "up without fork."
        ),
        "nodes": 6,
        "n_vals": 6,
        "n_active": 4,
        "kind": "core",
        "churn": {"every": 4, "shift": 1},
        "config": {"skip_timeout_commit": True, "timeout_commit_ms": 40},
        "timeline": [
            {"at_height": 6, "action": "partition",
             "groups": [[0, 1, 2, 4, 5], [3]]},
            {"at_height": 14, "action": "heal"},
        ],
        "run": {"target_height": 20, "timeout_s": 180.0},
        "expect": {
            "min_height": 20,
            "min_epochs": 4,
            "bisection_bridges": True,
        },
        "slow": True,
    },
}
