"""Nemesis: a chaos driver for multi-node in-process consensus networks.

Runs N full consensus nodes (ConsensusState + reactor + Switch, the
`tests/test_reactor.py` topology promoted to a reusable harness) in one
process and attacks them while INVARIANT CHECKERS run continuously:

* **no-fork** — every height stored by 2+ nodes has exactly one block
  hash across all block stores;
* **commit agreement** — each node's seen-commit for a height certifies
  the block it stored at that height;
* **eventual progress** — after faults clear, the network keeps
  committing (asserted by `wait_height` / `wait_progress`).

Fault primitives compose (Jepsen-nemesis style, hence the name):

* `partition(groups)` / `heal()` — switch-level link black-holing via
  runtime `LinkChaos` flags (`p2p/transport.py`); new links inherit the
  live partition, so a restarting node cannot tunnel across it;
* `delay(i, j, s)` / `duplicate(i, j, p)` — per-link latency and
  duplicate delivery (delayed sends may reorder, like a real path);
* `FuzzConfig` — probabilistic background faults on every link
  (reference `p2p/fuzz.go`), composed under the chaos wrapper;
* `crash(i)` / `restart(i)` — stop a node abruptly and rebuild it from
  its surviving stores + WAL (crash recovery is the code under test,
  not a harness feature); `crash_at_fail_point(idx)` arms the existing
  `FAIL_TEST_INDEX` machinery in soft mode so the node's consensus
  thread dies mid-persistence-step, in process;
* `truncate_wal_tail(i)` / `corrupt_wal_tail(i)` — damage the crashed
  node's WAL the way a torn write would, before restarting it;
* device fault injection (`utils/fail.py` TENDERMINT_TPU_DEVICE_FAIL /
  `set_device_fault`) — trips the resilient-dispatch circuit breaker
  (`services/resilient.py`) mid-height; the invariants then prove the
  host-fallback keeps both safety AND liveness.

Degradation cycles are asserted on the EXPORTED telemetry
(`breaker_baseline` / `assert_breaker_tripped` /
`assert_breaker_recovered` for the host-fallback ladder;
`mesh_baseline` / `assert_mesh_degraded` / `assert_mesh_restored` for
the sharded-mesh survivor re-mesh cycle a `shard<i>` fault drives; plus
`wait_telemetry_above` for counters like round skips): what an
operator's dashboard would show is what the chaos suite checks
(docs/OBSERVABILITY.md).

Forensics: chaos runs force distributed-trace sampling
(`tracectx.force_all`) so every message is attributable, and an
invariant violation dumps the flight recorder
(`telemetry/flightrec.py`) to the harness home — the dump path is
appended to the InvariantViolation message, so a red run points at its
own black box (`tools/trace_timeline.py --flight <dump> --height H`).
"""

from __future__ import annotations

import os
import threading
import time

from tendermint_tpu.p2p.peer import NodeInfo
from tendermint_tpu.p2p.switch import Switch, connect_switches
from tendermint_tpu.p2p.transport import (
    ChaosEndpoint,
    FuzzConfig,
    FuzzedEndpoint,
    LinkChaos,
)
from tendermint_tpu.utils.log import kv, logger
import logging

_log = logger("nemesis")


class InvariantViolation(AssertionError):
    """A safety invariant broke under chaos — the bug this harness hunts."""


def make_genesis(n_vals: int, chain_id: str, n_active: int | None = None):
    """Deterministic genesis + index-aligned priv validators (the
    `tests/helpers.py` fixture shape, owned here so the harness is
    importable outside the test tree).

    `n_active` caps how many of the `n_vals` keys enter the GENESIS
    valset; the rest form a standby pool for churn scenarios — their
    nodes run as non-validators until an EndBlock rotation admits them
    (returned privs stay index-aligned: valset order first, then the
    standby pool in deterministic key order)."""
    from tendermint_tpu.crypto import PrivKey
    from tendermint_tpu.types import PrivValidator, Validator, ValidatorSet
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    privs = [
        PrivValidator(PrivKey(i.to_bytes(32, "little")))
        for i in range(1, n_vals + 1)
    ]
    active = privs if n_active is None else privs[:n_active]
    vs = ValidatorSet(
        [
            Validator(address=p.address, pub_key=p.pub_key, voting_power=10)
            for p in active
        ]
    )
    by_addr = {p.address: p for p in active}
    ordered = [by_addr[v.address] for v in vs.validators] + [
        p for p in privs if p.address not in by_addr
    ]
    genesis = GenesisDoc(
        chain_id=chain_id,
        genesis_time=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(pub_key=v.pub_key, power=v.voting_power)
            for v in vs.validators
        ],
    )
    return genesis, ordered


class FaultedApplyApp:
    """KVStore app whose commit RAISES from `fail_from_height` on — the
    in-process stand-in for a breaker-faulted/corrupted ABCI apply
    landing mid-pipeline. The pipelined finalize must drain at the join
    barrier (FatalConsensusError) and halt the node with its persisted
    state still at the last honestly-applied height: the speculative
    H+1 round state never reaches disk, a signature, or a commit."""

    def __new__(cls, fail_from_height: int = 0):
        from tendermint_tpu.abci.apps import KVStoreApp

        class _App(KVStoreApp):
            def commit(self) -> object:
                if fail_from_height and self._height >= fail_from_height:
                    raise RuntimeError(
                        f"injected faulted apply at height {self._height}"
                    )
                return super().commit()

        return _App()


class ForgedHashApp:
    """KVStore app that returns a FORGED app hash from
    `fail_from_height` on — a node whose local execution diverges (the
    fork attempt the no-fork invariants must prove impossible). The
    forged node prevotes nil on every honest proposal (its state
    disagrees), and when the honest +2/3 commits anyway, its own apply
    of the honest block fails validation and halts it — the forged
    state never propagates into a committed block."""

    def __new__(cls, fail_from_height: int = 0):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.types import Result

        class _App(KVStoreApp):
            def commit(self) -> Result:
                if fail_from_height and self._height >= fail_from_height:
                    return Result(data=b"\xde\xad\xbe\xef" * 5)
                return super().commit()

        return _App()


def one_bad_app_factory(bad_index: int, bad_app_cls, n_nodes: int, **kwargs):
    """An `app_factory` for `Nemesis.full_node_factory` that hands node
    `bad_index` a misbehaving app and everyone else the honest KVStore.
    Construction order == node index (the factory is called once per
    node, in order)."""
    from tendermint_tpu.abci.apps import KVStoreApp

    counter = iter(range(n_nodes))

    def factory():
        i = next(counter)
        return bad_app_cls(**kwargs) if i == bad_index else KVStoreApp()

    return factory


class NemesisNode:
    """One rebuildable in-process node: durable stores + disposable
    runtime (consensus state, reactor, switch are rebuilt on restart;
    state DB, block store DB, app instance, and the on-disk WAL
    survive, exactly the crash-recovery contract of a real node)."""

    def __init__(
        self,
        index: int,
        genesis,
        privs,
        home: str,
        chain_id: str,
        config=None,
        verifier=None,
        hasher=None,
        app_factory=None,
    ) -> None:
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.db.kv import MemDB
        from tendermint_tpu.state import make_genesis_state

        self.index = index
        self.chain_id = chain_id
        self.genesis = genesis
        self.priv_validator = privs[index] if index < len(privs) else None
        self.config = config or self.default_config()
        self.verifier = verifier
        self.hasher = hasher
        self.state_db = MemDB()
        self.store_db = MemDB()
        # app-side persistence is the app's concern (the reference
        # Handshaker replays it back in sync); modeling a durable app
        # keeps the harness focused on consensus-side recovery
        self.app = (app_factory or KVStoreApp)()
        self.wal_path = os.path.join(home, f"node{index}", "cs.wal")
        os.makedirs(os.path.dirname(self.wal_path), exist_ok=True)
        state = make_genesis_state(self.state_db, genesis)
        state.save()
        self.running = False
        self._build()

    @staticmethod
    def default_config():
        """test_config timeouts, but PACED commits: at full test speed
        (skip_timeout_commit, 10 ms) a healthy 4-node chain commits
        ~50 heights/s — faster than one-height-at-a-time consensus
        catchup can ever walk, so a partitioned/restarted node would
        never rejoin a long-running net. ~4 heights/s leaves catchup
        (and CI machines under load) decisive headroom."""
        from tendermint_tpu.consensus.config import ConsensusConfig

        cfg = ConsensusConfig.test_config()
        cfg.timeout_commit = 250
        cfg.skip_timeout_commit = False
        # keep the deliberate pacing: measured-latency timeouts would
        # shrink the 250 ms commit wait right back to full test speed
        # and starve consensus catchup of its headroom
        cfg.adaptive_timeouts = False
        return cfg

    def _build(self) -> None:
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.blockchain.store import BlockStore
        from tendermint_tpu.consensus.reactor import ConsensusReactor
        from tendermint_tpu.consensus.state import ConsensusState
        from tendermint_tpu.consensus.ticker import TimeoutTicker
        from tendermint_tpu.evidence import EvidencePool, EvidenceReactor
        from tendermint_tpu.state.state import load_state

        from tendermint_tpu.telemetry.heightlog import HeightLedger

        state = load_state(self.state_db)
        self.store = BlockStore(self.store_db)
        self.conns = local_client_creator(self.app)()
        # finality ledger persists next to the WAL (tail reloads across
        # crash/restart; tools/finality_report.py merges the nodes')
        self.height_ledger = HeightLedger(
            path=os.path.join(os.path.dirname(self.wal_path), "heights.jsonl"),
            node_id=f"node{self.index}",
        )
        # evidence WAL survives crash/restart next to the consensus WAL
        self.evidence_pool = EvidencePool(
            wal_path=os.path.join(os.path.dirname(self.wal_path), "evidence.wal"),
            params=state.consensus_params.evidence,
            verifier=self.verifier,
            chain_id=self.chain_id,
        )
        self.cs = ConsensusState(
            config=self.config,
            state=state,
            app_conn=self.conns.consensus,
            block_store=self.store,
            priv_validator=self.priv_validator,
            wal_path=self.wal_path,
            ticker=TimeoutTicker(),
            verifier=self.verifier,
            hasher=self.hasher,
            evidence_pool=self.evidence_pool,
            heightlog=self.height_ledger,
        )
        self.reactor = ConsensusReactor(self.cs)
        self.switch = Switch(
            NodeInfo(
                node_id=f"node{self.index}",
                moniker=f"nemesis{self.index}",
                chain_id=self.chain_id,
            )
        )
        self.switch.add_reactor("consensus", self.reactor)
        self.switch.add_reactor("evidence", EvidenceReactor(self.evidence_pool))

    def start(self) -> None:
        self.switch.start()  # reactor.on_start starts the consensus loop
        self.running = True

    def stop(self) -> None:
        if self.running:
            self.switch.stop()
            self.evidence_pool.close()
            self.height_ledger.close()
            self.running = False

    def crash(self) -> None:
        """Abrupt teardown: peers cut, loop stopped, WAL left exactly as
        the last fsync'd record (no clean end-of-height marker is
        written — ConsensusState only marks committed heights, so the
        tail is whatever the 'crash' interrupted)."""
        self.stop()

    def restart(self) -> None:
        """Rebuild from surviving stores; `_catchup_replay` replays the
        WAL tail for the in-progress height before the loop starts."""
        if self.running:
            raise RuntimeError(f"node{self.index} is running; crash() first")
        self._build()
        self.start()

    @property
    def height(self) -> int:
        return self.cs.height


class FullNemesisNode:
    """One rebuildable in-process FULL node (`node.Node`): fast-sync +
    mempool + RPC + state-sync reactors under chaos, not just the
    ConsensusState core `NemesisNode` drives.

    Durable pieces survive restart exactly like a real deployment: the
    MemDB-backed state/blockstore/txindex/snapshot DBs, the app
    instance, and the on-disk WALs under `home/fullnode<i>/`. The
    runtime (Node with its switch, reactors, RPC listener) is rebuilt.
    In-process wiring: `p2p.laddr` is empty (no TCP listener) and the
    harness links switches over chaos-wrapped pipes.
    """

    def __init__(
        self,
        index: int,
        genesis,
        privs,
        home: str,
        chain_id: str,
        config=None,
        verifier=None,
        hasher=None,
        app_factory=None,
        config_mutator=None,
    ) -> None:
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.config import Config
        from tendermint_tpu.db.kv import MemDB

        self.index = index
        self.chain_id = chain_id
        self.genesis = genesis
        self.priv_validator = privs[index] if index < len(privs) else None
        self.home = os.path.join(home, f"fullnode{index}")
        os.makedirs(self.home, exist_ok=True)
        self.app = (app_factory or KVStoreApp)()
        self.verifier = verifier
        self.hasher = hasher
        self._dbs: dict[str, object] = {}
        self._memdb = MemDB
        if config is None:
            config = Config.test_config(self.home)
            config.base.moniker = f"fullnemesis{index}"
            config.p2p.laddr = ""  # harness-wired pipes, no TCP accept
            config.p2p.pex = False
            config.rpc.grpc_laddr = ""
            config.consensus = NemesisNode.default_config()
        if config_mutator is not None:
            config_mutator(config)
        self.config = config
        self.running = False
        self._build()

    def _db_provider(self, name: str):
        db = self._dbs.get(name)
        if db is None:
            db = self._dbs[name] = self._memdb()
        return db

    def _build(self) -> None:
        from tendermint_tpu.node.node import Node

        self.node = Node(
            self.config,
            genesis=self.genesis,
            priv_validator=self.priv_validator,
            app=self.app,
            db_provider=self._db_provider,
            verifier=self.verifier,
            hasher=self.hasher,
        )

    # -- the informal node interface the harness drives --------------------

    @property
    def switch(self):
        return self.node.switch

    @property
    def store(self):
        return self.node.block_store

    @property
    def cs(self):
        return self.node.consensus

    @property
    def evidence_pool(self):
        return self.node.evidence_pool

    @property
    def height(self) -> int:
        return self.node.block_store.height

    @property
    def rpc_port(self) -> int:
        return self.node.rpc_port

    def start(self) -> None:
        self.node.start()
        self.running = True

    def stop(self) -> None:
        if self.running:
            self.node.stop()
            self.running = False

    def crash(self) -> None:
        """Abrupt teardown; WALs keep whatever the last fsync wrote."""
        self.stop()

    def restart(self) -> None:
        if self.running:
            raise RuntimeError(f"fullnode{self.index} is running; crash() first")
        self._build()
        self.start()


class Nemesis:
    """N-node in-process network + fault primitives + live invariants.

    Use as a context manager: `with Nemesis(4, home=tmp) as net: ...` —
    exit stops everything and re-raises any invariant violation the
    background monitor recorded. `node_factory` swaps the node type:
    the default drives consensus cores (`NemesisNode`), pass
    `Nemesis.full_node_factory()` to drive complete `node.Node`
    instances (fast-sync + mempool + RPC + state-sync under chaos).
    """

    def __init__(
        self,
        n_nodes: int,
        n_vals: int | None = None,
        home: str | None = None,
        config=None,
        fuzz: FuzzConfig | None = None,
        chain_id: str = "nemesis-chain",
        verifier_factory=None,
        hasher_factory=None,
        monitor_interval_s: float = 0.25,
        node_factory=None,
        n_active: int | None = None,
    ) -> None:
        import tempfile

        self.chain_id = chain_id
        self.home = home or tempfile.mkdtemp(prefix="nemesis-")
        self.fuzz = fuzz
        genesis, privs = make_genesis(
            n_vals or n_nodes, chain_id=chain_id, n_active=n_active
        )
        self.genesis, self.privs = genesis, privs
        self.node_factory = node_factory or NemesisNode
        self.nodes = [
            self.node_factory(
                i,
                genesis,
                privs,
                self.home,
                chain_id,
                config=config,
                verifier=verifier_factory(i) if verifier_factory else None,
                hasher=hasher_factory(i) if hasher_factory else None,
            )
            for i in range(n_nodes)
        ]
        # (i, j) i<j -> (chaos i->j, chaos j->i); flags survive re-links
        self._links: dict[tuple[int, int], tuple[LinkChaos, LinkChaos]] = {}
        self._partition: list[set[int]] | None = None
        self._topology = None  # WanTopology; reshapes recreated links
        self._monitor_interval = monitor_interval_s
        self._monitor: threading.Thread | None = None
        self._monitor_stop = threading.Event()
        self.violations: list[str] = []

    @staticmethod
    def core_node_factory(app_factory=None):
        """A `node_factory` building consensus-core `NemesisNode`s with
        a custom ABCI app per node (e.g. the churn app rotating the
        valset at EndBlock). The factory is called once per node, in
        index order — `one_bad_app_factory` composes."""

        def factory(i, genesis, privs, home, chain_id, config=None, verifier=None, hasher=None):
            return NemesisNode(
                i,
                genesis,
                privs,
                home,
                chain_id,
                config=config,
                verifier=verifier,
                hasher=hasher,
                app_factory=app_factory,
            )

        return factory

    @staticmethod
    def full_node_factory(app_factory=None, config_mutator=None):
        """A `node_factory` building `FullNemesisNode`s; `config_mutator`
        edits each node's Config before composition (snapshot intervals,
        state-sync trust roots, ...)."""

        def factory(i, genesis, privs, home, chain_id, config=None, verifier=None, hasher=None):
            return FullNemesisNode(
                i,
                genesis,
                privs,
                home,
                chain_id,
                config=config,
                verifier=verifier,
                hasher=hasher,
                app_factory=app_factory,
                config_mutator=config_mutator,
            )

        return factory

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "Nemesis":
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(check=exc_type is None)

    def start(self) -> None:
        # chaos runs sample EVERY trace context: when an invariant
        # trips, the flight-recorder dump + span logs must attribute
        # every message in flight, not 1-in-64 of them
        from tendermint_tpu.telemetry import tracectx
        from tendermint_tpu.telemetry.flightrec import FLIGHT

        tracectx.force_all(True)
        FLIGHT.set_dump_dir(self.home)
        for node in self.nodes:
            node.start()
        for i in range(len(self.nodes)):
            for j in range(i + 1, len(self.nodes)):
                self._connect(i, j)
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="nemesis-invariants", daemon=True
        )
        self._monitor.start()

    def stop(self, check: bool = True) -> None:
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        for node in self.nodes:
            node.stop()
        from tendermint_tpu.telemetry import tracectx

        tracectx.force_all(False)
        if check:
            self.assert_invariants()

    # -- wiring --------------------------------------------------------------

    def _chaos_pair(self, i: int, j: int) -> tuple[LinkChaos, LinkChaos]:
        key = (min(i, j), max(i, j))
        if key not in self._links:
            self._links[key] = (LinkChaos(seed=key[0]), LinkChaos(seed=key[1]))
            if self._partition is not None and self._crosses_partition(i, j):
                for c in self._links[key]:
                    c.partitioned = True
            if self._topology is not None:
                # recreated links (restart) must re-inherit the WAN shape
                self._topology.shape(self._links[key][0], key[0], key[1])
                self._topology.shape(self._links[key][1], key[1], key[0])
        return self._links[key]

    def link_chaos(self, i: int, j: int) -> LinkChaos:
        """The live LinkChaos governing direction i -> j (asymmetric
        routes are two calls)."""
        pair = self._chaos_pair(i, j)
        return pair[0] if i < j else pair[1]

    def set_topology(self, topology) -> None:
        """Shape every link (delay / jitter / bandwidth, per direction)
        from a WAN topology (`testing/topology.py`). Stored so links
        recreated by `restart()` inherit the shaping, exactly like the
        live partition flags."""
        self._topology = topology
        for (i, j), (c_ij, c_ji) in self._links.items():
            topology.shape(c_ij, i, j)
            topology.shape(c_ji, j, i)
        kv(
            _log,
            logging.INFO,
            "topology applied",
            name=getattr(topology, "name", "custom"),
            links=len(self._links),
        )

    def _connect(self, i: int, j: int) -> None:
        c_ij, c_ji = self._chaos_pair(i, j)

        def wrap(ea, eb):
            if self.fuzz is not None:
                ea = FuzzedEndpoint(ea, self.fuzz)
                eb = FuzzedEndpoint(eb, self.fuzz)
            return ChaosEndpoint(ea, c_ij), ChaosEndpoint(eb, c_ji)

        connect_switches(self.nodes[i].switch, self.nodes[j].switch, wrap=wrap)

    # -- fault primitives ----------------------------------------------------

    def _crosses_partition(self, i: int, j: int) -> bool:
        assert self._partition is not None
        for group in self._partition:
            if i in group and j in group:
                return False
        return True

    def partition(self, *groups) -> None:
        """Split the network into isolated groups, e.g.
        `partition({0, 1}, {2, 3})`. Links inside a group stay clean;
        links across groups black-hole in both directions. A node in no
        listed group is isolated entirely."""
        self._partition = [set(g) for g in groups]
        for (i, j), (c_ij, c_ji) in self._links.items():
            cut = self._crosses_partition(i, j)
            c_ij.partitioned = cut
            c_ji.partitioned = cut
        kv(_log, logging.INFO, "partition", groups=str(groups))

    def heal(self) -> None:
        """Remove the partition (other per-link chaos keeps its settings)."""
        self._partition = None
        for c_ij, c_ji in self._links.values():
            c_ij.partitioned = False
            c_ji.partitioned = False
        kv(_log, logging.INFO, "heal", links=len(self._links))

    def delay(self, i: int, j: int, seconds: float, both_ways: bool = True) -> None:
        c_ij, c_ji = self._chaos_pair(i, j)
        c_ij.delay_s = seconds
        if both_ways:
            c_ji.delay_s = seconds

    def duplicate(self, i: int, j: int, prob: float, both_ways: bool = True) -> None:
        c_ij, c_ji = self._chaos_pair(i, j)
        c_ij.dup_prob = prob
        if both_ways:
            c_ji.dup_prob = prob

    def crash(self, i: int) -> None:
        self.nodes[i].crash()

    def restart(self, i: int) -> None:
        """Restart a crashed node and re-link it to every running node
        (links inherit the live partition state)."""
        node = self.nodes[i]
        node.restart()
        for j, other in enumerate(self.nodes):
            if j == i or not other.running:
                continue
            key = (min(i, j), max(i, j))
            self._links.pop(key, None)  # old endpoints died with the crash
            self._connect(*key)

    def add_node(self, node) -> int:
        """Admit a late joiner (e.g. a fresh node that will state-sync
        in): start it and link it to every running node. Links inherit
        the live partition — declare the joiner's group in `partition`
        BEFORE adding it, or it starts fully isolated."""
        i = len(self.nodes)
        self.nodes.append(node)
        if not node.running:
            node.start()
        for j, other in enumerate(self.nodes[:i]):
            if other.running:
                self._connect(j, i)
        return i

    def crash_at_fail_point(self, index: int) -> None:
        """Arm the process-wide fail-point counter (`utils/fail.py`) in
        SOFT mode: the `index`-th fail_point() call from now raises
        SimulatedCrash, killing that node's consensus thread mid-step.
        Counts are process-global — all nodes' persistence steps share
        the sequence, like the reference's kill-at-every-index matrix."""
        from tendermint_tpu.utils import fail

        fail.reset_for_testing()
        os.environ["FAIL_TEST_SOFT"] = "1"
        os.environ["FAIL_TEST_INDEX"] = str(index)

    def clear_fail_point(self) -> None:
        os.environ.pop("FAIL_TEST_INDEX", None)
        os.environ.pop("FAIL_TEST_SOFT", None)

    # -- WAL damage ----------------------------------------------------------

    def truncate_wal_tail(self, i: int, nbytes: int = 16) -> None:
        """Chop `nbytes` off the crashed node's live WAL file — the torn
        tail a mid-write crash leaves. Replay must tolerate it."""
        node = self.nodes[i]
        if node.running:
            raise RuntimeError("truncate_wal_tail on a running node")
        size = os.path.getsize(node.wal_path)
        with open(node.wal_path, "ab") as f:
            f.truncate(max(0, size - nbytes))

    def corrupt_wal_tail(self, i: int, nbytes: int = 16) -> None:
        """Flip the last `nbytes` of the crashed node's WAL (bit rot /
        torn write with garbage). The CRC framing must reject the tail."""
        node = self.nodes[i]
        if node.running:
            raise RuntimeError("corrupt_wal_tail on a running node")
        size = os.path.getsize(node.wal_path)
        if size == 0:
            return
        n = min(nbytes, size)
        with open(node.wal_path, "r+b") as f:
            f.seek(size - n)
            tail = f.read(n)
            f.seek(size - n)
            f.write(bytes(b ^ 0xFF for b in tail))

    # -- telemetry invariants ------------------------------------------------
    #
    # Chaos assertions on the EXPORTED numbers, not harness internals:
    # what an operator's dashboard would show is what the invariant
    # checks. Counters are process-global (telemetry/metrics.py), so in
    # this multi-node-per-process harness they sum across nodes —
    # baselines make the deltas per-scenario.

    @staticmethod
    def telemetry_value(name: str, **labels) -> float:
        """Current value of an exported counter/gauge series (0 when the
        series has never been touched)."""
        from tendermint_tpu.telemetry import REGISTRY

        return REGISTRY.counter_value(name, **labels)

    def breaker_baseline(self, kind: str = "verify") -> dict:
        """Snapshot the breaker telemetry before injecting a fault; pass
        to `assert_breaker_tripped` / `assert_breaker_recovered`."""
        return {
            "kind": kind,
            "trips": self.telemetry_value(
                "tendermint_breaker_transitions_total", kind=kind, to="open"
            ),
            "recoveries": self.telemetry_value(
                "tendermint_breaker_transitions_total", kind=kind, to="closed"
            ),
            "fallbacks": self.telemetry_value(
                "tendermint_device_fallback_calls_total", kind=kind
            ),
        }

    def assert_breaker_tripped(self, baseline: dict, min_trips: int = 1) -> None:
        kind = baseline["kind"]
        trips = (
            self.telemetry_value(
                "tendermint_breaker_transitions_total", kind=kind, to="open"
            )
            - baseline["trips"]
        )
        fallbacks = (
            self.telemetry_value(
                "tendermint_device_fallback_calls_total", kind=kind
            )
            - baseline["fallbacks"]
        )
        if trips < min_trips:
            raise InvariantViolation(
                f"breaker[{kind}]: expected >= {min_trips} trips via telemetry, saw {trips}"
            )
        if fallbacks <= 0:
            raise InvariantViolation(
                f"breaker[{kind}]: tripped but no fallback calls exported"
            )

    def assert_breaker_recovered(
        self, baseline: dict, min_recoveries: int = 1
    ) -> None:
        kind = baseline["kind"]
        recoveries = (
            self.telemetry_value(
                "tendermint_breaker_transitions_total", kind=kind, to="closed"
            )
            - baseline["recoveries"]
        )
        if recoveries < min_recoveries:
            raise InvariantViolation(
                f"breaker[{kind}]: expected >= {min_recoveries} recoveries "
                f"via telemetry, saw {recoveries}"
            )

    def mesh_baseline(self) -> dict:
        """Snapshot the sharded-mesh telemetry before injecting a
        per-shard fault (`TENDERMINT_TPU_DEVICE_FAIL=shard<i>`); pass
        to `assert_mesh_degraded` / `assert_mesh_restored`."""
        return {
            "faults": self.telemetry_value("tendermint_mesh_shard_faults_total"),
            "shrinks": self.telemetry_value(
                "tendermint_mesh_remesh_total", direction="shrink"
            ),
            "restores": self.telemetry_value(
                "tendermint_mesh_remesh_total", direction="restore"
            ),
        }

    def assert_mesh_degraded(
        self, baseline: dict, min_faults: int = 1, timeout: float = 30.0
    ) -> None:
        """The shrink half of the cycle, via exported telemetry: shard
        faults observed AND survivor re-meshes performed — the chip
        loss was absorbed BELOW the breaker."""
        self.wait_telemetry_above(
            "tendermint_mesh_shard_faults_total",
            baseline["faults"] + min_faults - 1,
            timeout=timeout,
        )
        self.wait_telemetry_above(
            "tendermint_mesh_remesh_total",
            baseline["shrinks"],
            timeout=timeout,
            direction="shrink",
        )

    def assert_mesh_restored(
        self, baseline: dict, min_restores: int = 1, timeout: float = 30.0
    ) -> None:
        """The recover half: re-probe brought full meshes back."""
        self.wait_telemetry_above(
            "tendermint_mesh_remesh_total",
            baseline["restores"] + min_restores - 1,
            timeout=timeout,
            direction="restore",
        )

    def wait_telemetry_above(
        self, name: str, threshold: float, timeout: float = 30.0, **labels
    ) -> float:
        """Block until an exported series exceeds `threshold` (e.g. the
        round-skip counter during a starvation scenario)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.violations:
                raise InvariantViolation(self.violations[0])
            v = self.telemetry_value(name, **labels)
            if v > threshold:
                return v
            time.sleep(0.05)
        raise TimeoutError(
            f"{name}{labels or ''} stayed <= {threshold} for {timeout}s "
            f"(now {self.telemetry_value(name, **labels)})"
        )

    # -- invariants ----------------------------------------------------------

    def heights(self) -> list[int]:
        return [n.store.height for n in self.nodes]

    def _violation(self, msg: str) -> InvariantViolation:
        """Build the violation AND dump the forensics: the flight
        recorder's ring of round transitions / flushes / launches, plus
        the height ledgers' per-height critical-path records. Both dump
        paths ride the assertion message so a red CI run is
        self-diagnosing (`tools/trace_timeline.py --flight`,
        `tools/finality_report.py --ledgers`)."""
        from tendermint_tpu.telemetry import heightlog
        from tendermint_tpu.telemetry.flightrec import FLIGHT

        path = FLIGHT.dump(reason="invariant-violation", dir=self.home)
        if path:
            msg = f"{msg} [flight recorder: {path}]"
        hpath = heightlog.dump_all(self.home, reason="invariant-violation")
        if hpath:
            msg = f"{msg} [height ledger: {hpath}]"
        return InvariantViolation(msg)

    def check_no_fork(self) -> None:
        """One block hash per height across every store that has it."""
        top = max(self.heights(), default=0)
        for h in range(1, top + 1):
            seen: dict[bytes, int] = {}
            for node in self.nodes:
                meta = node.store.load_block_meta(h)
                if meta is not None:
                    seen.setdefault(bytes(meta.block_id.hash), node.index)
            if len(seen) > 1:
                raise self._violation(
                    f"FORK at height {h}: {[(v, k.hex()[:12]) for k, v in seen.items()]}"
                )

    def check_commit_agreement(self) -> None:
        """Every stored seen-commit certifies the block stored at that
        height (a node must never store a commit for one block and the
        data of another)."""
        for node in self.nodes:
            for h in range(1, node.store.height + 1):
                meta = node.store.load_block_meta(h)
                commit = node.store.load_seen_commit(h)
                if meta is None or commit is None:
                    continue
                if bytes(commit.block_id.hash) != bytes(meta.block_id.hash):
                    raise self._violation(
                        f"node{node.index} height {h}: seen-commit certifies "
                        f"{commit.block_id.hash.hex()[:12]} but stored block is "
                        f"{meta.block_id.hash.hex()[:12]}"
                    )

    def check_invariants(self) -> None:
        self.check_no_fork()
        self.check_commit_agreement()

    def assert_invariants(self) -> None:
        """Raise the first violation the background monitor recorded,
        then re-check once on the final state."""
        if self.violations:
            raise InvariantViolation(self.violations[0])
        self.check_invariants()

    def _monitor_loop(self) -> None:
        while not self._monitor_stop.wait(self._monitor_interval):
            try:
                self.check_invariants()
            except InvariantViolation as e:
                self.violations.append(str(e))
                kv(_log, logging.ERROR, "invariant violated", error=str(e)[:200])
                return  # state is already poisoned; keep the first report

    # -- progress ------------------------------------------------------------

    def wait_height(
        self,
        height: int,
        nodes: list[int] | None = None,
        timeout: float = 60.0,
    ) -> None:
        """Block until the given nodes' stores reach `height` (eventual
        progress — e.g. after heal). Raises on timeout or violation."""
        targets = nodes if nodes is not None else range(len(self.nodes))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.violations:
                raise InvariantViolation(self.violations[0])
            if all(self.nodes[i].store.height >= height for i in targets):
                return
            time.sleep(0.05)
        self._dump_stall_forensics()
        raise TimeoutError(
            f"heights {self.heights()} did not reach {height} in {timeout}s"
        )

    def _dump_stall_forensics(self) -> None:
        """A progress timeout on an UNpartitioned in-process net usually
        means one node's consensus thread is wedged or blocked — dump
        every thread's stack (plus the flight recorder) so the red run
        carries its own diagnosis, like invariant violations already do."""
        import faulthandler
        import sys

        from tendermint_tpu.telemetry.flightrec import FLIGHT

        try:
            sys.stderr.write(
                f"nemesis stall: heights={self.heights()} — thread stacks:\n"
            )
            faulthandler.dump_traceback(file=sys.stderr)
            FLIGHT.dump(reason="nemesis-stall", dir=self.home)
        except Exception:
            pass  # forensics must never mask the timeout itself

    def wait_progress(
        self,
        delta: int = 1,
        nodes: list[int] | None = None,
        timeout: float = 60.0,
    ) -> int:
        """Wait for `delta` MORE committed heights on the given nodes;
        returns the new minimum height."""
        targets = list(nodes if nodes is not None else range(len(self.nodes)))
        base = min(self.nodes[i].store.height for i in targets)
        self.wait_height(base + delta, nodes=targets, timeout=timeout)
        return min(self.nodes[i].store.height for i in targets)
