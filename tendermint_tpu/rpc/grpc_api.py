"""gRPC broadcast API (reference `rpc/grpc/api.go:13-30` + `types.proto`).

The reference exposes a minimal gRPC surface for app developers: `Ping`
and `BroadcastTx` (which wraps BroadcastTxCommit). Implemented with
grpcio's generic handler API — message bodies use this framework's
deterministic codec rather than protoc-generated classes, so there is
no generated-code build step; the transport is standard gRPC/HTTP2.

Service: `tendermint_tpu.BroadcastAPI`
  Ping(bytes)        -> b"pong"
  BroadcastTx(tx)    -> Writer(check_code, check_log,
                               deliver_code, deliver_data, deliver_log,
                               height)
"""

from __future__ import annotations

from concurrent import futures

from tendermint_tpu.codec.binary import Reader, Writer

_SERVICE = "tendermint_tpu.BroadcastAPI"


def _identity(b: bytes) -> bytes:
    return b


class GRPCBroadcastServer:
    """Serves Ping/BroadcastTx for one node (reference `grpccore`)."""

    def __init__(self, node, laddr: str) -> None:
        import grpc

        from tendermint_tpu.p2p.tcp import parse_laddr

        self._node = node
        host, port = parse_laddr(laddr)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                "Ping": grpc.unary_unary_rpc_method_handler(
                    self._ping,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                ),
                "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                    self._broadcast_tx,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                ),
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"gRPC bind failed for {laddr}")
        from tendermint_tpu.rpc.core import make_routes

        self._routes = make_routes(node)

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    # -- handlers ----------------------------------------------------------

    def _ping(self, request: bytes, context) -> bytes:
        return b"pong"

    def _broadcast_tx(self, request: bytes, context) -> bytes:
        """BroadcastTx == wait-for-commit (reference wraps
        BroadcastTxCommit)."""
        import grpc

        from tendermint_tpu.rpc.server import RPCError

        try:
            res = self._routes["broadcast_tx_commit"](tx=request.hex())
        except RPCError as e:
            # surface a structured failure (e.g. commit timeout) instead
            # of an opaque UNKNOWN with a server-side traceback
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED if "timed out" in e.message else grpc.StatusCode.INTERNAL, e.message)
        w = Writer()
        w.uvarint(res["check_tx"].get("code", 0))
        w.string(res["check_tx"].get("log", ""))
        deliver = res.get("deliver_tx") or {}
        w.uvarint(deliver.get("code", 0))
        w.bytes(bytes.fromhex(deliver.get("data", "")))
        w.string(deliver.get("log", ""))
        w.uvarint(res.get("height", 0))
        return w.build()


class GRPCBroadcastClient:
    """Client for the broadcast service (reference
    `rpc/grpc/client_server.go`)."""

    def __init__(self, address: str) -> None:
        import grpc

        addr = address.split("://", 1)[-1]
        self._channel = grpc.insecure_channel(addr)
        self._ping = self._channel.unary_unary(
            f"/{_SERVICE}/Ping",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._broadcast = self._channel.unary_unary(
            f"/{_SERVICE}/BroadcastTx",
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def ping(self, timeout: float = 10.0) -> bool:
        return self._ping(b"", timeout=timeout) == b"pong"

    def broadcast_tx(self, tx: bytes, timeout: float = 90.0) -> dict:
        r = Reader(self._broadcast(tx, timeout=timeout))
        return {
            "check_tx": {"code": r.uvarint(), "log": r.string()},
            "deliver_tx": {
                "code": r.uvarint(),
                "data": r.bytes(),
                "log": r.string(),
            },
            "height": r.uvarint(),
        }

    def close(self) -> None:
        self._channel.close()
