"""RPC clients: HTTP and in-process Local (reference `rpc/client/`
HTTP + Local implementing one interface, `interface.go`).

Every method mirrors a route in `rpc/core.py`; both clients are
interchangeable (the reference's test pattern) — HTTPClient speaks
JSON-RPC 2.0 over HTTP, LocalClient calls the route table directly.
"""

from __future__ import annotations

import json
import urllib.request


class RPCClientError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


class _ClientBase:
    def _call(self, method: str, **params):
        raise NotImplementedError

    # -- the client interface (reference rpc/client/interface.go) ---------

    def status(self):
        return self._call("status")

    def net_info(self):
        return self._call("net_info")

    def block(self, height: int):
        return self._call("block", height=height)

    def blockchain(self, min_height: int = 1, max_height: int = 0):
        return self._call("blockchain", min_height=min_height, max_height=max_height)

    def commit(self, height: int):
        return self._call("commit", height=height)

    def validators(self, height: int | None = None):
        if height is None:
            return self._call("validators")
        return self._call("validators", height=height)

    def dump_consensus_state(self):
        return self._call("dump_consensus_state")

    def abci_query(self, path: str = "", data: bytes = b"", height: int = 0, prove: bool = False):
        return self._call(
            "abci_query", path=path, data=data.hex(), height=height, prove=prove
        )

    def num_unconfirmed_txs(self):
        return self._call("num_unconfirmed_txs")

    def unconfirmed_txs(self):
        return self._call("unconfirmed_txs")

    def abci_info(self):
        return self._call("abci_info")

    def genesis(self):
        return self._call("genesis")

    def tx(self, tx_hash: bytes, prove: bool = False):
        return self._call("tx", hash=tx_hash.hex(), prove=prove)

    def broadcast_tx_async(self, tx: bytes):
        return self._call("broadcast_tx_async", tx=tx.hex())

    def broadcast_tx_sync(self, tx: bytes):
        return self._call("broadcast_tx_sync", tx=tx.hex())

    def broadcast_tx_commit(self, tx: bytes):
        return self._call("broadcast_tx_commit", tx=tx.hex())


class HTTPClient(_ClientBase):
    """JSON-RPC 2.0 over HTTP (reference `rpc/client/httpclient.go`)."""

    def __init__(self, address: str, timeout: float = 90.0):
        # accepts "host:port", "tcp://host:port", or "http://host:port"
        addr = address.split("://", 1)[-1]
        self.url = f"http://{addr}/"
        self.timeout = timeout
        self._id = 0

    def _call(self, method: str, **params):
        self._id += 1
        req = urllib.request.Request(
            self.url,
            data=json.dumps(
                {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            out = json.load(resp)
        if "error" in out:
            raise RPCClientError(out["error"]["code"], out["error"]["message"])
        return out["result"]


class WSClient:
    """WebSocket event-subscription client (reference
    `rpc/lib/client/ws_client.go`). Blocking iterator interface:

        ws = WSClient("127.0.0.1:46657")
        ws.subscribe("NewBlock")
        for event in ws.events(timeout=10): ...

    When `reconnect=True` (default), a dead connection is transparently
    re-established with jittered exponential backoff and all active
    subscriptions re-issued (reference auto-reconnect + resubscribe,
    `rpc/lib/client/ws_client.go:46-59`).
    """

    def __init__(
        self,
        address: str,
        timeout: float = 30.0,
        reconnect: bool = True,
        max_reconnect_attempts: int = 25,
        reconnect_base_backoff_s: float = 0.25,
    ):
        from tendermint_tpu.p2p.tcp import parse_laddr

        self._host, self._port = parse_laddr(
            address if "://" in address else f"tcp://{address}"
        )
        self._timeout = timeout
        self._reconnect_enabled = reconnect
        self._max_reconnect_attempts = max_reconnect_attempts
        self._reconnect_base_backoff_s = reconnect_base_backoff_s
        self._id = 0
        self._pending_events: list[dict] = []
        self._subscriptions: set[str] = set()
        self._closed = False
        self._connect()

    def _connect(self) -> None:
        import base64
        import os
        import socket

        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        key = base64.b64encode(os.urandom(16)).decode()
        self._sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {self._host}\r\n"
                "Connection: Upgrade\r\nUpgrade: websocket\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        self._rfile = self._sock.makefile("rb")
        status = self._rfile.readline()
        if b"101" not in status:
            raise RPCClientError(-32000, f"ws upgrade failed: {status!r}")
        while self._rfile.readline() not in (b"\r\n", b""):
            pass

    def _try_reconnect(self) -> bool:
        """Dial + resubscribe with jittered exponential backoff; False when
        disabled, closed, or out of attempts."""
        import time as _time

        if not self._reconnect_enabled or self._closed:
            return False
        try:
            self._sock.close()
        except OSError:
            pass
        from tendermint_tpu.utils.backoff import backoff_delay

        for attempt in range(self._max_reconnect_attempts):
            _time.sleep(
                backoff_delay(attempt, self._reconnect_base_backoff_s, cap=10.0)
            )
            if self._closed:
                return False
            try:
                self._connect()
                for event in list(self._subscriptions):
                    self._send("subscribe", event=event)
                    resp = self._recv_response(self._id, timeout=10)
                    if resp is None or "error" in resp:
                        raise RPCClientError(-32000, f"resubscribe failed: {resp}")
                return True
            except (OSError, RPCClientError):
                # don't leak a half-set-up conn when resubscribe fails
                try:
                    self._sock.close()
                except OSError:
                    pass
                continue
        return False

    def _send(self, method: str, **params) -> None:
        from tendermint_tpu.rpc.websocket import encode_frame

        self._id += 1
        payload = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        self._sock.sendall(encode_frame(payload, mask=True))

    def _recv_json(self, timeout: float | None = None) -> dict | None:
        from tendermint_tpu.rpc.websocket import read_frame

        self._sock.settimeout(timeout)
        frame = read_frame(self._rfile)
        if frame is None:
            return None
        opcode, payload = frame
        if opcode != 0x1:
            return self._recv_json(timeout)
        return json.loads(payload)

    def _recv_response(self, req_id: int, timeout: float) -> dict | None:
        """Next message with our request id; event notifications that
        arrive in the meantime are buffered for events()."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            msg = self._recv_json(max(deadline - _time.monotonic(), 0.05))
            if msg is None:
                return None
            if msg.get("method") == "event":
                self._pending_events.append(msg["params"])
                continue
            if msg.get("id") == req_id:
                return msg
        return None

    def subscribe(self, event: str) -> None:
        self._send("subscribe", event=event)
        resp = self._recv_response(self._id, timeout=10)
        if resp is None or "error" in resp:
            raise RPCClientError(-32000, f"subscribe failed: {resp}")
        self._subscriptions.add(event)

    def unsubscribe(self, event: str) -> None:
        self._subscriptions.discard(event)
        self._send("unsubscribe", event=event)

    def events(self, timeout: float = 30.0):
        """Yield event notification params until timeout/close. A dead
        connection triggers transparent reconnect + resubscribe. The
        iterator ends cleanly only on a quiet-period timeout or explicit
        close(); reconnect exhaustion raises RPCClientError so callers
        can tell "no events" from "connection permanently lost"."""

        def _recovered() -> bool:
            """True when reconnected, False on explicit close; raises on
            reconnect exhaustion of a live client."""
            if self._try_reconnect():
                return True
            if self._closed:
                return False
            raise RPCClientError(
                -32000,
                f"websocket connection lost and not recovered after "
                f"{self._max_reconnect_attempts} reconnect attempts",
            )

        while self._pending_events:
            yield self._pending_events.pop(0)
        while True:
            try:
                msg = self._recv_json(timeout)
            except TimeoutError:
                return  # no events within `timeout`: normal iterator end
            except OSError:
                if not _recovered():
                    return
                # resubscribe may have buffered events that raced the
                # subscribe responses — deliver them in order now
                while self._pending_events:
                    yield self._pending_events.pop(0)
                continue
            if msg is None:  # server closed the stream
                if not _recovered():
                    return
                while self._pending_events:
                    yield self._pending_events.pop(0)
                continue
            if msg.get("method") == "event":
                yield msg["params"]

    def close(self) -> None:
        self._closed = True
        self._sock.close()


class LocalClient(_ClientBase):
    """In-process client over a Node's route table (reference
    `rpc/client/localclient.go` — no HTTP hop, same interface)."""

    def __init__(self, node):
        from tendermint_tpu.rpc.core import make_routes

        self._routes = make_routes(node)

    def _call(self, method: str, **params):
        # mirror the HTTP server's error mapping so the two clients are
        # genuinely interchangeable (same except-clauses work for both)
        from tendermint_tpu.rpc.server import RPCError

        fn = self._routes.get(method)
        if fn is None:
            raise RPCClientError(-32601, f"unknown method {method}")
        try:
            return fn(**params)
        except RPCError as e:
            raise RPCClientError(e.code, e.message) from e
        except TypeError as e:
            raise RPCClientError(-32602, f"invalid params: {e}") from e
        except Exception as e:
            raise RPCClientError(-32603, str(e)) from e
