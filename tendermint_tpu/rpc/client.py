"""RPC clients: HTTP and in-process Local (reference `rpc/client/`
HTTP + Local implementing one interface, `interface.go`).

Every method mirrors a route in `rpc/core.py`; both clients are
interchangeable (the reference's test pattern) — HTTPClient speaks
JSON-RPC 2.0 over HTTP, LocalClient calls the route table directly.
"""

from __future__ import annotations

import json
import urllib.request


class RPCClientError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


class _ClientBase:
    def _call(self, method: str, **params):
        raise NotImplementedError

    # -- the client interface (reference rpc/client/interface.go) ---------

    def status(self):
        return self._call("status")

    def net_info(self):
        return self._call("net_info")

    def block(self, height: int):
        return self._call("block", height=height)

    def blockchain(self, min_height: int = 1, max_height: int = 0):
        return self._call("blockchain", min_height=min_height, max_height=max_height)

    def commit(self, height: int):
        return self._call("commit", height=height)

    def validators(self, height: int | None = None):
        if height is None:
            return self._call("validators")
        return self._call("validators", height=height)

    def dump_consensus_state(self):
        return self._call("dump_consensus_state")

    def abci_query(self, path: str = "", data: bytes = b"", height: int = 0, prove: bool = False):
        return self._call(
            "abci_query", path=path, data=data.hex(), height=height, prove=prove
        )

    def num_unconfirmed_txs(self):
        return self._call("num_unconfirmed_txs")

    def genesis(self):
        return self._call("genesis")

    def tx(self, tx_hash: bytes):
        return self._call("tx", hash=tx_hash.hex())

    def broadcast_tx_async(self, tx: bytes):
        return self._call("broadcast_tx_async", tx=tx.hex())

    def broadcast_tx_sync(self, tx: bytes):
        return self._call("broadcast_tx_sync", tx=tx.hex())

    def broadcast_tx_commit(self, tx: bytes):
        return self._call("broadcast_tx_commit", tx=tx.hex())


class HTTPClient(_ClientBase):
    """JSON-RPC 2.0 over HTTP (reference `rpc/client/httpclient.go`)."""

    def __init__(self, address: str, timeout: float = 90.0):
        # accepts "host:port", "tcp://host:port", or "http://host:port"
        addr = address.split("://", 1)[-1]
        self.url = f"http://{addr}/"
        self.timeout = timeout
        self._id = 0

    def _call(self, method: str, **params):
        self._id += 1
        req = urllib.request.Request(
            self.url,
            data=json.dumps(
                {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            out = json.load(resp)
        if "error" in out:
            raise RPCClientError(out["error"]["code"], out["error"]["message"])
        return out["result"]


class LocalClient(_ClientBase):
    """In-process client over a Node's route table (reference
    `rpc/client/localclient.go` — no HTTP hop, same interface)."""

    def __init__(self, node):
        from tendermint_tpu.rpc.core import make_routes

        self._routes = make_routes(node)

    def _call(self, method: str, **params):
        # mirror the HTTP server's error mapping so the two clients are
        # genuinely interchangeable (same except-clauses work for both)
        from tendermint_tpu.rpc.server import RPCError

        fn = self._routes.get(method)
        if fn is None:
            raise RPCClientError(-32601, f"unknown method {method}")
        try:
            return fn(**params)
        except RPCError as e:
            raise RPCClientError(e.code, e.message) from e
        except TypeError as e:
            raise RPCClientError(-32602, f"invalid params: {e}") from e
        except Exception as e:
            raise RPCClientError(-32603, str(e)) from e
