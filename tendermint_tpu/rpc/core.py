"""RPC core handlers wired to node internals (reference
`rpc/core/routes.go:8-45` + per-file handlers).

`make_routes(node)` builds the route table from a composed Node;
responses are hex-encoded JSON dicts mirroring the reference's
result types.
"""

from __future__ import annotations

import queue
import time

from tendermint_tpu.rpc.server import RPCError
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.types import events as ev
from tendermint_tpu.types.tx import tx_hash

BROADCAST_TX_COMMIT_TIMEOUT_S = 60.0  # reference waits up to 120s


def _header_json(header) -> dict:
    return {
        "chain_id": header.chain_id,
        "height": header.height,
        "time": header.time,
        "num_txs": header.num_txs,
        "last_block_id": {
            "hash": header.last_block_id.hash.hex(),
            "parts": {
                "total": header.last_block_id.parts_header.total,
                "hash": header.last_block_id.parts_header.hash.hex(),
            },
        },
        "last_commit_hash": header.last_commit_hash.hex(),
        "data_hash": header.data_hash.hex(),
        "validators_hash": header.validators_hash.hex(),
        "app_hash": header.app_hash.hex(),
        "hash": header.hash().hex(),
    }


def _block_json(block) -> dict:
    return {
        "header": _header_json(block.header),
        "txs": [bytes(tx).hex() for tx in block.data.txs],
        "last_commit": {
            "block_id": block.last_commit.block_id.hash.hex()
            if block.last_commit.precommits
            else "",
            "precommits": sum(
                1 for p in block.last_commit.precommits if p is not None
            ),
        },
    }


def make_routes(node) -> dict:
    """Route table (reference `rpc/core/routes.go:8-34`)."""

    def status() -> dict:
        rs = node.consensus.get_round_state() if node.consensus else None
        latest = node.block_store.load_block_meta(node.block_store.height)
        return {
            "node_info": {
                "id": node.node_id,
                "moniker": node.config.base.moniker,
                "chain_id": node.genesis.chain_id,
            },
            "sync_info": {
                "latest_block_height": node.block_store.height,
                "latest_block_hash": latest.block_id.hash.hex() if latest else "",
                "latest_app_hash": node.current_state.app_hash.hex(),
                "catching_up": node.blockchain_reactor.fast_sync
                if node.blockchain_reactor
                else False,
            },
            "validator_info": {
                "address": node.priv_validator.address.hex()
                if node.priv_validator
                else "",
                "voting_power": next(
                    (
                        v.voting_power
                        for v in node.current_state.validators
                        if node.priv_validator
                        and v.address == node.priv_validator.address
                    ),
                    0,
                ),
            },
            "consensus_state": {
                "height": rs.height if rs else 0,
                "round": rs.round if rs else 0,
                "step": rs.step if rs else 0,
            },
        }

    def net_info() -> dict:
        peers = node.switch.peers() if node.switch else []
        return {
            "n_peers": len(peers),
            "peers": [
                {
                    "id": p.id,
                    "moniker": p.node_info.moniker,
                    "outbound": p.outbound,
                    "send_rate": round(p.send_monitor.rate, 1),
                    "recv_rate": round(p.recv_monitor.rate, 1),
                    "bytes_sent": p.send_monitor.total,
                    "bytes_recv": p.recv_monitor.total,
                }
                for p in peers
            ],
        }

    def block(height: int) -> dict:
        b = node.block_store.load_block(int(height))
        if b is None:
            raise RPCError(-32000, f"no block at height {height}")
        return {"block": _block_json(b)}

    def blockchain(min_height: int = 1, max_height: int = 0) -> dict:
        top = node.block_store.height
        max_h = int(max_height) or top
        max_h = min(max_h, top)
        min_h = max(int(min_height), max(1, max_h - 20 + 1))
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = node.block_store.load_block_meta(h)
            if m is not None:
                metas.append(
                    {"height": m.header.height, "hash": m.block_id.hash.hex()}
                )
        return {"last_height": top, "block_metas": metas}

    def commit(height: int) -> dict:
        c = node.block_store.load_block_commit(int(height))
        seen = c is None
        if c is None:
            c = node.block_store.load_seen_commit(int(height))
        if c is None:
            raise RPCError(-32000, f"no commit for height {height}")
        meta = node.block_store.load_block_meta(int(height))
        out = {
            "canonical": not seen,
            "commit": {
                "height": c.height(),
                "round": c.round(),
                "block_id": {
                    "hash": c.block_id.hash.hex(),
                    "parts": {
                        "total": c.block_id.parts_header.total,
                        "hash": c.block_id.parts_header.hash.hex(),
                    },
                },
                "signatures": sum(1 for p in c.precommits if p is not None),
                # full precommits (null = absent vote) so external light
                # clients can re-verify — the reference's ResultCommit
                # carries the complete SignedHeader
                # (`rpc/core/blocks.go` Commit)
                "precommits": [
                    None
                    if v is None
                    else {
                        "validator_address": v.validator_address.hex(),
                        "validator_index": v.validator_index,
                        "height": v.height,
                        "round": v.round,
                        "timestamp": v.timestamp,
                        "type": v.type,
                        "block_id": {
                            "hash": v.block_id.hash.hex(),
                            "parts": {
                                "total": v.block_id.parts_header.total,
                                "hash": v.block_id.parts_header.hash.hex(),
                            },
                        },
                        "signature": v.signature.hex(),
                    }
                    for v in c.precommits
                ],
            },
        }
        if meta is not None:
            out["header"] = _header_json(meta.header)
        _metrics.REPLICA_PROOFS_SERVED.labels(kind="commit").inc()
        return out

    def full_commit(height: int = 0) -> dict:
        """One light-client proof unit — header + commit + valset at a
        height (0 = tip) — served from the certified cache / local
        stores through the 0x68 reactor's exact->floor lookup. The
        `full_commit` hex decodes via `FullCommit.decode`; external
        light clients feed it straight into a certifier walk without
        the three-round-trip commit+validators+header dance."""
        reactor = getattr(node, "lightclient_reactor", None)
        fc = reactor.serve_commit(int(height)) if reactor is not None else None
        if fc is None:
            raise RPCError(-32000, f"no full commit at height {height}")
        _metrics.REPLICA_PROOFS_SERVED.labels(kind="full_commit").inc()
        return {
            "height": fc.height(),
            "header": _header_json(fc.header),
            "canonical": True,
            "full_commit": fc.encode().hex(),
        }

    def validators(height: int | None = None) -> dict:
        h = int(height) if height is not None else node.current_state.last_block_height + 1
        vs = node.current_state.load_validators(h)
        _metrics.REPLICA_PROOFS_SERVED.labels(kind="validators").inc()
        return {
            "block_height": h,
            "validators": [
                {
                    "address": v.address.hex(),
                    "pub_key": v.pub_key.data.hex(),
                    "voting_power": v.voting_power,
                }
                for v in vs
            ],
        }

    def dump_consensus_state() -> dict:
        if node.consensus is None:
            raise RPCError(-32000, "consensus not running")
        rs = node.consensus.get_round_state()
        peers = []
        reactor = getattr(node, "consensus_reactor", None)
        if reactor is not None and node.switch is not None:
            for p in node.switch.peers():
                ps = p.get(reactor.PEER_STATE_KEY)
                if ps is None:
                    continue
                prs = ps.snapshot()
                peers.append(
                    {
                        "id": p.id,
                        "height": prs.height,
                        "round": prs.round,
                        "step": prs.step,
                        "has_proposal": prs.proposal,
                    }
                )
        return {
            "height": rs.height,
            "round": rs.round,
            "step": rs.step,
            "proposal": rs.proposal is not None,
            "locked_round": rs.locked_round,
            "validators": len(rs.validators),
            "peers": peers,
        }

    def health() -> dict:
        """Health / readiness snapshot (telemetry/health.py): status ∈
        ok | degraded | not_ready, per-check detail, rolling finality
        SLO. Also served as plain `GET /health` with HTTP 503 on
        not_ready so load balancers can act without parsing JSON-RPC."""
        from tendermint_tpu.telemetry.health import build_health

        return build_health(node)

    def dump_telemetry(
        spans: int = 128,
        prefix: str = "",
        trace_id: str = "",
        flight: int = 0,
        heights: int = 0,
        profile: int = 0,
        launches: int = 0,
        gossip: int = 0,
    ) -> dict:
        """Structured telemetry dump: the full metrics registry, the
        recent span window (consensus round phases, device dispatch),
        and per-service breaker snapshots. The JSON twin of
        `GET /metrics` (docs/OBSERVABILITY.md).

        `trace_id` (hex) narrows the span window to one distributed
        trace — the live-node half of `tools/trace_timeline.py`;
        `flight` > 0 additionally returns that many recent flight-
        recorder events; `heights` > 0 returns the last N HeightLedger
        records (per-height phases + critical-path attribution);
        `profile` > 0 returns the contention-observatory view (profiler
        snapshot + top-contended locks + unified queue waits —
        `tools/contention_report.py` consumes it); `launches` > 0
        returns the last N LaunchLedger records + per-kind rollup (the
        device observatory — `tools/device_report.py` consumes it);
        `gossip` > 0 returns the gossip observatory view (per-peer ×
        per-channel × per-kind traffic, redundancy counters, first-seen
        propagation stamps — `tools/gossip_report.py` consumes it).

        High-cardinality detail (per-peer, per-thread, per-site) is
        served ONLY here, through `telemetry/views.py` — the dump-only
        convention (docs/OBSERVABILITY.md "Dump-only views")."""
        from tendermint_tpu.telemetry import REGISTRY, TRACER, views

        breakers = {}
        for name, svc in (
            ("verifier", getattr(node.consensus, "verifier", None)),
            ("hasher", getattr(node, "hasher", None)),
        ):
            if svc is not None and hasattr(svc, "snapshot"):
                try:
                    breakers[name] = svc.snapshot()
                except Exception:
                    pass
        if trace_id:
            # trace filter ignores the recency cap: a stitched timeline
            # wants every matching span still in the ring
            span_window = [
                s
                for s in TRACER.recent(prefix=str(prefix))
                if (s.get("attrs") or {}).get("trace") == str(trace_id)
            ]
        else:
            span_window = TRACER.recent(n=int(spans), prefix=str(prefix))
        out = {
            "metrics": REGISTRY.to_dict(),
            "spans": span_window,
            "breakers": breakers,
        }
        want: list = ["p2p", "vote_arrivals"]
        if int(profile) > 0:
            want.append("profile")
        if int(launches) > 0:
            want.append(("launches", {"n": int(launches)}))
        if int(gossip) > 0:
            want.append("gossip")
        out.update(views.collect(node, want))
        if int(flight) > 0:
            from tendermint_tpu.telemetry.flightrec import FLIGHT

            out["flight"] = FLIGHT.recent(n=int(flight))
        if int(heights) > 0:
            ledger = getattr(node.consensus, "height_ledger", None)
            if ledger is not None:
                out["heights"] = ledger.recent(int(heights))
        return out

    def abci_query(path: str = "", data: str = "", height: int = 0, prove: bool = False) -> dict:
        res = node.app_conns.query.query_sync(
            path, bytes.fromhex(data) if data else b"", int(height), bool(prove)
        )
        if prove:
            _metrics.REPLICA_PROOFS_SERVED.labels(kind="abci_query").inc()
        return {
            "code": res.code,
            "value": res.value.hex(),
            "log": res.log,
            "height": res.height,
        }

    def num_unconfirmed_txs() -> dict:
        return {"n_txs": node.mempool.size()}

    def unconfirmed_txs() -> dict:
        """Pending mempool txs (reference `rpc/core/mempool.go` +
        `routes.go:22` UnconfirmedTxs)."""
        txs = node.mempool.reap(-1)
        return {"n_txs": len(txs), "txs": [bytes(t).hex() for t in txs]}

    def abci_info() -> dict:
        """App Info over the query conn (reference `rpc/core/abci.go:36-42`,
        route `routes.go:30`)."""
        res = node.app_conns.query.info_sync()
        return {
            "data": res.data,
            "version": res.version,
            "last_block_height": res.last_block_height,
            "last_block_app_hash": res.last_block_app_hash.hex(),
        }

    def _decode_tx(tx: str) -> bytes:
        try:
            return bytes.fromhex(tx)
        except ValueError as e:
            raise RPCError(-32602, f"tx must be hex: {e}") from e

    def broadcast_tx_async(tx: str) -> dict:
        raw = _decode_tx(tx)
        # fire-and-forget (reference BroadcastTxAsync returns before
        # CheckTx): the tx joins the next ingress verify window and this
        # handler thread is free for the next request
        submit = getattr(node.mempool, "check_tx_async", None)
        (submit or node.mempool.check_tx)(raw)
        return {"hash": tx_hash(raw).hex()}

    def broadcast_tx_sync(tx: str) -> dict:
        raw = _decode_tx(tx)
        res = node.mempool.check_tx(raw)
        return {
            "code": res.code,
            "data": res.data.hex(),
            "log": res.log,
            "hash": tx_hash(raw).hex(),
        }

    def broadcast_tx_commit(tx: str) -> dict:
        """CheckTx, then wait for the tx to be committed in a block
        (reference `rpc/core/mempool.go:149-215`)."""
        raw = _decode_tx(tx)
        h = tx_hash(raw)
        got: "queue.Queue" = queue.Queue()
        key = ev.event_tx(h)
        listener_id = f"rpc-tx-{h.hex()[:16]}-{time.monotonic_ns()}"
        node.event_switch.add_listener(listener_id, key, got.put)
        try:
            check = node.mempool.check_tx(raw)
            if not check.is_ok:
                return {
                    "check_tx": {"code": check.code, "log": check.log},
                    "deliver_tx": {},
                    "hash": h.hex(),
                    "height": 0,
                }
            try:
                data = got.get(timeout=BROADCAST_TX_COMMIT_TIMEOUT_S)
            except queue.Empty:
                raise RPCError(-32000, "timed out waiting for tx commit") from None
            return {
                "check_tx": {"code": check.code, "log": check.log},
                "deliver_tx": {
                    "code": data.code,
                    "data": data.data.hex(),
                    "log": data.log,
                },
                "hash": h.hex(),
                "height": data.height,
            }
        finally:
            node.event_switch.remove_listener(listener_id)

    def tx(hash: str, prove: bool = False) -> dict:
        if node.tx_indexer is None:
            raise RPCError(-32000, "tx indexing disabled")
        tr = node.tx_indexer.get(bytes.fromhex(hash))
        if tr is None:
            raise RPCError(-32000, f"tx {hash} not found")
        out = {
            "height": tr.height,
            "index": tr.index,
            "tx": tr.tx.hex(),
            "result": {
                "code": tr.result.code,
                "data": tr.result.data.hex(),
                "log": tr.result.log,
            },
        }
        if prove:
            # Rebuild the block's tx tree and serve the inclusion proof
            # (reference `rpc/core/tx.go` Tx prove + `types/tx.go:71-112`)
            blk = node.block_store.load_block(tr.height)
            if blk is None:
                raise RPCError(-32000, f"block {tr.height} not in store")
            tx_proof = blk.data.txs.proof(tr.index)
            _metrics.REPLICA_PROOFS_SERVED.labels(kind="tx").inc()
            out["proof"] = {
                "root_hash": tx_proof.root_hash.hex(),
                "data": tx_proof.data.hex(),
                "proof": {
                    "index": tx_proof.proof.index,
                    "total": tx_proof.proof.total,
                    "leaf": tx_proof.proof.leaf.hex(),
                    "aunts": [a.hex() for a in tx_proof.proof.aunts],
                },
            }
        return out

    def genesis() -> dict:
        import json as _json

        return {"genesis": _json.loads(node.genesis.to_json())}

    # -- unsafe profiling/introspection routes (reference
    # `rpc/core/routes.go:36-45` + `dev.go`, served only with
    # rpc.unsafe; the pprof-server analog for this runtime) ------------

    # Sampling profiler across ALL threads: cProfile hooks only the
    # calling thread, which over HTTP is a short-lived request-handler
    # thread — it would capture nothing of the node's work. A sampler
    # walking sys._current_frames() sees consensus/gossip/sync threads
    # regardless of which thread starts it.
    _profiler: dict = {}
    _profiler_lock = __import__("threading").Lock()

    def unsafe_start_cpu_profiler(interval_ms: int = 5) -> dict:
        import collections
        import sys
        import threading
        import time as time_mod

        if not _profiler_lock.acquire(blocking=False):
            raise RPCError(-32000, "profiler already running")
        # held until unsafe_stop_cpu_profiler releases: two concurrent
        # starts must not each spawn an (then-unstoppable) sampler
        if _profiler:
            _profiler_lock.release()
            raise RPCError(-32000, "profiler already running")
        counts = collections.Counter()
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                for frame in list(sys._current_frames().values()):
                    counts[
                        f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{frame.f_lineno} {frame.f_code.co_name}"
                    ] += 1
                time_mod.sleep(max(int(interval_ms), 1) / 1000.0)

        t = threading.Thread(target=sampler, name="rpc-profiler", daemon=True)
        _profiler["stop"] = stop
        _profiler["counts"] = counts
        _profiler["thread"] = t
        t.start()
        return {"started": True, "interval_ms": int(interval_ms)}

    def unsafe_stop_cpu_profiler(top: int = 25) -> dict:
        if not _profiler:
            raise RPCError(-32000, "profiler not running")
        _profiler["stop"].set()
        _profiler["thread"].join(timeout=2)
        counts = _profiler["counts"]
        _profiler.clear()
        _profiler_lock.release()
        total = sum(counts.values()) or 1
        return {
            "samples": total,
            "profile": [
                {"where": where, "pct": round(100.0 * n / total, 1)}
                for where, n in counts.most_common(int(top))
            ],
        }

    def unsafe_dump_threads() -> dict:
        import sys
        import threading
        import traceback

        frames = sys._current_frames()
        out = {}
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            if frame is not None:
                out[t.name] = traceback.format_stack(frame)[-3:]
        return {"threads": out, "count": len(out)}

    def unsafe_heap_summary(top: int = 20, keep_tracing: bool = False) -> dict:
        import tracemalloc

        if isinstance(keep_tracing, str):
            keep_tracing = keep_tracing.strip().lower() in ("true", "1", "yes")

        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return {"started": True, "note": "call again for a snapshot"}
        snap = tracemalloc.take_snapshot()
        # tracing taxes every allocation — turn it off once snapshotted
        # unless the operator explicitly keeps it for a follow-up diff
        if not keep_tracing:
            tracemalloc.stop()
        stats = snap.statistics("lineno")[: int(top)]
        return {
            "tracing": bool(keep_tracing),
            "top": [
                {"where": str(s.traceback), "kb": round(s.size / 1024, 1)}
                for s in stats
            ],
        }

    def dial_seeds(seeds: str = "") -> dict:
        """UnsafeDialSeeds (reference `rpc/core/net.go:57-69`): dial a
        comma-separated seed list in the background."""
        lst = [s.strip() for s in str(seeds).split(",") if s.strip()]
        if not lst:
            raise RPCError(-32602, "no seeds provided")
        import threading

        for seed in lst:
            threading.Thread(
                target=node.dial_seed, args=(seed,), daemon=True
            ).start()
        return {"log": "Dialing seeds in progress. See /net_info for details"}

    def unsafe_flush_mempool() -> dict:
        """Drop every pending tx (reference `rpc/core/mempool.go`
        UnsafeFlushMempool, route `routes.go:39`)."""
        node.mempool.flush()
        return {"result": "flushed"}

    routes_unsafe = {
        "dial_seeds": dial_seeds,
        "unsafe_flush_mempool": unsafe_flush_mempool,
        "unsafe_start_cpu_profiler": unsafe_start_cpu_profiler,
        "unsafe_stop_cpu_profiler": unsafe_stop_cpu_profiler,
        "unsafe_dump_threads": unsafe_dump_threads,
        "unsafe_heap_summary": unsafe_heap_summary,
    }

    return {
        **(routes_unsafe if node.config.rpc.unsafe else {}),
        "status": status,
        "net_info": net_info,
        "block": block,
        "blockchain": blockchain,
        "commit": commit,
        "full_commit": full_commit,
        "validators": validators,
        "dump_consensus_state": dump_consensus_state,
        "dump_telemetry": dump_telemetry,
        "health": health,
        "abci_query": abci_query,
        "abci_info": abci_info,
        "num_unconfirmed_txs": num_unconfirmed_txs,
        "unconfirmed_txs": unconfirmed_txs,
        "broadcast_tx_async": broadcast_tx_async,
        "broadcast_tx_sync": broadcast_tx_sync,
        "broadcast_tx_commit": broadcast_tx_commit,
        "tx": tx,
        "genesis": genesis,
    }
