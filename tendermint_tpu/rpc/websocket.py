"""WebSocket event subscription (reference `rpc/lib/server/handlers.go:384`
WebsocketManager + `rpc/core/routes.go` subscribe/unsubscribe).

Minimal RFC 6455 implementation over the RPC HTTP server's socket:
clients upgrade at `/websocket`, then speak JSON-RPC —
`{"method":"subscribe","params":{"event":"NewBlock"}}` — and receive
each matching event as a JSON-RPC notification. Supported event names
are the `types.events` constants (NewBlock, NewRound, Vote, Tx, …) and
per-tx keys (`Tx:<hash>`).
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def accept_key(client_key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((client_key + _GUID).encode()).digest()
    ).decode()


# -- frame codec --------------------------------------------------------------


def encode_frame(payload: bytes, opcode: int = 0x1, mask: bool = False) -> bytes:
    """One frame. Servers send unmasked; clients MUST mask (RFC 6455)."""
    import os

    header = bytes([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        header += bytes([mask_bit | n])
    elif n < 65536:
        header += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        header += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if not mask:
        return header + payload
    key = os.urandom(4)
    masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return header + key + masked


def _read_exact(rfile, n: int) -> bytes | None:
    buf = rfile.read(n)
    return buf if buf is not None and len(buf) == n else None


def read_frame(rfile) -> tuple[int, bytes] | None:
    """(opcode, payload) or None on EOF/short read (abrupt disconnect at
    ANY header position ends the stream cleanly instead of raising)."""
    head = _read_exact(rfile, 2)
    if head is None:
        return None
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    n = head[1] & 0x7F
    if n == 126:
        ext = _read_exact(rfile, 2)
        if ext is None:
            return None
        n = struct.unpack(">H", ext)[0]
    elif n == 127:
        ext = _read_exact(rfile, 8)
        if ext is None:
            return None
        n = struct.unpack(">Q", ext)[0]
    if n > 1 << 20:
        return None
    mask = b""
    if masked:
        mask = _read_exact(rfile, 4)
        if mask is None:
            return None
    payload = _read_exact(rfile, n)
    if payload is None:
        return None
    if masked:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, payload


# -- event serialization ------------------------------------------------------


def event_to_json(event: str, data) -> dict:
    """Compact JSON view of the typed event payloads."""
    out: dict = {"event": event}
    block = getattr(data, "block", None)
    if block is not None:
        out["height"] = block.header.height
        out["hash"] = block.hash().hex()
        return out
    header = getattr(data, "header", None)
    if header is not None and hasattr(header, "height"):
        out["height"] = header.height
        return out
    if hasattr(data, "sequence") and hasattr(data, "validator_address"):
        out.update(
            height=data.height,
            round=data.round,
            sequence=data.sequence,
            validator=data.validator_address.hex(),
        )
        return out  # ProposalHeartbeat
    vote = getattr(data, "vote", None)
    if vote is not None:
        out.update(
            height=vote.height,
            round=vote.round,
            type=vote.type,
            index=vote.validator_index,
        )
        return out
    for field in ("height", "round", "step", "tx", "data", "log", "code"):
        v = getattr(data, field, None)
        if v is not None:
            out[field] = v.hex() if isinstance(v, bytes) else v
    return out


# -- per-connection session ---------------------------------------------------


class WSSession:
    """One upgraded connection: subscription bookkeeping + event pump.

    Runs on the HTTP handler's thread (reads frames); event callbacks
    fire from other threads and write under a lock.
    """

    def __init__(self, handler, event_switch) -> None:
        self._handler = handler
        self._events = event_switch
        self._wlock = threading.Lock()
        self._id = f"ws-{id(self):x}"
        self._subs: set[str] = set()
        self._alive = True

    def _send_json(self, obj: dict) -> bool:
        data = encode_frame(json.dumps(obj).encode())
        try:
            with self._wlock:
                self._handler.wfile.write(data)
                self._handler.wfile.flush()
            return True
        except OSError:
            self._alive = False
            return False

    def _on_event(self, event: str, data) -> None:
        if self._alive:
            self._send_json(
                {"jsonrpc": "2.0", "method": "event", "params": event_to_json(event, data)}
            )

    def run(self) -> None:
        try:
            while self._alive:
                frame = read_frame(self._handler.rfile)
                if frame is None:
                    return
                opcode, payload = frame
                if opcode == 0x8:  # close
                    with self._wlock:
                        self._handler.wfile.write(encode_frame(b"", 0x8))
                    return
                if opcode == 0x9:  # ping -> pong
                    with self._wlock:
                        self._handler.wfile.write(encode_frame(payload, 0xA))
                    continue
                if opcode != 0x1:
                    continue
                self._handle_rpc(payload)
        finally:
            self._alive = False
            self._events.remove_listener(self._id)

    def _handle_rpc(self, payload: bytes) -> None:
        try:
            req = json.loads(payload)
            method = req.get("method", "")
            params = req.get("params", {}) or {}
            req_id = req.get("id")
        except (json.JSONDecodeError, AttributeError):
            self._send_json(
                {"jsonrpc": "2.0", "id": None, "error": {"code": -32700, "message": "parse error"}}
            )
            return
        if method == "subscribe":
            event = params.get("event", "")
            if not event:
                self._send_json(
                    {"jsonrpc": "2.0", "id": req_id, "error": {"code": -32602, "message": "missing event"}}
                )
                return
            if event not in self._subs:
                self._subs.add(event)
                self._events.add_listener(
                    self._id, event, lambda d, ev=event: self._on_event(ev, d)
                )
            self._send_json({"jsonrpc": "2.0", "id": req_id, "result": {"subscribed": event}})
        elif method == "unsubscribe":
            event = params.get("event", "")
            self._subs.discard(event)
            self._events.remove_listener(self._id, event)
            self._send_json({"jsonrpc": "2.0", "id": req_id, "result": {"unsubscribed": event}})
        else:
            self._send_json(
                {"jsonrpc": "2.0", "id": req_id, "error": {"code": -32601, "message": f"unknown ws method {method}"}}
            )
