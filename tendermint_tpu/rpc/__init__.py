"""JSON-RPC API layer (reference `rpc/lib` + `rpc/core`)."""

from tendermint_tpu.rpc.server import RPCServer
from tendermint_tpu.rpc.core import make_routes

__all__ = ["RPCServer", "make_routes"]
