"""Minimal JSON-RPC 2.0 server: HTTP POST + GET-URI forms.

Reference `rpc/lib/server/handlers.go:101` (JSON-RPC over POST) and
`:234` (GET with query params). Handlers are plain callables registered
by name with keyword params; results must be JSON-serializable dicts.
WebSocket event subscription lives in `rpc/websocket.py` (RFC 6455
upgrade served off this same listener).
"""

from __future__ import annotations

import json
import socket as socket_mod
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that can sever live connections on stop.

    Long-lived WebSocket upgrades would otherwise outlive `shutdown()`
    (which only stops the accept loop), leaving clients half-open and
    unaware the server is gone."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._live: set = set()
        self._live_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._live_lock:
            self._live.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._live_lock:
            self._live.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._live_lock:
            live = list(self._live)
        for sock in live:
            try:
                sock.shutdown(socket_mod.SHUT_RDWR)
            except OSError:
                pass


class RPCServer:
    def __init__(
        self, routes: dict, laddr: str = "tcp://127.0.0.1:46657", event_switch=None
    ):
        from tendermint_tpu.p2p.tcp import parse_laddr

        self.routes = routes
        host, port = parse_laddr(laddr)
        handler = _make_handler(routes, event_switch)
        self._httpd = _TrackingHTTPServer((host, port), handler)
        self.addr = self._httpd.server_address
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.addr[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        # sever in-flight conns (WS subscribers) so clients see the close
        self._httpd.close_all_connections()


def _make_handler(routes: dict, event_switch=None):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        def _respond(self, obj, status=200):
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _call(self, req_id, method, params):
            import time as time_mod

            from tendermint_tpu.telemetry import metrics as _metrics

            fn = routes.get(method)
            if fn is None:
                _metrics.RPC_REQUESTS.labels(
                    method="<unknown>", result="error"
                ).inc()
                return {
                    "jsonrpc": "2.0",
                    "id": req_id,
                    "error": {"code": -32601, "message": f"unknown method {method}"},
                }
            t0 = time_mod.perf_counter()
            try:
                result = fn(**params) if isinstance(params, dict) else fn(*params)
                _metrics.RPC_SECONDS.labels(method=method).observe(
                    time_mod.perf_counter() - t0
                )
                _metrics.RPC_REQUESTS.labels(method=method, result="ok").inc()
                return {"jsonrpc": "2.0", "id": req_id, "result": result}
            except RPCError as e:
                _metrics.RPC_SECONDS.labels(method=method).observe(
                    time_mod.perf_counter() - t0
                )
                _metrics.RPC_REQUESTS.labels(method=method, result="error").inc()
                return {
                    "jsonrpc": "2.0",
                    "id": req_id,
                    "error": {"code": e.code, "message": e.message},
                }
            except TypeError as e:
                _metrics.RPC_REQUESTS.labels(method=method, result="error").inc()
                return {
                    "jsonrpc": "2.0",
                    "id": req_id,
                    "error": {"code": -32602, "message": f"invalid params: {e}"},
                }
            except Exception as e:
                _metrics.RPC_REQUESTS.labels(method=method, result="error").inc()
                return {
                    "jsonrpc": "2.0",
                    "id": req_id,
                    "error": {"code": -32603, "message": str(e)},
                }

        def do_POST(self):
            length = int(self.headers.get("Content-Length", "0"))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                self._respond(
                    {
                        "jsonrpc": "2.0",
                        "id": None,
                        "error": {"code": -32700, "message": "parse error"},
                    }
                )
                return
            if not isinstance(req, dict):
                self._respond(
                    {
                        "jsonrpc": "2.0",
                        "id": None,
                        "error": {
                            "code": -32600,
                            "message": "request must be a JSON object",
                        },
                    }
                )
                return
            self._respond(
                self._call(req.get("id"), req.get("method", ""), req.get("params", {}))
            )

        def do_GET(self):
            url = urlparse(self.path)
            method = url.path.strip("/")
            if (
                method == "websocket"
                and event_switch is not None
                and "upgrade" in self.headers.get("Connection", "").lower()
            ):
                self._upgrade_websocket()
                return
            if method == "metrics":
                # Prometheus text exposition — plain HTTP, not JSON-RPC,
                # so any scraper can point straight at the RPC listener
                self._serve_metrics()
                return
            if method == "health" and "health" in routes:
                # plain-HTTP readiness probe: the health dict as the raw
                # body, 503 when not ready — load balancers act on the
                # status code, dashboards read the JSON
                self._serve_health()
                return
            if method == "":
                # route listing (reference serves an index page)
                self._respond({"jsonrpc": "2.0", "id": -1, "result": sorted(routes)})
                return
            params = {}
            for k, v in parse_qsl(url.query):
                # keep values as strings except explicit booleans —
                # handlers coerce numerics themselves (json.loads would
                # mangle all-digit hex params like tx/hash/data into ints)
                if v in ("true", "false"):
                    params[k] = v == "true"
                else:
                    params[k] = v.strip('"')
            self._respond(self._call(-1, method, params))

        def _serve_metrics(self):
            from tendermint_tpu.telemetry import REGISTRY
            from tendermint_tpu.telemetry import metrics as _metrics

            body = REGISTRY.prometheus_text().encode()
            _metrics.RPC_REQUESTS.labels(method="metrics", result="ok").inc()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _serve_health(self):
            from tendermint_tpu.telemetry import metrics as _metrics

            try:
                body = routes["health"]()
            except Exception as e:
                _metrics.RPC_REQUESTS.labels(method="health", result="error").inc()
                self._respond({"status": "error", "error": str(e)}, status=500)
                return
            _metrics.RPC_REQUESTS.labels(method="health", result="ok").inc()
            status = 200 if body.get("ready", False) else 503
            self._respond(body, status=status)

        def _upgrade_websocket(self):
            from tendermint_tpu.rpc.websocket import WSSession, accept_key

            key = self.headers.get("Sec-WebSocket-Key", "")
            if not key:
                self.send_error(400, "missing Sec-WebSocket-Key")
                return
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", "websocket")
            self.send_header("Connection", "Upgrade")
            self.send_header("Sec-WebSocket-Accept", accept_key(key))
            self.end_headers()
            self.close_connection = True
            WSSession(self, event_switch).run()

    return Handler
