"""tendermint_tpu — a TPU-native BFT state-machine-replication framework.

A ground-up rebuild of the capabilities of Tendermint Core v0.12.1
(reference: /root/reference, Go) designed TPU-first:

- **Hot numeric plane** (`tendermint_tpu.ops`, `tendermint_tpu.parallel`):
  ed25519 batch signature verification, SHA-256/SHA-512/RIPEMD-160 hashing and
  Merkle tree reduction as JAX/Pallas kernels — pure, fixed-shape, integer-only,
  deterministic, sharded over `jax.sharding.Mesh` for multi-chip scale.
- **Control plane** (host Python + C++): consensus state machine, WAL,
  mempool, p2p gossip, RPC, storage — async IO around an event-sourced
  functional core.

The seam between the two planes is `crypto.BatchVerifier` / `merkle.TreeHasher`
— the exact interface positions occupied by `crypto.PubKey.VerifyBytes` and
`tmlibs/merkle.SimpleHash*` in the reference (see SURVEY.md §2b).
"""

from tendermint_tpu.version import __version__

__all__ = ["__version__"]
