"""ed25519 keys and host-side sign/verify.

Fills the slot of go-crypto's `PrivKeyEd25519`/`PubKeyEd25519`/`Signature`
(reference call sites: `types/priv_validator.go:92` signing,
`types/vote_set.go:177` and `types/validator_set.go:253` verification).
Host path wraps the `cryptography` library when available and degrades
to the pure-Python RFC 8032 backend (`crypto/ed25519_ref.py`) when that
import fails — same shape as the device→host dispatch in
`services/resilient.py`. The batched device path lives in
`tendermint_tpu.ops.ed25519_kernel` and is cross-validated against this
one.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pure-Python fallback backend
    HAVE_CRYPTOGRAPHY = False

from tendermint_tpu.crypto.hashing import address_hash

PRIVKEY_SEED_LEN = 32
PUBKEY_LEN = 32
SIGNATURE_LEN = 64

# pubkey -> unsafe? memo (keys repeat heavily: valset members, signed-tx
# senders); bounded so an attacker cycling fresh garbage keys cannot
# grow it without limit
_UNSAFE_PK_CACHE: dict[bytes, bool] = {}
_UNSAFE_PK_CACHE_MAX = 8192


def _unsafe_pubkey(pub: bytes) -> bool:
    """Small-order / non-canonical screen (ed25519_ref.is_small_order),
    memoized per key."""
    v = _UNSAFE_PK_CACHE.get(pub)
    if v is None:
        from tendermint_tpu.crypto import ed25519_ref

        v = ed25519_ref.is_small_order(pub)
        if len(_UNSAFE_PK_CACHE) >= _UNSAFE_PK_CACHE_MAX:
            _UNSAFE_PK_CACHE.clear()
        _UNSAFE_PK_CACHE[pub] = v
    return v


@dataclass(frozen=True)
class PubKey:
    """32-byte ed25519 public key."""

    data: bytes

    def __post_init__(self) -> None:
        if len(self.data) != PUBKEY_LEN:
            raise ValueError(f"pubkey must be {PUBKEY_LEN} bytes, got {len(self.data)}")

    def verify(self, msg: bytes, signature: bytes) -> bool:
        """One-at-a-time host verification (the slow reference path)."""
        if len(signature) != SIGNATURE_LEN:
            return False
        # Small-order / non-canonical keys are keyless-forgery inputs
        # (the zero key "verifies" ~1/4 of messages through library
        # cofactorless verifies) — screened HERE so every consumer of
        # the host path is covered, library or reference backend alike.
        if _unsafe_pubkey(self.data):
            return False
        if not HAVE_CRYPTOGRAPHY:
            from tendermint_tpu.crypto import ed25519_ref

            return ed25519_ref.verify(self.data, msg, signature)
        try:
            Ed25519PublicKey.from_public_bytes(self.data).verify(signature, msg)
            return True
        except InvalidSignature:
            return False
        except Exception:
            return False

    @property
    def address(self) -> bytes:
        return address_hash(self.data)

    def __bytes__(self) -> bytes:
        return self.data

    def hex(self) -> str:
        return self.data.hex()


@dataclass(frozen=True)
class PrivKey:
    """ed25519 private key from a 32-byte seed (RFC 8032 style)."""

    seed: bytes

    def __post_init__(self) -> None:
        if len(self.seed) != PRIVKEY_SEED_LEN:
            raise ValueError(f"privkey seed must be {PRIVKEY_SEED_LEN} bytes")

    def _key(self) -> "Ed25519PrivateKey":
        return Ed25519PrivateKey.from_private_bytes(self.seed)

    def sign(self, msg: bytes) -> bytes:
        if not HAVE_CRYPTOGRAPHY:
            from tendermint_tpu.crypto import ed25519_ref

            return ed25519_ref.sign(self.seed, msg)
        return self._key().sign(msg)

    @property
    def pub_key(self) -> PubKey:
        if not HAVE_CRYPTOGRAPHY:
            from tendermint_tpu.crypto import ed25519_ref

            return PubKey(ed25519_ref.public_from_seed(self.seed))
        raw = self._key().public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        return PubKey(raw)

    def __repr__(self) -> str:  # never leak the seed
        return f"PrivKey(pub={self.pub_key.hex()[:16]}…)"


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    """Generate a key; pass a fixed seed for deterministic test fixtures."""
    return PrivKey(seed if seed is not None else os.urandom(PRIVKEY_SEED_LEN))
