"""Pure-Python ed25519 (RFC 8032) — the no-dependency fallback backend.

`crypto/keys.py` prefers the `cryptography` library (OpenSSL-backed, the
fast host path) and degrades to this module when that import fails —
the same graceful-degradation shape as the device→host dispatch in
`services/resilient.py`: correctness is never hostage to an optional
dependency, only speed is.

Verification is COFACTORLESS ([S]B - [h]A == R by encoding compare),
matching both OpenSSL's behavior and the batched device kernel
(`ops/ed25519_kernel.py`), so verdicts are identical across all three
backends. Arithmetic uses Python ints in extended homogeneous
coordinates — ~1-3 ms per operation, three orders slower than OpenSSL
but bit-compatible and fast enough for tests and light control-plane
use.
"""

from __future__ import annotations

import functools as _functools
import hashlib

P = 2**255 - 19  # field prime
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P  # curve constant -121665/121666

# base point
_BY = 4 * pow(5, P - 2, P) % P
_BX: int


def _recover_x(y: int, sign: int) -> int | None:
    """x from y per RFC 8032 §5.1.3; None when no square root exists."""
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P)
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
        if (x * x - x2) % P != 0:
            return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
_B = (_BX, _BY, 1, _BX * _BY % P)  # extended (X, Y, Z, T), Z=1
_IDENT = (0, 1, 1, 0)


def _pt_add(p, q):
    """add-2008-hwcd-3 on extended coordinates (a=-1 twist form)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * D % P * t2 % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _pt_double(p):
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = a + b
    e = h - (x1 + y1) * (x1 + y1)
    g = a - b
    f = c + g
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _scalar_mult(s: int, p) -> tuple:
    acc = _IDENT
    while s:
        if s & 1:
            acc = _pt_add(acc, p)
        p = _pt_double(p)
        s >>= 1
    return acc


# -- speed: fixed-base comb + windowed variable-base ---------------------------
#
# Pure-Python point ops cost ~5 us each; the naive double-and-add burns
# ~770 of them per verify. Two classic precomputation tricks cut that to
# ~350 (and [S]B to 32 adds flat), which is what makes this fallback
# usable for signature-heavy test suites, not just smoke tests:
#
# * fixed-base comb for B: 32 radix-256 digit tables, [S]B = <=32 adds,
#   zero doublings (tables built lazily once per process);
# * window-4 multiplication for the variable base A: 15 precomputed
#   odd+even multiples, 63 nibbles msb-first -> 252 doublings + <=63
#   adds instead of 253 doublings + ~127 adds.

_BASE_COMB: list[list[tuple]] | None = None


def _base_comb() -> list[list[tuple]]:
    global _BASE_COMB
    if _BASE_COMB is None:
        tables = []
        base = _B
        for _ in range(32):
            row = [_IDENT]
            acc = _IDENT
            for _d in range(255):
                acc = _pt_add(acc, base)
                row.append(acc)
            tables.append(row)
            for _ in range(8):
                base = _pt_double(base)
        _BASE_COMB = tables
    return _BASE_COMB


def _mult_base(s: int) -> tuple:
    """[s]B via the comb: one table add per radix-256 digit."""
    tables = _base_comb()
    acc = _IDENT
    i = 0
    while s:
        d = s & 0xFF
        if d:
            acc = _pt_add(acc, tables[i][d])
        s >>= 8
        i += 1
    return acc


def _mult_var(s: int, p) -> tuple:
    """[s]p for an arbitrary point: fixed window of 4 bits."""
    pre = [_IDENT, p]
    for _d in range(2, 16):
        pre.append(_pt_add(pre[-1], p))
    acc = _IDENT
    started = False
    for shift in range(252, -4, -4):
        if started:
            acc = _pt_double(_pt_double(_pt_double(_pt_double(acc))))
        d = (s >> shift) & 0xF
        if d:
            acc = _pt_add(acc, pre[d])
            started = True
    return acc


def _encode_point(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return ((y | ((x & 1) << 255))).to_bytes(32, "little")


def _decode_point(data: bytes) -> tuple | None:
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, enc >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def is_small_order(pub: bytes) -> bool:
    """True when `pub` is NOT a safe signing identity: undecodable,
    non-canonical (y >= p), or a torsion point of order dividing 8.

    Small-order keys are the classic ed25519 adversarial input: for the
    all-zero key (order 4), h = H(R, A, M) mod 4 lands on 0 for ~1/4 of
    messages, making (zero key, zero sig) "verify" against arbitrary
    payloads through cofactorless library verifies — a keyless forgery.
    Every verify path in this package screens signer keys through this
    check (found by the Byzantine garbage-sig flood harness, ISSUE 9)."""
    if len(pub) != 32:
        return True
    if (int.from_bytes(pub, "little") & ((1 << 255) - 1)) >= P:
        return True  # non-canonical encoding
    pt = _decode_point(pub)
    if pt is None:
        return True
    q = _pt_double(_pt_double(_pt_double(pt)))  # [8]A
    # identity in extended coordinates: X/Z == 0 and Y/Z == 1
    return q[0] % P == 0 and (q[1] - q[2]) % P == 0


def _sha512_int(*chunks: bytes) -> int:
    h = hashlib.sha512()
    for c in chunks:
        h.update(c)
    return int.from_bytes(h.digest(), "little")


@_functools.lru_cache(maxsize=1024)
def _expand_seed(seed: bytes) -> tuple[int, bytes, bytes]:
    """Seed -> (clamped scalar a, prefix, pub) per RFC 8032 §5.1.5.
    Cached: signers (priv validators) hash + derive once per process."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:], _encode_point(_mult_base(a))


def public_from_seed(seed: bytes) -> bytes:
    return _expand_seed(seed)[2]


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix, pub = _expand_seed(seed)
    r = _sha512_int(prefix, msg) % L
    r_enc = _encode_point(_mult_base(r))
    h = _sha512_int(r_enc, pub, msg) % L
    s = (r + h * a) % L
    return r_enc + s.to_bytes(32, "little")


@_functools.lru_cache(maxsize=8192)
def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Cofactorless verify: encode([S]B - [h]A) == R bytes.

    Memoized — verification is a pure function, and consensus re-checks
    the same (commit, valset) triples across WAL replay, catchup gossip,
    and store reloads; repeats must not re-pay ~2 ms each.
    """
    if len(sig) != 64:
        return False
    if is_small_order(pub):  # keyless-forgery screen (see is_small_order)
        return False
    a_pt = _decode_point(pub)
    if a_pt is None:
        return False
    r_enc, s_enc = sig[:32], sig[32:]
    s = int.from_bytes(s_enc, "little")
    if s >= L:  # malleability check, same as OpenSSL / the device kernel
        return False
    h = _sha512_int(r_enc, pub, msg) % L
    neg_a = (P - a_pt[0], a_pt[1], a_pt[2], P - a_pt[3])
    check = _pt_add(_mult_base(s), _mult_var(h, neg_a))
    return _encode_point(check) == r_enc
