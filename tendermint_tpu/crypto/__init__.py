"""Crypto primitives and the batch-verification seam.

Host-side keys/signing wrap the `cryptography` library (the role go-crypto's
`PrivKeyEd25519/PubKeyEd25519` play in the reference, SURVEY.md §2b). The
TPU-facing surface is `BatchVerifier` (`tendermint_tpu.crypto.batch`):
accumulate (pubkey, message, signature) triples, flush as one batched
ed25519 verification on device — replacing the reference's one-at-a-time
`PubKey.VerifyBytes` calls at `types/vote_set.go:177` and
`types/validator_set.go:253`.
"""

from tendermint_tpu.crypto.hashing import ADDRESS_LEN, address_hash, ripemd160, sha256, tmhash
from tendermint_tpu.crypto.keys import (
    PRIVKEY_SEED_LEN,
    PUBKEY_LEN,
    SIGNATURE_LEN,
    PrivKey,
    PubKey,
    gen_priv_key,
)

__all__ = [
    "PrivKey",
    "PubKey",
    "gen_priv_key",
    "sha256",
    "ripemd160",
    "tmhash",
    "address_hash",
    "ADDRESS_LEN",
    "PUBKEY_LEN",
    "SIGNATURE_LEN",
    "PRIVKEY_SEED_LEN",
]
