"""Host hash functions.

The reference hashes with RIPEMD-160 everywhere (Merkle nodes, part hashes,
addresses — `docs/specification/merkle.rst:52-90`, `types/part_set.go:36-40`).
This framework's native algorithm is SHA-256 (the TPU kernel target per
BASELINE.md) with RIPEMD-160 retained as a compatibility variant; both have
batched TPU implementations in `tendermint_tpu.ops`.
"""

from __future__ import annotations

import hashlib

ADDRESS_LEN = 20

# Default tree/leaf hash algorithm for the whole framework.
DEFAULT_ALGO = "sha256"


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def ripemd160(data: bytes) -> bytes:
    h = hashlib.new("ripemd160")
    h.update(data)
    return h.digest()


def tmhash(data: bytes, algo: str = DEFAULT_ALGO) -> bytes:
    """The framework hash: SHA-256 (32B) by default, RIPEMD-160 (20B) compat."""
    if algo == "sha256":
        return sha256(data)
    if algo == "ripemd160":
        return ripemd160(data)
    raise ValueError(f"unknown hash algo {algo!r}")


def address_hash(pubkey_bytes: bytes) -> bytes:
    """Validator/node address = first 20 bytes of SHA-256 of the raw pubkey.

    (Reference derives addresses by RIPEMD-160 of the go-wire-encoded pubkey;
    we define the analogous deterministic 20-byte address natively.)
    """
    return sha256(pubkey_bytes)[:ADDRESS_LEN]
