"""Multi-host data plane: the jax.distributed-over-DCN seam
(SURVEY.md §5.8).

The reference scales its data plane with NCCL/MPI-style backends; the
TPU-native equivalent is a PROCESS-SPANNING `jax.sharding.Mesh`: every
host runs this same program, `jax.distributed.initialize` wires the
hosts into one runtime, and the existing `shard_map` steps in
`parallel/mesh.py` (batch-sharded generic verify, validator-sharded
table verify, psum power tallies) compile unchanged over the global
mesh — XLA routes the collectives over ICI within a slice and DCN
across hosts. Nothing in the verification code is single-host-specific;
this module is the composition seam:

    # on every host (same code, per-host coordinator/process args):
    from tendermint_tpu.parallel import distributed as dist
    dist.initialize(coordinator="host0:8476", num_processes=4,
                    process_id=<rank>)
    mesh = dist.global_batch_mesh()          # all chips on all hosts
    step = sharded_tables_verify_and_tally(mesh)
    ...                                      # identical from here on

Host-side inputs must be GLOBAL arrays: use `host_local_to_global` to
assemble a jax.Array from per-host shards (each host supplies only the
lanes of its own validators — the same shard-major layout
`shard_lanes_validator_major` produces).

There is no multi-host hardware in the bench environment, so this seam
is exercised degenerately (1 process) by tests; the mesh/step code it
feeds is the same code the 8-device virtual mesh and the driver's
multichip dryrun run.
"""

from __future__ import annotations

import numpy as np

_init_mode: str | None = None  # None | "local" | "multi"


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Wire this process into the multi-host runtime.

    No-op when called with no arguments in a single-process setup (the
    common test/bench path), so call sites can run the same code on one
    host or many. Idempotent for the SAME mode; a multi-host request
    after a local init raises instead of silently running local-only
    (every host would otherwise verify just its own shard while
    believing the mesh is global)."""
    global _init_mode
    want_multi = coordinator is not None or (
        num_processes is not None and num_processes > 1
    )
    if _init_mode is not None:
        if want_multi and _init_mode == "local":
            raise RuntimeError(
                "distributed.initialize: already initialized single-process; "
                "multi-host init must happen before any local initialize()"
            )
        return
    if not want_multi:
        _init_mode = "local"  # single-process: nothing to wire
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _init_mode = "multi"


def global_batch_mesh():
    """1-D mesh over EVERY device of EVERY process (jax.devices() is
    global after jax.distributed.initialize)."""
    from tendermint_tpu.parallel.mesh import batch_mesh

    return batch_mesh()


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) when single-process."""
    import jax

    return jax.process_index(), jax.process_count()


def host_local_to_global(mesh, spec, host_local: np.ndarray):
    """Assemble a global jax.Array from this host's shard.

    `host_local` is the slice of the global array this process owns
    under `spec` (e.g. its own validators' lanes in shard-major order).
    Single-process meshes just device_put with the sharding — the SAME
    call works in both topologies, which is what makes the step
    functions topology-agnostic."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(host_local, sharding)
    # multi-host: each process contributes its addressable shards; a
    # fully-replicated spec means every host holds the whole array
    global_shape = list(host_local.shape)
    axis = next((i for i, name in enumerate(spec) if name is not None), None)
    if axis is not None:
        # assumes the sharded axis is split exactly process_count ways
        # (one contiguous block per host — the layout
        # shard_lanes_validator_major produces); other layouts must call
        # jax.make_array_from_process_local_data themselves
        global_shape[axis] *= jax.process_count()
    return jax.make_array_from_process_local_data(
        sharding, host_local, tuple(global_shape)
    )
