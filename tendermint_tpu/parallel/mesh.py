"""Device-mesh sharding for the verification data plane.

One logical commit (N validator signatures + voting powers) is sharded
along the batch axis across every chip in the mesh; each chip runs the
ed25519 ladder on its shard and the >2/3 power tally is reduced with a
single `psum` over ICI — the collective replaces the reference's
sequential accumulate in `types/validator_set.go:236-261`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from tendermint_tpu.ops.ed25519_kernel import verify_kernel
from tendermint_tpu.ops.ed25519_tables import verify_tables_kernel

BATCH_AXIS = "batch"


def batch_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, batch-sharded."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def sharded_verify_kernel(mesh: Mesh):
    """Compile a batch-sharded verify: (B,32)x4 uint8 -> (B,) bool.

    B must be divisible by the mesh size; callers pad with zero rows
    and slice the output back to the real count (zero rows verify
    False — see pad_to_multiple).
    """
    spec = P(BATCH_AXIS)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=spec,
    )
    def _verify(pub, r, s, h):
        return verify_kernel(pub, r, s, h)

    return _verify


def sharded_verify_and_tally(mesh: Mesh):
    """Compile the full commit-verification step over the mesh.

    Inputs: (B,32)x4 uint8 sig batch + (B,) int32 voting powers.
    Returns ((B,) bool verdicts, () int32 verified-power total) — the
    total is psum-reduced across chips so every shard holds the global
    tally (the 2/3-quorum decision needs no host gather).
    """
    spec = P(BATCH_AXIS)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=(spec, P()),
    )
    def _step(pub, r, s, h, power):
        ok = verify_kernel(pub, r, s, h)
        # int32 tally: JAX x64 is globally disabled; callers with >2^31
        # total power must scale powers down before shipping them.
        local = jnp.sum(jnp.where(ok, power, 0).astype(jnp.int32))
        total = jax.lax.psum(local, BATCH_AXIS)
        return ok, total

    return _step


def sharded_tables_verify_and_tally(mesh: Mesh):
    """Compile the TABLE fast path — the production steady-state kernel
    (`ops.ed25519_tables`) — over the mesh.

    Sharding is along the VALIDATOR axis: each device holds 1/ndev of the
    comb-table columns (tables (64, 16, 60, N) int16 sharded on the last
    axis — 1.25 GB at N=10k splits to ~160 MB/chip) plus the lanes of its
    own validators for all K stacked commits. Lane arrays must be in
    shard-major order (see shard_lanes_validator_major); the >2/3 power
    tally is psum-reduced so every shard holds the global total.

    Inputs: tables (64, 16, 60, N) int16; s/h/r (K*N, 32) uint8; lane_ok
    (K*N,) bool — the host precheck AND the table build's key_ok tiled
    over commits (an invalid-key table column degrades to a forgeable
    check, so it MUST be masked in-device before the tally); powers
    (K*N,) int32. ALL lane arrays — s, h, r, lane_ok, powers — must be
    in the same shard-major order (shard_lanes_validator_major).
    Returns ((K*N,) bool shard-major verdicts, () int32 global tally).
    """
    lane_spec = P(BATCH_AXIS)
    tbl_spec = P(None, None, None, BATCH_AXIS)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(tbl_spec,) + (lane_spec,) * 5,
        out_specs=(lane_spec, P()),
    )
    def _step(tables, s, h, r, lane_ok, power):
        ok = verify_tables_kernel(tables, s, h, r) & lane_ok
        local = jnp.sum(jnp.where(ok, power, 0).astype(jnp.int32))
        total = jax.lax.psum(local, BATCH_AXIS)
        return ok, total

    return _step


def shard_lanes_validator_major(arrays, n_vals: int, n_shards: int):
    """Reorder commit-major lanes (lane = c*N + v, the
    prepare_commit_lanes layout) into shard-major order (shard, commit,
    local validator) so a P(batch) sharding of the lane axis hands every
    device exactly the lanes of its own table columns. N must divide
    evenly into n_shards blocks."""
    if n_vals % n_shards:
        raise ValueError(f"n_vals {n_vals} not divisible by {n_shards} shards")
    out = []
    for a in arrays:
        k = a.shape[0] // n_vals
        a2 = a.reshape((k, n_shards, n_vals // n_shards) + a.shape[1:])
        out.append(
            np.ascontiguousarray(np.moveaxis(a2, 1, 0)).reshape(a.shape)
        )
    return out


def unshard_lanes_validator_major(a, n_vals: int, n_shards: int):
    """Inverse of shard_lanes_validator_major (device order -> commit-major)."""
    k = a.shape[0] // n_vals
    a2 = a.reshape((n_shards, k, n_vals // n_shards) + a.shape[1:])
    return np.ascontiguousarray(np.moveaxis(a2, 0, 1)).reshape(a.shape)


def pad_to_multiple(arrays, powers, multiple: int):
    """Pad (B,32) byte arrays + (B,) powers up to a multiple of `multiple`.

    Padding rows are zeros. A zero row does decode (y=0 is a valid
    order-4 point) but still verifies False because S=h=0 makes the
    ladder produce the identity, which never equals the decoded R point
    (0, 1) != (±sqrt(-1), 0); powers are zero too, so the psum tally is
    unaffected either way. Don't replace zero padding with copied rows —
    those WOULD verify True and corrupt the tally if given power.
    """
    b = arrays[0].shape[0]
    size = ((b + multiple - 1) // multiple) * multiple
    if size == b:
        return arrays, powers, b
    pad = size - b
    arrays = [np.concatenate([a, np.zeros((pad, 32), dtype=np.uint8)]) for a in arrays]
    powers = np.concatenate([powers, np.zeros(pad, dtype=powers.dtype)])
    return arrays, powers, b
