"""Device-mesh sharding for the verification data plane.

One logical commit (N validator signatures + voting powers) is sharded
along the batch axis across every chip in the mesh; each chip runs the
ed25519 ladder on its shard and the >2/3 power tally is reduced with a
single `psum` over ICI — the collective replaces the reference's
sequential accumulate in `types/validator_set.go:236-261`.

`MeshManager` is the production lifecycle around those kernels: device
discovery (capped by TENDERMINT_TPU_MESH_DEVICES), per-device-set
compiled-step caching, and the survivor re-mesh cycle — a per-shard
device fault (`utils.fail.ShardDeviceFault`, injected via
`TENDERMINT_TPU_DEVICE_FAIL=shard<i>`) drops that chip from the mesh
and recompiles over the survivors, so the verify spine keeps serving on
N-1 chips instead of falling all the way back to host crypto; a
re-probe window later the full mesh is restored. Only when NO devices
survive does the launch raise out to the `CircuitBreaker` in
`services/resilient.py` (host fallback, the PR 1 degradation ladder).
"""

from __future__ import annotations

import os
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from tendermint_tpu.ops.ed25519_kernel import verify_kernel
from tendermint_tpu.ops.ed25519_tables import verify_tables_kernel

BATCH_AXIS = "batch"


def batch_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, batch-sharded."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def sharded_verify_kernel(mesh: Mesh):
    """Compile a batch-sharded verify: (B,32)x4 uint8 -> (B,) bool.

    B must be divisible by the mesh size; callers pad with zero rows
    and slice the output back to the real count (zero rows verify
    False — see pad_to_multiple).
    """
    spec = P(BATCH_AXIS)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=spec,
    )
    def _verify(pub, r, s, h):
        return verify_kernel(pub, r, s, h)

    return _verify


def sharded_verify_and_tally(mesh: Mesh):
    """Compile the full commit-verification step over the mesh.

    Inputs: (B,32)x4 uint8 sig batch + (B,) int32 voting powers.
    Returns ((B,) bool verdicts, () int32 verified-power total) — the
    total is psum-reduced across chips so every shard holds the global
    tally (the 2/3-quorum decision needs no host gather).
    """
    spec = P(BATCH_AXIS)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=(spec, P()),
    )
    def _step(pub, r, s, h, power):
        ok = verify_kernel(pub, r, s, h)
        # int32 tally: JAX x64 is globally disabled; callers with >2^31
        # total power must scale powers down before shipping them.
        local = jnp.sum(jnp.where(ok, power, 0).astype(jnp.int32))
        total = jax.lax.psum(local, BATCH_AXIS)
        return ok, total

    return _step


def sharded_tables_verify_and_tally(mesh: Mesh):
    """Compile the TABLE fast path — the production steady-state kernel
    (`ops.ed25519_tables`) — over the mesh.

    Sharding is along the VALIDATOR axis: each device holds 1/ndev of the
    comb-table columns (tables (64, 16, 60, N) int16 sharded on the last
    axis — 1.25 GB at N=10k splits to ~160 MB/chip) plus the lanes of its
    own validators for all K stacked commits. Lane arrays must be in
    shard-major order (see shard_lanes_validator_major); the >2/3 power
    tally is psum-reduced so every shard holds the global total.

    Inputs: tables (64, 16, 60, N) int16; s/h/r (K*N, 32) uint8; lane_ok
    (K*N,) bool — the host precheck AND the table build's key_ok tiled
    over commits (an invalid-key table column degrades to a forgeable
    check, so it MUST be masked in-device before the tally); powers
    (K*N,) int32. ALL lane arrays — s, h, r, lane_ok, powers — must be
    in the same shard-major order (shard_lanes_validator_major).
    Returns ((K*N,) bool shard-major verdicts, () int32 global tally).
    """
    lane_spec = P(BATCH_AXIS)
    tbl_spec = P(None, None, None, BATCH_AXIS)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(tbl_spec,) + (lane_spec,) * 5,
        out_specs=(lane_spec, P()),
    )
    def _step(tables, s, h, r, lane_ok, power):
        ok = verify_tables_kernel(tables, s, h, r) & lane_ok
        local = jnp.sum(jnp.where(ok, power, 0).astype(jnp.int32))
        total = jax.lax.psum(local, BATCH_AXIS)
        return ok, total

    return _step


def shard_lanes_validator_major(arrays, n_vals: int, n_shards: int):
    """Reorder commit-major lanes (lane = c*N + v, the
    prepare_commit_lanes layout) into shard-major order (shard, commit,
    local validator) so a P(batch) sharding of the lane axis hands every
    device exactly the lanes of its own table columns. N must divide
    evenly into n_shards blocks."""
    if n_vals % n_shards:
        raise ValueError(f"n_vals {n_vals} not divisible by {n_shards} shards")
    out = []
    for a in arrays:
        k = a.shape[0] // n_vals
        a2 = a.reshape((k, n_shards, n_vals // n_shards) + a.shape[1:])
        out.append(
            np.ascontiguousarray(np.moveaxis(a2, 1, 0)).reshape(a.shape)
        )
    return out


def unshard_lanes_validator_major(a, n_vals: int, n_shards: int):
    """Inverse of shard_lanes_validator_major (device order -> commit-major)."""
    k = a.shape[0] // n_vals
    a2 = a.reshape((n_shards, k, n_vals // n_shards) + a.shape[1:])
    return np.ascontiguousarray(np.moveaxis(a2, 0, 1)).reshape(a.shape)


def mesh_device_count() -> int:
    """Devices the verify mesh should span on this backend.

    TENDERMINT_TPU_MESH_DEVICES: unset/0 = every visible device,
    1 = force the single-device legacy path, N = cap at N. The knob is
    what lets CPU CI (8 virtual devices via
    --xla_force_host_platform_device_count) opt IN and a multi-chip TPU
    host opt OUT."""
    try:
        have = len(jax.devices())
    except Exception:
        return 1
    knob = int(os.environ.get("TENDERMINT_TPU_MESH_DEVICES", "0"))
    if knob <= 0:
        return have
    return min(knob, have)


class MeshExhaustedError(RuntimeError):
    """Every device of the mesh has faulted out; the caller's breaker
    owns the next step (host fallback)."""


def _host_verify_prepared_rows(pub, r, s, h) -> np.ndarray:
    """Bit-faithful host evaluation of the device verify equation
    ([S]B + [h](-A) == R, cofactorless) over prepared (B, 32) rows —
    the `executor="host"` stand-in that lets mesh *choreography* (pad
    geometry, shard faults, survivor re-mesh) run tier-1 on CPU without
    an XLA kernel compile. All-zero pad rows short-circuit to False,
    matching the kernel property documented on `pad_to_multiple`."""
    from tendermint_tpu.crypto import ed25519_ref as ref

    n = pub.shape[0]
    out = np.zeros(n, dtype=bool)
    for i in range(n):
        row_pub, row_r = bytes(pub[i]), bytes(r[i])
        row_s, row_h = bytes(s[i]), bytes(h[i])
        if row_pub == b"\x00" * 32 and row_r == b"\x00" * 32:
            continue  # zero pad row: verifies False by construction
        a_pt = ref._decode_point(row_pub)
        r_pt = ref._decode_point(row_r)
        if a_pt is None or r_pt is None:
            continue
        s_int = int.from_bytes(row_s, "little")
        h_int = int.from_bytes(row_h, "little")
        neg_a = (
            ref.P - a_pt[0],
            a_pt[1],
            a_pt[2],
            ref.P - a_pt[3],
        )
        check = ref._pt_add(ref._mult_base(s_int), ref._mult_var(h_int, neg_a))
        out[i] = ref._encode_point(check) == row_r
    return out


# Compiled sharded steps keyed by (executor, device tuple, program) so
# every MeshManager in the process (default verifier stack, tests,
# bench) shares one compile per device set — a survivor re-mesh costs
# ONE recompile process-wide, and restoring the full mesh is free.
_STEP_CACHE: dict = {}
_STEP_LOCK = threading.Lock()
# compiles currently building under _STEP_LOCK — the `/health` device
# section's "compile in progress" flag (read without the lock: a
# single-int read is atomic enough for a health probe)
_COMPILES_IN_PROGRESS = 0


def compiles_in_progress() -> int:
    """Compiled-step builds running right now (0 or 1 — builds serialize
    on the step-cache lock). Health reports it so a load balancer can
    tell a compile stall from a dead device."""
    return _COMPILES_IN_PROGRESS


class MeshManager:
    """Mesh lifecycle: discovery, step compilation, survivor re-mesh.

    One manager is shared by the verifier and hasher mesh lanes of a
    process (they degrade together — a sick chip is sick for every
    kernel). Thread-safe: launches from the dispatch worker, re-probes,
    and telemetry snapshots may interleave.

    `executor="host"` swaps the compiled shard_map steps for host
    evaluations with identical verdict semantics and the SAME fault /
    re-mesh choreography — the CPU-CI seam (tests, nemesis chaos) where
    an XLA:CPU kernel compile would cost minutes.
    """

    def __init__(
        self,
        devices=None,
        executor: str = "device",
        reprobe_s: float | None = None,
    ) -> None:
        if executor not in ("device", "host"):
            raise ValueError(f"unknown mesh executor {executor!r}")
        self.executor = executor
        if devices is None:
            devices = list(jax.devices())[: mesh_device_count()]
        self._all = list(devices)
        if not self._all:
            raise ValueError("mesh needs at least one device")
        self._excluded: set[int] = set()
        self._last_fault = 0.0
        self._reprobe_s = (
            float(os.environ.get("TENDERMINT_TPU_MESH_REPROBE_S", "5.0"))
            if reprobe_s is None
            else reprobe_s
        )
        self._lock = threading.RLock()
        self._bind_gauge()

    def _bind_gauge(self) -> None:
        from tendermint_tpu.telemetry import metrics as _metrics

        _metrics.MESH_DEVICES.set(self.n_active)

    # -- topology ----------------------------------------------------------

    @property
    def n_total(self) -> int:
        return len(self._all)

    @property
    def n_active(self) -> int:
        return len(self._all) - len(self._excluded)

    @property
    def degraded(self) -> bool:
        return bool(self._excluded)

    def active_indices(self) -> tuple[int, ...]:
        with self._lock:
            return tuple(
                i for i in range(len(self._all)) if i not in self._excluded
            )

    def mesh(self) -> Mesh:
        with self._lock:
            return batch_mesh([self._all[i] for i in self.active_indices()])

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "executor": self.executor,
                "devices_total": self.n_total,
                "devices_active": self.n_active,
                "excluded": sorted(self._excluded),
            }

    # -- fault / re-mesh cycle ---------------------------------------------

    def check_shard_faults(self) -> None:
        """Injected per-shard fault gate — called at the top of every
        mesh launch with the ACTIVE device indices, so an armed
        `shard<i>` spec only fires while chip i is in the mesh."""
        from tendermint_tpu.utils.fail import shard_fail_point

        shard_fail_point(self.active_indices())

    def record_shard_fault(self, shard: int) -> bool:
        """Drop `shard` from the mesh; True while survivors remain.
        False means the mesh is exhausted — the caller raises to its
        breaker and host crypto takes over."""
        import logging

        from tendermint_tpu.telemetry import metrics as _metrics
        from tendermint_tpu.utils.log import kv, logger

        from tendermint_tpu.telemetry import tracectx as _tracectx
        from tendermint_tpu.telemetry.flightrec import FLIGHT

        with self._lock:
            _metrics.MESH_SHARD_FAULTS.inc()
            self._last_fault = time.monotonic()
            if shard in self._excluded:
                return self.n_active > 0
            self._excluded.add(shard)
            survivors = self.n_active
            if survivors > 0:
                _metrics.MESH_REMESH.labels(direction="shrink").inc()
            self._bind_gauge()
        # a mesh transition is a forensic moment: black-box it and
        # sample everything for a window (same policy as breaker trips)
        FLIGHT.record("mesh", event="shard_fault", shard=shard, survivors=survivors)
        _tracectx.boost()
        kv(
            logger("mesh"),
            logging.WARNING,
            "mesh shard fault",
            shard=shard,
            survivors=survivors,
            total=self.n_total,
        )
        return survivors > 0

    def maybe_reprobe(self) -> None:
        """Restore the full mesh once the re-probe window has passed
        since the last shard fault. Shards whose injected fault is
        still armed stay excluded (the peek costs no budget); a REAL
        recovered chip simply starts serving again — if it is still
        sick the next launch's fault re-excludes it, which is the
        probe."""
        from tendermint_tpu.telemetry import metrics as _metrics
        from tendermint_tpu.utils.fail import shard_fault_armed

        with self._lock:
            if not self._excluded:
                return
            if time.monotonic() - self._last_fault < self._reprobe_s:
                return
            recovered = {
                i for i in self._excluded if not shard_fault_armed(i)
            }
            if not recovered:
                self._last_fault = time.monotonic()  # re-arm the window
                return
            self._excluded -= recovered
            _metrics.MESH_REMESH.labels(direction="restore").inc()
            self._bind_gauge()
        from tendermint_tpu.telemetry.flightrec import FLIGHT

        FLIGHT.record(
            "mesh", event="restore", recovered=sorted(recovered), active=self.n_active
        )

    def reset(self) -> None:
        """Forget all exclusions (tests)."""
        with self._lock:
            self._excluded.clear()
            self._bind_gauge()

    # -- compiled steps ----------------------------------------------------

    def _cached_step(self, program: str, build):
        global _COMPILES_IN_PROGRESS

        from tendermint_tpu.telemetry import launchlog as _launchlog
        from tendermint_tpu.telemetry import metrics as _metrics

        key = (self.executor, tuple(self._all[i] for i in self.active_indices()), program)
        compile_s = None
        with _STEP_LOCK:
            step = _STEP_CACHE.get(key)
            if step is None:
                _COMPILES_IN_PROGRESS += 1
                t0 = time.perf_counter()
                try:
                    step = build()
                finally:
                    _COMPILES_IN_PROGRESS -= 1
                compile_s = time.perf_counter() - t0
                _STEP_CACHE[key] = step
        # compile-cache telemetry outside the lock: the miss stalls the
        # launch for the whole build, and its record carries the cost
        if compile_s is None:
            _metrics.MESH_COMPILE.labels(result="hit").inc()
            _launchlog.annotate(compile="hit")
        else:
            _metrics.MESH_COMPILE.labels(result="miss").inc()
            _metrics.MESH_COMPILE_SECONDS.observe(compile_s)
            _launchlog.annotate(compile="miss")
            _launchlog.annotate(_additive=True, compile_s=compile_s)
        return step

    def verify_step(self):
        """(pub, r, s, h, powers) -> (verdicts, psum power tally) over
        the ACTIVE mesh. Row counts must already be padded to a
        multiple of `n_active` (`ops.padding.pad_rows_to`)."""
        if self.executor == "host":
            def _host_step(pub, r, s, h, power):
                ok = _host_verify_prepared_rows(pub, r, s, h)
                return ok, int(np.where(ok, power, 0).sum())

            return _host_step
        return self._cached_step(
            "verify_tally", lambda: sharded_verify_and_tally(self.mesh())
        )

    def tables_step(self):
        """Sharded TABLE fast path over the active mesh (validator-axis
        sharding; see `sharded_tables_verify_and_tally`)."""
        if self.executor == "host":
            raise NotImplementedError(
                "host executor has no table path — use the generic verify_step"
            )
        return self._cached_step(
            "tables_tally", lambda: sharded_tables_verify_and_tally(self.mesh())
        )

    def leaf_hash_step(self, algo: str, max_blocks: int):
        """Batch-sharded leaf hashing over the active mesh: (blocks
        (B, max_blocks, 16) u32, n_blocks (B,) i32) -> (B, W) u32
        digests, B a multiple of `n_active`."""
        if self.executor == "host":
            return None  # hasher mesh lane hashes host-side per shard
        return self._cached_step(
            f"leafhash_{algo}_{max_blocks}",
            lambda: sharded_leaf_hash_kernel(self.mesh(), algo, max_blocks),
        )


_DEFAULT_MANAGER: MeshManager | None = None
_DEFAULT_MANAGER_LOCK = threading.Lock()


def default_mesh_manager() -> MeshManager:
    """The process-wide mesh shared by the default verifier and hasher
    stacks — one health view per process: a chip that faults out of the
    verify lane is out of the hash lane too."""
    global _DEFAULT_MANAGER
    if _DEFAULT_MANAGER is None:
        with _DEFAULT_MANAGER_LOCK:
            if _DEFAULT_MANAGER is None:
                _DEFAULT_MANAGER = MeshManager()
    return _DEFAULT_MANAGER


def set_default_mesh_manager(manager: MeshManager | None) -> None:
    global _DEFAULT_MANAGER
    _DEFAULT_MANAGER = manager


def sharded_leaf_hash_kernel(mesh: Mesh, algo: str, max_blocks: int):
    """Compile the Merkle LEAF lane over the mesh: every chip hashes
    1/ndev of the padded leaf messages (one batched masked-SHA-256 /
    RIPEMD-160 pass, `ops.sha256_kernel._sha256_masked` semantics).
    Tree *reduction* stays single-device — inner levels halve too fast
    to amortize collectives; the leaf pass is the O(N) term."""
    spec = P(BATCH_AXIS)

    if algo == "ripemd160":
        from tendermint_tpu.ops.ripemd160_kernel import _ripemd160_masked as _masked
    else:
        from tendermint_tpu.ops.sha256_kernel import _sha256_masked as _masked

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=spec,
    )
    def _leaves(blocks, n_blocks):
        return _masked(blocks, n_blocks, max_blocks)

    return _leaves


def pad_to_multiple(arrays, powers, multiple: int):
    """Pad (B,32) byte arrays + (B,) powers up to a multiple of `multiple`.

    Padding rows are zeros. A zero row does decode (y=0 is a valid
    order-4 point) but still verifies False because S=h=0 makes the
    ladder produce the identity, which never equals the decoded R point
    (0, 1) != (±sqrt(-1), 0); powers are zero too, so the psum tally is
    unaffected either way. Don't replace zero padding with copied rows —
    those WOULD verify True and corrupt the tally if given power.
    """
    b = arrays[0].shape[0]
    size = ((b + multiple - 1) // multiple) * multiple
    if size == b:
        return arrays, powers, b
    pad = size - b
    arrays = [np.concatenate([a, np.zeros((pad, 32), dtype=np.uint8)]) for a in arrays]
    powers = np.concatenate([powers, np.zeros(pad, dtype=powers.dtype)])
    return arrays, powers, b
