"""Multi-chip parallelism: mesh construction + sharded batch kernels.

The reference scales by gossip across WAN peers (`p2p/`); the TPU-native
data plane scales a *single node's* verification throughput across
ICI-connected chips (SURVEY.md §5.8): shard the signature batch over a
`jax.sharding.Mesh`, verify locally per chip, and reduce the voting-power
tally with `psum`.
"""

from tendermint_tpu.parallel.mesh import (
    batch_mesh,
    sharded_verify_and_tally,
    sharded_verify_kernel,
)

__all__ = ["batch_mesh", "sharded_verify_and_tally", "sharded_verify_kernel"]
