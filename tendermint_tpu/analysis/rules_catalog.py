"""M001/M002/M003: the former tests/conftest.py collection lints,
re-homed into the tmlint engine (conftest keeps thin shims calling the
module-level helpers here, so collection behavior and messages are
unchanged while the CLI and the engine's baseline/suppression
machinery apply uniformly).

M001  every `tendermint_*` metric literal in the package (and tools/)
      must be registered in telemetry/metrics.py's REGISTRY.
M002  every literal passed to TRACER.span()/TRACER.add() must be in
      telemetry/metrics.py's SPAN_CATALOG.
M003  every `kernel`-marked test must also carry `slow` (tier-1's
      `-m 'not slow'` overrides pytest.ini's `-m 'not kernel'`).
"""

from __future__ import annotations

import ast
import pathlib
import re

from tendermint_tpu.analysis.engine import (
    Finding,
    SourceFile,
    _is_fixture,
    repo_root,
)

_METRIC_PAT = re.compile(r"""["'](tendermint_[a-z0-9_]+)["']""")
_SPAN_PAT = re.compile(r"""TRACER\.(?:span|add)\(\s*["']([a-z0-9_.]+)["']""")


def _registered_metrics() -> set[str]:
    import tendermint_tpu.telemetry.metrics  # noqa: F401 — fills the registry
    from tendermint_tpu.telemetry import REGISTRY

    return {m.name for m in REGISTRY.metrics()}


def metric_offenders(roots=None) -> list[str]:
    """`path:name` for unregistered tendermint_* literals — the exact
    behavior tests/conftest.py::lint_metric_catalog shipped with."""
    repo = repo_root()
    if roots is None:
        roots = [repo / "tendermint_tpu", repo / "tools"]
    registered = _registered_metrics()
    offenders: list[str] = []
    for root in roots:
        for path in sorted(pathlib.Path(root).rglob("*.py")):
            if "__pycache__" in path.parts or _is_fixture(path):
                continue
            for line, name in _metric_hits(path.read_text(encoding="utf-8")):
                if _metric_ok(name, registered):
                    continue
                try:
                    shown = path.relative_to(repo)
                except ValueError:  # lint tests point at tmp dirs
                    shown = path
                offenders.append(f"{shown}:{name}")
    return offenders


def _metric_hits(text: str):
    for i, line in enumerate(text.splitlines(), start=1):
        for name in _METRIC_PAT.findall(line):
            yield i, name


def _metric_ok(name: str, registered: set[str]) -> bool:
    if name.startswith("tendermint_tpu"):
        return True  # the package name, not a metric
    base = re.sub(r"_(bucket|sum|count)$", "", name)
    return name in registered or base in registered


def span_offenders(roots=None) -> list[str]:
    from tendermint_tpu.telemetry.metrics import SPAN_CATALOG

    repo = repo_root()
    if roots is None:
        roots = [repo / "tendermint_tpu", repo / "tools"]
    offenders: list[str] = []
    for root in roots:
        for path in sorted(pathlib.Path(root).rglob("*.py")):
            if "__pycache__" in path.parts or _is_fixture(path):
                continue
            for name in _SPAN_PAT.findall(path.read_text(encoding="utf-8")):
                if name in SPAN_CATALOG:
                    continue
                try:
                    shown = path.relative_to(repo)
                except ValueError:
                    shown = path
                offenders.append(f"{shown}:{name}")
    return offenders


def kernel_mark_offenders(items) -> list[str]:
    """Collected-item variant (pytest collection hook): node ids of
    kernel-marked tests missing the slow mark."""
    return [
        item.nodeid
        for item in items
        if item.get_closest_marker("kernel") is not None
        and item.get_closest_marker("slow") is None
    ]


# -- engine rules -------------------------------------------------------------


class MetricCatalogRule:
    code = "M001"
    description = "tendermint_* metric literal missing from the catalog"

    def applies_to(self, src: SourceFile) -> bool:
        return True

    def check(self, src: SourceFile) -> list[Finding]:
        if src.rel.startswith("tests/") or "test_" in pathlib.Path(src.rel).name:
            return []  # catalog scope is the package + tools, not tests
        registered = _registered_metrics()
        return [
            src.finding(
                self.code,
                line,
                f"metric {name!r} is not registered in "
                "telemetry/metrics.py — a dashboard or invariant "
                "querying it would match nothing",
            )
            for line, name in _metric_hits(src.text)
            if not _metric_ok(name, registered)
        ]


class SpanCatalogRule:
    code = "M002"
    description = "TRACER span literal missing from SPAN_CATALOG"

    def applies_to(self, src: SourceFile) -> bool:
        return "TRACER" in src.text

    def check(self, src: SourceFile) -> list[Finding]:
        if src.rel.startswith("tests/") or "test_" in pathlib.Path(src.rel).name:
            return []
        from tendermint_tpu.telemetry.metrics import SPAN_CATALOG

        findings = []
        for i, line in enumerate(src.lines, start=1):
            for name in _SPAN_PAT.findall(line):
                if name not in SPAN_CATALOG:
                    findings.append(
                        src.finding(
                            self.code,
                            i,
                            f"span {name!r} is not in SPAN_CATALOG "
                            "(telemetry/metrics.py)",
                        )
                    )
        return findings


class KernelMarkRule:
    """Static twin of the collection-time kernel/slow marker lint: finds
    `pytest.mark.kernel` (decorator or pytestmark list) without a
    matching `slow` in the same scope chain."""

    code = "M003"
    description = "kernel-marked test missing the slow mark"

    def applies_to(self, src: SourceFile) -> bool:
        name = pathlib.Path(src.rel).name
        return src.tree is not None and (
            name.startswith("test_") or name == "conftest.py"
        )

    def check(self, src: SourceFile) -> list[Finding]:
        module_marks = self._pytestmark_marks(src.tree)
        findings: list[Finding] = []
        self._walk(src, src.tree, module_marks, findings)
        return findings

    def _pytestmark_marks(self, tree) -> set[str]:
        marks: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark"
                for t in node.targets
            ):
                marks |= self._marks_in(node.value)
        return marks

    @staticmethod
    def _marks_in(node) -> set[str]:
        """Names X from pytest.mark.X references in `node`'s subtree."""
        marks: set[str] = set()
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "mark"
            ):
                marks.add(sub.attr)
        return marks

    def _decorator_marks(self, node) -> set[str]:
        marks: set[str] = set()
        for dec in node.decorator_list:
            marks |= self._marks_in(dec)
        return marks

    def _walk(self, src, node, inherited: set[str], findings):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                marks = inherited | self._decorator_marks(child)
                is_test = child.name.startswith(("test_", "Test"))
                if (
                    is_test
                    and not isinstance(child, ast.ClassDef)
                    and "kernel" in marks
                    and "slow" not in marks
                ):
                    findings.append(
                        src.finding(
                            self.code,
                            child.lineno,
                            f"{child.name} is kernel-marked but not "
                            "slow-marked — tier-1's `-m 'not slow'` would "
                            "pull its XLA compile into the fast lane",
                        )
                    )
                self._walk(src, child, marks, findings)
            else:
                self._walk(src, child, inherited, findings)
