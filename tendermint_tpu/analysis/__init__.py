"""tmlint: repo-invariant static analysis for the concurrency spine.

`engine.py` walks Python sources, runs the registered rules, applies
inline suppressions (`# tmlint: disable=RULE -- reason`) and the
findings baseline, and renders CLI output for `tools/tmlint.py`.
The rule catalog (docs/STATIC_ANALYSIS.md):

  L001  lock-order: nested `with lock:` acquisitions vs the declared
        rank table (utils/lockrank.py RANKS)
  L002  blocking call (`time.sleep`, `.result()`, `.join()`, blocking
        `.get()`/`.wait()`) inside a lock body
  T001  bare / silently-swallowing overbroad `except` in reactor
        receive loops and thread run() bodies
  W001  wire back-compat: codec reads after the optional tail region
        (new fields must be trailing-optional)
  J001  JAX purity: host side effects / Python branching on traced
        values inside jitted or shard_map'd functions
  M001  tendermint_* metric literals missing from the telemetry catalog
  M002  TRACER span literals missing from SPAN_CATALOG
  M003  `kernel`-marked tests missing the `slow` mark
  S001  suppression comment without a reason string

M001-M003 are the former tests/conftest.py collection lints, re-homed
here; conftest keeps thin shims that invoke this engine.
"""

from tendermint_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Report,
    all_rules,
    lint_paths,
    load_baseline,
    write_baseline,
)
