"""The tmlint engine: file walking, rule dispatch, suppressions, baseline.

Design goals (ISSUE 10): one lint engine and one baseline format for
every repo invariant; AST-based file rules plus repo-scope catalog
rules; suppressions must carry a reason; full-package runs stay well
under 5 s so the engine can gate tier-1 collection.
"""

from __future__ import annotations

import ast
import hashlib
import json
import pathlib
import re
from dataclasses import dataclass, field


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


DEFAULT_BASELINE = "tools/tmlint_baseline.json"

# `# tmlint: disable=L001` or `# tmlint: disable=L001,L002 -- reason`
_SUPPRESS_RE = re.compile(
    r"#\s*tmlint:\s*disable=([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)"
    r"(?:\s*--\s*(.*?))?\s*$"
)


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative (or absolute for out-of-repo roots)
    line: int
    message: str
    source: str = ""  # stripped source line, for fingerprinting

    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.source}".encode()
        ).hexdigest()
        return h[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)  # fresh (fail)
    baselined: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale_baseline: list[str] = field(default_factory=list)
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


class SourceFile:
    """One parsed source handed to file rules (AST parsed once)."""

    def __init__(self, path: pathlib.Path, rel: str) -> None:
        self.path = path
        self.rel = rel
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree: ast.AST | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(self.text, filename=str(path))
        except SyntaxError as e:
            self.parse_error = str(e)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, lineno: int, message: str) -> Finding:
        return Finding(rule, self.rel, lineno, message, self.line_at(lineno))


# -- rule registry ------------------------------------------------------------


def all_rules() -> dict[str, "object"]:
    """code -> rule instance. Imported lazily so `tools/tmlint.py
    --list-rules` stays cheap and rule modules can import the engine."""
    from tendermint_tpu.analysis import (
        rules_catalog,
        rules_jax,
        rules_locks,
        rules_threads,
        rules_wire,
    )

    rules = [
        rules_locks.LockOrderRule(),
        rules_locks.BlockingUnderLockRule(),
        rules_threads.SilentThreadDeathRule(),
        rules_wire.TrailingOptionalRule(),
        rules_jax.JaxPurityRule(),
        rules_catalog.MetricCatalogRule(),
        rules_catalog.SpanCatalogRule(),
        rules_catalog.KernelMarkRule(),
    ]
    return {r.code: r for r in rules}


# -- suppressions -------------------------------------------------------------


def _suppressions(src: SourceFile) -> tuple[dict[int, set[str]], list[Finding]]:
    """line -> suppressed rule codes; plus S001 findings for reasonless
    suppressions (a suppression must say WHY — reasonless ones fail)."""
    table: dict[int, set[str]] = {}
    bad: list[Finding] = []
    for i, line in enumerate(src.lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if m is None:
            continue
        codes = {c.strip() for c in m.group(1).split(",")}
        reason = (m.group(2) or "").strip()
        if not reason:
            bad.append(
                src.finding(
                    "S001",
                    i,
                    "suppression without a reason — write "
                    "`# tmlint: disable=RULE -- why this is safe`",
                )
            )
            continue
        table[i] = codes
    return table, bad


# -- baseline -----------------------------------------------------------------


def load_baseline(path: pathlib.Path | str | None) -> dict:
    if path is None:
        return {}
    p = pathlib.Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text(encoding="utf-8"))
    return data.get("findings", {})


def write_baseline(path: pathlib.Path | str, findings: list[Finding]) -> None:
    entries = {}
    for f in findings:
        entries[f.fingerprint()] = {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "source": f.source,
        }
    payload = {
        "version": 1,
        "comment": (
            "tmlint findings baseline: grandfathered sites. Entries are "
            "keyed by sha1(rule|path|source-line) so line drift does not "
            "invalidate them. Regenerate with tools/tmlint.py "
            "--write-baseline; prefer fixing or reason-annotated "
            "suppressions over baselining."
        ),
        "findings": entries,
    }
    pathlib.Path(path).write_text(
        json.dumps(payload, indent=2, sort_keys=True) + "\n", encoding="utf-8"
    )


# -- driver -------------------------------------------------------------------


def _is_fixture(path: pathlib.Path) -> bool:
    """The rule fixture corpus (analysis/fixtures/) contains deliberate
    violations; directory walks skip it — lint it by naming a fixture
    file explicitly (which is what tests/test_tmlint.py does)."""
    parts = path.parts
    for i in range(len(parts) - 1):
        if parts[i] == "analysis" and parts[i + 1] == "fixtures":
            return True
    return False


def iter_py_files(paths: list[pathlib.Path]) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            out.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts and not _is_fixture(f)
            )
    return out


def lint_paths(
    paths: list[pathlib.Path | str],
    rules: list[str] | None = None,
    baseline_path: pathlib.Path | str | None = None,
    root: pathlib.Path | None = None,
) -> Report:
    """Run `rules` (default: all) over `paths`; returns the Report with
    fresh findings (suppressions applied, baseline subtracted)."""
    root = root or repo_root()
    registry = all_rules()
    if rules is not None:
        unknown = set(rules) - set(registry) - {"S001"}
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
        registry = {c: r for c, r in registry.items() if c in rules}
    files = iter_py_files([pathlib.Path(p) for p in paths])
    report = Report(files_checked=len(files))
    raw: list[Finding] = []
    sources: list[SourceFile] = []
    suppress_tables: dict[str, dict[int, set[str]]] = {}
    for path in files:
        try:
            rel = str(path.resolve().relative_to(root))
        except ValueError:
            rel = str(path)
        src = SourceFile(path, rel)
        sources.append(src)
        table, bad = _suppressions(src)
        suppress_tables[rel] = table
        if rules is None or "S001" in rules:
            raw.extend(bad)
        if src.parse_error is not None:
            raw.append(
                src.finding("E999", 1, f"syntax error: {src.parse_error}")
            )
            continue
        for rule in registry.values():
            if getattr(rule, "repo_scope", False):
                continue
            if not rule.applies_to(src):
                continue
            raw.extend(rule.check(src))
    # repo-scope rules see the whole file set at once
    for rule in registry.values():
        if getattr(rule, "repo_scope", False):
            raw.extend(rule.check_repo(sources))

    baseline = load_baseline(baseline_path)
    seen_fps: set[str] = set()
    for f in raw:
        table = suppress_tables.get(f.path, {})
        codes = table.get(f.line, set()) | table.get(f.line - 1, set())
        if f.rule in codes:
            report.suppressed.append(f)
            continue
        fp = f.fingerprint()
        seen_fps.add(fp)
        if fp in baseline:
            report.baselined.append(f)
        else:
            report.findings.append(f)
    report.stale_baseline = sorted(set(baseline) - seen_fps)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report


def render_report(report: Report, verbose: bool = False) -> str:
    lines = [f.render() for f in report.findings]
    if verbose:
        for f in report.baselined:
            lines.append(f.render() + "  [baselined]")
        for f in report.suppressed:
            lines.append(f.render() + "  [suppressed]")
    summary = (
        f"tmlint: {len(report.findings)} finding(s), "
        f"{len(report.baselined)} baselined, "
        f"{len(report.suppressed)} suppressed, "
        f"{report.files_checked} file(s)"
    )
    if report.stale_baseline:
        summary += f", {len(report.stale_baseline)} stale baseline entr(y/ies)"
    lines.append(summary)
    return "\n".join(lines)
