"""J001: JAX purity — no host side effects or Python control flow on
traced values inside jitted / shard_map'd functions.

A `print()`, `time.time()`, metric increment, or tracer span inside a
jitted function runs at TRACE time (once per compilation), not per
call — a silent correctness/observability bug. Python `if`/`while` on
a traced argument raises `TracerBoolConversionError` at runtime, but
only on the branch actually traced; this rule catches both statically.

Scope: functions decorated `@jax.jit` / `@jit` /
`@partial(jax.jit, ...)` (static_argnames/static_argnums respected —
branching on a static argument is fine, as is branching on `.shape` /
`.ndim` / `.dtype` / `len(...)`, which are concrete at trace time).
"""

from __future__ import annotations

import ast

from tendermint_tpu.analysis.engine import Finding, SourceFile

# call roots whose invocation inside a jitted body is a host effect
_EFFECT_ROOTS = {
    "time",
    "os",
    "TRACER",
    "REGISTRY",
    "FLIGHT",
    "logging",
    "random",
    "_metrics",
    "metrics",
}
_EFFECT_NAMES = {"print", "open", "breakpoint", "input"}


def _jit_decoration(fn: ast.FunctionDef) -> tuple[bool, set[str], set[int]]:
    """(is_jitted, static_argnames, static_argnums) from decorators."""
    for dec in fn.decorator_list:
        # `@jax.jit`, `@jit`, and `@partial(jax.jit, ...)` — for call
        # decorators the jit reference sits in the ARGS, so walk the
        # whole decorator expression
        names: list[str] = []
        for node in ast.walk(dec):
            if isinstance(node, ast.Name):
                names.append(node.id)
            elif isinstance(node, ast.Attribute):
                names.append(node.attr)
        if "jit" not in names and "shard_map" not in names:
            continue
        static_names: set[str] = set()
        static_nums: set[int] = set()
        if isinstance(dec, ast.Call):
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str
                        ):
                            static_names.add(sub.value)
                elif kw.arg == "static_argnums":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, int
                        ):
                            static_nums.add(sub.value)
        return True, static_names, static_nums
    return False, set(), set()


def _traced_params(fn: ast.FunctionDef, static_names, static_nums) -> set[str]:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return {
        p
        for i, p in enumerate(params)
        if p not in static_names and i not in static_nums
    }


def _branch_names(test: ast.AST) -> set[str]:
    """Bare Names in a branch test, excluding concrete-at-trace-time
    accessors: attribute chains (x.shape/x.ndim/x.dtype), len()."""
    names: set[str] = set()

    def visit(node, skip):
        if isinstance(node, ast.Attribute):
            return  # x.shape etc: attribute access is concrete or traced-op
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            if fname in ("len", "isinstance", "getattr", "hasattr"):
                return
            for child in ast.iter_child_nodes(node):
                visit(child, skip)
            return
        if isinstance(node, ast.Name):
            names.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, skip)

    visit(test, False)
    return names


class JaxPurityRule:
    code = "J001"
    description = (
        "host side effect or Python branch on a traced value inside a "
        "jitted function"
    )

    def applies_to(self, src: SourceFile) -> bool:
        return src.tree is not None and (
            "jit" in src.text or "shard_map" in src.text
        )

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            jitted, static_names, static_nums = _jit_decoration(node)
            if not jitted:
                continue
            traced = _traced_params(node, static_names, static_nums)
            self._check_body(src, node, traced, findings)
        return findings

    def _check_body(self, src, fn, traced: set[str], findings):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id in _EFFECT_NAMES:
                    findings.append(
                        src.finding(
                            self.code,
                            node.lineno,
                            f"host call {f.id}() inside jitted "
                            f"{fn.name}() runs at trace time, not per call",
                        )
                    )
                elif isinstance(f, ast.Attribute):
                    root = f.value
                    while isinstance(root, ast.Attribute):
                        root = root.value
                    if (
                        isinstance(root, ast.Name)
                        and root.id in _EFFECT_ROOTS
                    ):
                        findings.append(
                            src.finding(
                                self.code,
                                node.lineno,
                                f"host side effect "
                                f"{root.id}.{f.attr}() inside jitted "
                                f"{fn.name}()",
                            )
                        )
            elif isinstance(node, (ast.If, ast.While)):
                hit = _branch_names(node.test) & traced
                if hit:
                    findings.append(
                        src.finding(
                            self.code,
                            node.lineno,
                            f"Python branch on traced value(s) "
                            f"{', '.join(sorted(hit))} inside jitted "
                            f"{fn.name}() — use jnp.where/lax.cond or mark "
                            "the argument static",
                        )
                    )
