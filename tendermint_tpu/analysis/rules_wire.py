"""W001: wire back-compat — new codec fields must be trailing-optional.

The repo's compatibility discipline (PR 7 trace blocks, PR 9 block
evidence): a decoder reads its mandatory fields unconditionally, then
an OPTIONAL tail region guarded by remaining-length checks (`if not
r.done():`) or try/except. Once a decoder enters the optional region,
every later read must also be guarded — an unguarded read after a
guarded one means a new MANDATORY field was appended behind optional
ones, which breaks old encoders (they never write it) and old decoders
(they misparse the tail).

Scope: functions named like parse_* / decode_* / *from_wire* in any
module; "reads" are calls to Reader methods (uvarint/svarint/bytes/
string/bool/raw) or the module-level decode_* helpers.
"""

from __future__ import annotations

import ast
import re

from tendermint_tpu.analysis.engine import Finding, SourceFile

_SCOPE_FN = re.compile(r"^(parse_.*|decode_.*|.*from_wire.*|decode_wire)$")
_READ_METHODS = {"uvarint", "svarint", "bytes", "string", "bool", "raw"}
_READ_FNS = re.compile(r"^decode_[a-z_]+$")


def _reads_in(node: ast.AST) -> list[ast.Call]:
    out = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if isinstance(fn, ast.Attribute) and fn.attr in _READ_METHODS:
            out.append(sub)
        elif isinstance(fn, ast.Name) and _READ_FNS.match(fn.id):
            out.append(sub)
    return out


def _guarded_lines(stmt: ast.stmt) -> set[int]:
    """Line numbers of reads nested under an If/Try BODY inside `stmt`.

    Reads in an `if` TEST are validation (`if r.uvarint() != MSG: raise`)
    — mandatory, not optional-tail; only the bodies (and try handlers)
    constitute the guarded optional region."""
    guarded: set[int] = set()
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.If):
            parts = sub.body + sub.orelse
        elif isinstance(sub, ast.Try):
            parts = sub.body + sub.orelse + sub.finalbody
            for h in sub.handlers:
                parts = parts + h.body
        else:
            continue
        for part in parts:
            for call in _reads_in(part):
                guarded.add(call.lineno)
    return guarded


class TrailingOptionalRule:
    code = "W001"
    description = (
        "unguarded wire read after the optional tail region — new codec "
        "fields must be trailing-optional"
    )

    def applies_to(self, src: SourceFile) -> bool:
        return src.tree is not None

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _SCOPE_FN.match(node.name):
                continue
            self._check_fn(src, node, findings)
        return findings

    def _check_fn(self, src, fn, findings):
        in_optional_tail = False
        for stmt in fn.body:
            reads = _reads_in(stmt)
            if not reads:
                continue
            guarded = _guarded_lines(stmt)
            # an If/Try statement whose reads are all inside it opens
            # (or continues) the optional tail region
            unguarded = [c for c in reads if c.lineno not in guarded]
            if in_optional_tail and unguarded:
                findings.append(
                    src.finding(
                        self.code,
                        unguarded[0].lineno,
                        f"{fn.name}(): unconditional wire read after the "
                        "optional tail began — append new fields as "
                        "guarded trailing-optional reads instead",
                    )
                )
                # keep scanning; each unguarded-after-optional read in a
                # later statement gets its own finding
            if guarded and not unguarded:
                in_optional_tail = True


def decoder_functions(src: SourceFile) -> list[str]:
    """Names of in-scope decoder functions (docs/debugging helper)."""
    if src.tree is None:
        return []
    return [
        n.name
        for n in ast.walk(src.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and _SCOPE_FN.match(n.name)
    ]
