"""tmlint fixture: M003 — kernel mark without slow (file is named like a
fixture, so tests feed it to the engine under a test_*.py alias)."""

import pytest


@pytest.mark.kernel
def test_compiles_kernel_only():
    pass


@pytest.mark.kernel
@pytest.mark.slow
def test_compiles_both_marks():
    pass


@pytest.mark.kernel
class TestKernelClass:
    def test_inherits_kernel_only(self):
        pass


pytestmark = []
