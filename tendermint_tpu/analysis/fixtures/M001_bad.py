"""tmlint fixture: M001 — metric literal missing from the catalog."""

NAME = "tendermint_not_in_the_catalog_total"
OK_SUFFIX = "tendermint_verify_seconds_count"  # exposition suffix: fine
PKG = "tendermint_tpu.services"  # package path, not a metric
