"""tmlint fixture: L002 blocking calls under a lock (deliberately bad)."""

import time

from tendermint_tpu.utils.lockrank import ranked_lock


class Worker:
    def __init__(self, handle, thread, q):
        self._lock = ranked_lock("dispatch.state")
        self.handle = handle
        self.thread = thread
        self.q = q

    def sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)

    def join_result_under_lock(self):
        with self._lock:
            v = self.handle.result()
            self.thread.join()
            return v, self.q.get()

    def foreign_wait_under_lock(self, event):
        with self._lock:
            event.wait()
