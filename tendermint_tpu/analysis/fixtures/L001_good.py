"""tmlint fixture: L001-clean nesting (ascending rank order)."""

from tendermint_tpu.utils.lockrank import ranked_lock


class Pool:
    def __init__(self):
        self._wal_lock = ranked_lock("mempool.wal")
        self._counter_lock = ranked_lock("mempool.counter")

    def ordered(self):
        with self._wal_lock:
            with self._counter_lock:
                return 1

    def sequential_not_nested(self):
        with self._counter_lock:
            hi = 1
        with self._wal_lock:
            return hi

    def nested_def_resets_held(self):
        with self._counter_lock:
            def helper():
                # not executed under the lock at this site
                with self._wal_lock:
                    return 2

            return helper
