"""tmlint fixture: M002 — span literal missing from SPAN_CATALOG."""

TRACER = None


def record():
    TRACER.add("not.in.catalog", 0.0, 1.0)
    TRACER.add("mempool.admission", 0.0, 1.0)  # cataloged: fine
