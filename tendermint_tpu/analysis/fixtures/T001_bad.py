"""tmlint fixture: T001 silent thread death (deliberately bad)."""


def anywhere():
    try:
        risky()
    except:  # bare except is flagged anywhere
        pass


def risky():
    raise RuntimeError


class NoisyReactor:
    def receive(self, chan_id, peer, payload):
        try:
            decode(payload)
        except Exception:
            pass  # silent swallow in a reactor receive path


class Runner:
    def run(self):
        while True:
            try:
                step()
            except Exception:
                continue  # silent swallow in a thread run body


def _recv_loop(sock):
    while True:
        try:
            sock.recv(1)
        except Exception:
            pass


def decode(payload):
    return payload


def step():
    pass
