"""tmlint fixture: J001-clean jitted functions."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("mode",))
def branch_on_static(x, mode):
    if mode == "neg":  # mode is static: concrete at trace time
        return -x
    return x


@jax.jit
def branch_on_shape(x):
    if x.shape[0] == 0:  # shapes are concrete at trace time
        return x
    if len(x) > 4:  # len() of an array is its (static) leading dim
        return x[:4]
    return jnp.where(x > 0, x, -x)  # traced select belongs on-device


def host_helper(x):
    print("not jitted: host effects are fine here")
    return x
