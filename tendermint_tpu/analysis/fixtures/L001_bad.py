"""tmlint fixture: L001 lock-order violations (deliberately bad)."""

from tendermint_tpu.utils.lockrank import ranked_lock


class Pool:
    def __init__(self):
        self._wal_lock = ranked_lock("mempool.wal")
        self._counter_lock = ranked_lock("mempool.counter")
        self._avail_lock = ranked_lock("mempool.avail")

    def inverted(self):
        # counter (52) then wal (48): descends the rank table
        with self._counter_lock:
            with self._wal_lock:
                return 1

    def inverted_multi_item(self):
        # one `with`, two items, still out of order
        with self._wal_lock, self._avail_lock:
            return 2
