"""tmlint fixture: T001-clean exception handling."""

import logging

log = logging.getLogger(__name__)


class QuietReactor:
    def receive(self, chan_id, peer, payload):
        try:
            decode(payload)
        except ValueError:
            pass  # narrow catch is fine even when silent
        except Exception as e:
            log.warning("bad payload: %s", e)  # observable: not silent


class Runner:
    def run(self):
        while True:
            try:
                step()
            except Exception as e:
                self.on_error(e)  # routed, not swallowed
                return

    def on_error(self, e):
        log.error("runner died: %s", e)


def helper():
    # overbroad+silent OUTSIDE thread-loop scopes is not T001's business
    try:
        step()
    except Exception:
        pass


def decode(payload):
    return payload


def step():
    pass
