"""tmlint fixture: W001-clean decoders (trailing-optional discipline)."""


def parse_frame_good(r):
    chan_id = r.uvarint()
    payload = r.bytes()
    ctx = None
    if not r.done():
        try:
            ctx = r.bytes()
        except ValueError:
            ctx = None
    return chan_id, payload, ctx


def decode_msg_good(r):
    # a read inside an `if` TEST is validation, not an optional region
    if r.uvarint() != 1:
        raise ValueError("unknown message")
    body = r.bytes()
    extra = None
    if not r.done():
        extra = r.bytes()
    return body, extra


def encode_not_in_scope(w, payload):
    # Writer calls share method names with Reader; encoders are out of scope
    w.uvarint(1)
    w.bytes(payload)
    return w.build()
