"""tmlint fixture: W001 wire back-compat violations (deliberately bad)."""


def parse_frame_bad(r):
    chan_id = r.uvarint()
    payload = r.bytes()
    if not r.done():
        trace = r.bytes()  # optional tail begins
    flags = r.uvarint()  # BAD: mandatory read appended after the tail
    return chan_id, payload, flags


def decode_record_bad(r):
    tag = r.uvarint()
    try:
        extra = r.bytes()  # optional (guarded by try)
    except ValueError:
        extra = b""
    body = r.bytes()  # BAD: unguarded read after the optional region
    return tag, extra, body
