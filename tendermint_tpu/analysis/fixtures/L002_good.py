"""tmlint fixture: L002-clean patterns."""

import threading
import time

from tendermint_tpu.utils.lockrank import ranked_lock


class Worker:
    def __init__(self, handle, thread, q):
        self._lock = ranked_lock("dispatch.state")
        self._cond = threading.Condition()
        self.handle = handle
        self.thread = thread
        self.q = q

    def blocking_outside_lock(self):
        v = self.handle.result()
        self.thread.join()
        time.sleep(0.01)
        with self._lock:
            return v, self.q.get_nowait()

    def condition_self_wait(self):
        # the one blocking call a lock body is FOR
        with self._cond:
            self._cond.wait(0.1)

    def non_blocking_lookalikes(self, d, parts):
        with self._lock:
            return d.get("key"), ",".join(parts)
