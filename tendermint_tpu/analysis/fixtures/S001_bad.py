"""tmlint fixture: S001 — suppression without a reason string."""

import time

from tendermint_tpu.utils.lockrank import ranked_lock

_lock = ranked_lock("dispatch.state")


def suppressed_without_reason():
    with _lock:
        time.sleep(0.1)  # tmlint: disable=L002


def suppressed_with_reason():
    with _lock:
        time.sleep(0.1)  # tmlint: disable=L002 -- fixture: demonstrates a valid reasoned suppression
