"""tmlint fixture: J001 JAX purity violations (deliberately bad).

Never imported — parsed only; the jax names are placeholders.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def noisy(x):
    print("tracing")  # runs once per compile, not per call
    t = time.time()  # host clock frozen into the trace
    return x + t


@partial(jax.jit, static_argnames=("mode",))
def branch_on_traced(x, mode):
    if x > 0:  # BAD: x is traced
        return x
    return -x


@jax.jit
def while_on_traced(n):
    while n > 0:  # BAD: traced loop condition
        n = n - 1
    return n
