"""T001: silent thread death / swallowed exceptions in loop bodies.

A reactor receive loop or a thread's run() body that catches
``except:`` (bare) or ``except Exception: pass`` does not crash — it
silently stops doing its job, which in a consensus system means frames
dropped, gossip wedged, or a dead reader nobody notices. The rule
flags:

* bare ``except:`` anywhere in the package (never acceptable — it also
  swallows KeyboardInterrupt/SystemExit);
* overbroad handlers (``Exception`` / ``BaseException``) whose body is
  ONLY ``pass`` / ``continue`` (no logging, no scoring, no re-raise)
  inside thread-loop scopes: functions named like run/_recv*/_send*/
  *_loop/receive/_dispatch, or any method of a class named *Reactor*.
"""

from __future__ import annotations

import ast
import re

from tendermint_tpu.analysis.engine import Finding, SourceFile

_SCOPE_FN = re.compile(
    r"^(run|receive|_dispatch|_recv.*|_send.*|.*_loop|_worker)$"
)


def _is_overbroad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in [t] + (list(t.elts) if isinstance(t, ast.Tuple) else []):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in ("Exception", "BaseException") for n in names)


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """Handler does nothing observable: only pass/continue/constant."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring/ellipsis
        return False
    return True


class SilentThreadDeathRule:
    code = "T001"
    description = (
        "bare or silently-swallowing overbroad except in a thread "
        "loop / reactor receive path"
    )

    def applies_to(self, src: SourceFile) -> bool:
        return src.tree is not None

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        self._walk(src, src.tree, in_scope=False, findings=findings)
        return findings

    def _walk(self, src, node, in_scope: bool, findings):
        for child in ast.iter_child_nodes(node):
            scope = in_scope
            if isinstance(child, ast.ClassDef):
                scope = child.name.endswith("Reactor")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = in_scope or bool(_SCOPE_FN.match(child.name))
            elif isinstance(child, ast.ExceptHandler):
                if child.type is None:
                    findings.append(
                        src.finding(
                            self.code,
                            child.lineno,
                            "bare `except:` swallows everything including "
                            "KeyboardInterrupt — catch a concrete type",
                        )
                    )
                elif in_scope and _is_overbroad(child) and _is_silent(child):
                    findings.append(
                        src.finding(
                            self.code,
                            child.lineno,
                            "overbroad except silently swallowed in a "
                            "thread-loop scope — a dying reader/reactor "
                            "must log, score, or re-raise",
                        )
                    )
            self._walk(src, child, scope, findings)
