"""L001 lock-order and L002 blocking-call-under-lock.

Both rules resolve `with <expr>:` context managers to entries in the
declared rank table (`utils/lockrank.py RANKS`). Resolution is
two-stage:

1. module-local: any assignment whose value contains a
   ``ranked_lock("name", ...)`` / ``ranked_rlock("name", ...)`` call
   (including wrapped ones like ``threading.Condition(ranked_lock(...))``)
   binds the assigned attribute to that rank name, so annotating a lock
   at its construction site is all a module needs;
2. a fallback suffix table for idioms the assignment scan can't see
   (``lane.lock`` — the lane object is built in another class).
"""

from __future__ import annotations

import ast

from tendermint_tpu.analysis.engine import Finding, SourceFile
from tendermint_tpu.utils.lockrank import RANKS

# with-expression suffix -> rank name, for locks whose construction the
# per-module scan can't attribute (cross-object attribute paths).
WITH_EXPR_FALLBACK: dict[str, str] = {
    "lane.lock": "mempool.lane",
    "self._lanes[0].lock": "mempool.lane",
}

# Attribute-ish expressions that look like locks even when unranked —
# L002 applies to these too (a blocking call under ANY lock is suspect).
_LOCKISH = ("lock", "mtx", "mutex", "cond", "avail")

# Blocking calls that must not run under a held lock. `.wait()` on the
# with-target itself (a Condition) is exempt — that is the one blocking
# call conditions exist to make.
_BLOCKING_ATTRS = {"result", "join", "wait", "get", "recv", "accept"}


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - ast.unparse is total on py>=3.9
        return "<expr>"


def _ranked_call_name(node: ast.AST) -> str | None:
    """The rank name if `node`'s subtree contains ranked_lock/_rlock("x")."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        fname = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if fname in ("ranked_lock", "ranked_rlock") and sub.args:
            arg = sub.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    return None


def _local_lock_map(tree: ast.AST) -> dict[str, str]:
    """attr/name -> rank name, from ranked_lock assignment sites."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if value is None:
            continue
        rank_name = _ranked_call_name(value)
        if rank_name is None:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Attribute):
                out[tgt.attr] = rank_name
            elif isinstance(tgt, ast.Name):
                out[tgt.id] = rank_name
    return out


class _Ctx:
    __slots__ = ("expr", "rank_name", "rank")

    def __init__(self, expr: str, rank_name: str | None):
        self.expr = expr
        self.rank_name = rank_name
        self.rank = RANKS.get(rank_name) if rank_name else None


def _resolve(expr: ast.AST, lock_map: dict[str, str]) -> _Ctx | None:
    """Map a with-item expression to a lock context (None: not a lock)."""
    text = _unparse(expr)
    # module-local ranked assignment: self._wal_lock / bare names
    if isinstance(expr, ast.Attribute) and expr.attr in lock_map:
        return _Ctx(text, lock_map[expr.attr])
    if isinstance(expr, ast.Name) and expr.id in lock_map:
        return _Ctx(text, lock_map[expr.id])
    for suffix, rank_name in WITH_EXPR_FALLBACK.items():
        if text == suffix or text.endswith("." + suffix):
            return _Ctx(text, rank_name)
    tail = text.rsplit(".", 1)[-1].lower()
    if any(t in tail for t in _LOCKISH):
        return _Ctx(text, None)  # lock-looking but unranked
    return None


class LockOrderRule:
    """L001: nested `with lock:` acquisitions must ascend the rank table."""

    code = "L001"
    description = (
        "nested lock acquisition out of declared rank order "
        "(utils/lockrank.py RANKS)"
    )

    def applies_to(self, src: SourceFile) -> bool:
        return src.tree is not None

    def check(self, src: SourceFile) -> list[Finding]:
        lock_map = _local_lock_map(src.tree)
        findings: list[Finding] = []
        self._walk_body(src, src.tree, [], lock_map, findings)
        return findings

    def _walk_body(self, src, node, held: list[_Ctx], lock_map, findings):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.With, ast.AsyncWith)):
                entered: list[_Ctx] = []
                for item in child.items:
                    ctx = _resolve(item.context_expr, lock_map)
                    if ctx is None or ctx.rank is None:
                        continue
                    for outer in held + entered:
                        if outer.rank is None:
                            continue
                        if ctx.rank < outer.rank or (
                            ctx.rank == outer.rank
                            and ctx.rank_name != outer.rank_name
                        ):
                            findings.append(
                                src.finding(
                                    self.code,
                                    child.lineno,
                                    f"acquires {ctx.rank_name!r} (rank "
                                    f"{ctx.rank}) while holding "
                                    f"{outer.rank_name!r} (rank {outer.rank})"
                                    " — declared order is ascending rank",
                                )
                            )
                    entered.append(ctx)
                self._walk_body(src, child, held + entered, lock_map, findings)
            elif isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # a nested def is not executed under the lock at this site
                self._walk_body(src, child, [], lock_map, findings)
            else:
                self._walk_body(src, child, held, lock_map, findings)


class BlockingUnderLockRule:
    """L002: no blocking call inside a lock body.

    time.sleep, socket/endpoint recv/accept, future `.result()`,
    thread `.join()`, and zero-positional-arg `.get()` / `.wait()`
    calls (queue/event blocking reads) are flagged when lexically
    inside a `with <lock>:` body. A Condition waiting on itself
    (`with self._cond: self._cond.wait()`) is exempt.
    """

    code = "L002"
    description = "blocking call while holding a lock"

    def applies_to(self, src: SourceFile) -> bool:
        return src.tree is not None

    def check(self, src: SourceFile) -> list[Finding]:
        lock_map = _local_lock_map(src.tree)
        findings: list[Finding] = []
        self._walk(src, src.tree, [], lock_map, findings)
        return findings

    def _walk(self, src, node, held: list[_Ctx], lock_map, findings):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.With, ast.AsyncWith)):
                entered = [
                    ctx
                    for item in child.items
                    if (ctx := _resolve(item.context_expr, lock_map))
                    is not None
                ]
                self._walk(src, child, held + entered, lock_map, findings)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(src, child, [], lock_map, findings)
            else:
                if held and isinstance(child, ast.Call):
                    self._check_call(src, child, held, findings)
                self._walk(src, child, held, lock_map, findings)

    def _check_call(self, src, call: ast.Call, held: list[_Ctx], findings):
        fn = call.func
        lock_names = ", ".join(
            c.rank_name or c.expr for c in held
        )
        # time.sleep(...)
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "sleep"
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "time"
        ):
            findings.append(
                src.finding(
                    self.code,
                    call.lineno,
                    f"time.sleep() while holding [{lock_names}]",
                )
            )
            return
        if not isinstance(fn, ast.Attribute) or fn.attr not in _BLOCKING_ATTRS:
            return
        recv_text = _unparse(fn.value)
        if fn.attr == "wait":
            # `with self._cond: self._cond.wait()` is the condition idiom
            if any(recv_text == c.expr for c in held):
                return
            findings.append(
                src.finding(
                    self.code,
                    call.lineno,
                    f"{recv_text}.wait() while holding [{lock_names}] "
                    "(waiting on a foreign primitive under a lock)",
                )
            )
            return
        if fn.attr in ("join", "get") and call.args:
            return  # str.join(iterable) / dict.get(key) — not blocking
        if fn.attr in ("recv", "accept") and not _looks_io(recv_text):
            return
        findings.append(
            src.finding(
                self.code,
                call.lineno,
                f"{recv_text}.{fn.attr}() while holding [{lock_names}] "
                "(blocking call under a lock)",
            )
        )


def _looks_io(recv_text: str) -> bool:
    tail = recv_text.rsplit(".", 1)[-1].lower()
    return any(
        t in tail for t in ("sock", "conn", "endpoint", "pipe", "client")
    )
