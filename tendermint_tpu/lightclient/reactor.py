"""LightClientReactor: FullCommit serving + subscription on channel 0x68.

Protocol (all frames `uvarint tag || fields`, like the statesync
channel):

* `fc_request(height)` -> `fc_response(height, FullCommit?)` — the
  proof-serving path. `height=0` asks for the chain tip. The serving
  side answers exact-height first (certified cache, then local
  stores), falling back to the newest commit it has at/below the
  request (the provider floor contract `certifiers/provider.py`);
* `fc_subscribe` -> a stream of `fc_announce(FullCommit)` pushes — the
  replica follow stream: every node that commits (or certifies) a new
  height pushes the FullCommit to its subscribers, so replicas serve
  the tip without polling and without joining consensus.

Client-side trust is NEVER the transport's: a pushed/fetched
FullCommit only enters the certified cache after the node's
`BisectingCertifier` proved it. A push that fails certification with a
hard error is a FORGED commit: the peer is scored
(`forged_fullcommit`, instant ban) and any genuinely double-signed
vote inside the forgery becomes `DuplicateVoteEvidence` routed to the
evidence pool (`lightclient/evidence.py`) — the PR 9 attribution
pipeline, applied to the read path.

`PeerProvider` adapts the request/response half to the certifier
`Provider` contract so a walk can fetch candidates from ANY connected
peer — the piece that turns "one full node" into "the fleet".
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.certifiers.provider import Provider
from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.types.errors import (
    ErrNoSourceCommit,
    ErrTooMuchChange,
    ErrTrustExpired,
    ErrValidatorsChanged,
    ValidationError,
)
from tendermint_tpu.utils.lockrank import ranked_lock
from tendermint_tpu.utils.log import kv, logger

LIGHTCLIENT_CHANNEL = 0x68

_MSG_FC_REQUEST = 0x01
_MSG_FC_RESPONSE = 0x02
_MSG_FC_SUBSCRIBE = 0x03
_MSG_FC_ANNOUNCE = 0x04

_log = logger("lightclient")


def decode_message(payload: bytes):
    r = Reader(payload)
    tag = r.uvarint()
    if tag == _MSG_FC_REQUEST:
        return ("fc_request", r.uvarint())
    if tag == _MSG_FC_RESPONSE:
        height = r.uvarint()
        raw = r.bytes()
        return ("fc_response", (height, FullCommit.decode(raw) if raw else None))
    if tag == _MSG_FC_SUBSCRIBE:
        return ("fc_subscribe", None)
    if tag == _MSG_FC_ANNOUNCE:
        return ("fc_announce", FullCommit.decode(r.bytes()))
    raise ValueError(f"unknown lightclient message tag {tag:#x}")


def _enc_fc_request(height: int) -> bytes:
    return Writer().uvarint(_MSG_FC_REQUEST).uvarint(height).build()


def _enc_fc_response(height: int, fc: FullCommit | None) -> bytes:
    return (
        Writer()
        .uvarint(_MSG_FC_RESPONSE)
        .uvarint(height)
        .bytes(fc.encode() if fc is not None else b"")
        .build()
    )


def _enc_fc_subscribe() -> bytes:
    return Writer().uvarint(_MSG_FC_SUBSCRIBE).build()


def _enc_fc_announce(fc: FullCommit) -> bytes:
    return Writer().uvarint(_MSG_FC_ANNOUNCE).bytes(fc.encode()).build()


class LightClientReactor(Reactor):
    """Serves FullCommits to light clients; optionally follows pushes.

    Every node runs the serving half. Nodes built with `subscribe=True`
    (replicas, and any client that wants the tip stream) additionally
    subscribe to each peer and certify incoming pushes through
    `certifier` before caching/forwarding them.
    """

    def __init__(
        self,
        chain_id: str = "",
        block_store=None,
        state=None,
        cache=None,
        certifier=None,
        subscribe: bool = False,
        evidence_pool=None,
        verifier=None,
        request_timeout_s: float = 5.0,
    ) -> None:
        super().__init__()
        self.chain_id = chain_id
        self.block_store = block_store
        self.state = state
        self.cache = cache
        self.certifier = certifier
        self.subscribe = subscribe
        self.evidence_pool = evidence_pool
        self.verifier = verifier
        self.request_timeout_s = request_timeout_s
        # leaf lock: held over set/dict surgery only, never across sends
        self._mtx = ranked_lock("lightclient.reactor")
        self._subscribers: set[str] = set()
        # request correlation: height -> list of (event, box) waiters —
        # a LIST so concurrent same-height requests each keep their own
        # slot instead of clobbering a shared one
        self._waits: dict[int, list[tuple[threading.Event, list]]] = {}
        # subscription-liveness clock (health's serving section)
        self._last_push_mono: float | None = None
        self._last_pushed_height = 0
        # pushes certify OFF the p2p recv thread: certification may
        # fetch intermediate bisection commits from peers (PeerProvider
        # request/response), and a recv thread waiting on its own
        # peer's response would deadlock a 1-peer topology
        self._push_q: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()
        self._push_pending = 0
        self._running = False
        self._push_thread: threading.Thread | None = None

    # -- reactor interface ---------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        # FullCommit frames are commit + valset sized (tens of KB at
        # large valsets); modest queue, below block/statesync priority
        return [
            ChannelDescriptor(LIGHTCLIENT_CHANNEL, priority=2, send_queue_capacity=64)
        ]

    def on_start(self) -> None:
        self._running = True
        if self.subscribe:
            self._push_thread = threading.Thread(
                target=self._push_loop, name="lightclient-push", daemon=True
            )
            self._push_thread.start()

    def on_stop(self) -> None:
        self._running = False
        if self._push_thread is not None:
            self._push_q.put(None)

    def add_peer(self, peer: Peer) -> None:
        if self.subscribe:
            peer.try_send(LIGHTCLIENT_CHANNEL, _enc_fc_subscribe())

    def remove_peer(self, peer: Peer, reason) -> None:
        with self._mtx:
            self._subscribers.discard(peer.id)

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        kind, arg = decode_message(payload)
        if kind == "fc_request":
            fc = self.serve_commit(arg)
            if fc is not None:
                _metrics.REPLICA_PROOFS_SERVED.labels(kind="full_commit").inc()
            peer.try_send(LIGHTCLIENT_CHANNEL, _enc_fc_response(arg, fc))
        elif kind == "fc_response":
            height, fc = arg
            with self._mtx:
                waiters = list(self._waits.get(height, ()))
            for ev, box in waiters:
                box.append(fc)
                ev.set()
        elif kind == "fc_subscribe":
            with self._mtx:
                self._subscribers.add(peer.id)
        elif kind == "fc_announce":
            if self.certifier is None or not self._running:
                return  # not following: pushes are noise, not offenses
            with self._mtx:
                if self._push_pending >= 64:
                    return  # push flood: drop, the tip re-announces
                self._push_pending += 1
            self._push_q.put((peer.id, arg))

    # -- serving side --------------------------------------------------------

    def _serve_from_stores(self, height: int) -> FullCommit | None:
        """FullCommit from the local block store + historical valset
        index (the statesync reactor's `_serve_commit` shape)."""
        if self.block_store is None or self.state is None:
            return None
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            return None
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        if commit is None:
            return None
        try:
            validators = self.state.load_validators(height)
        except ValidationError:
            return None
        return FullCommit(header=meta.header, commit=commit, validators=validators)

    def serve_commit(self, height: int) -> FullCommit | None:
        """Answer one proof request: exact height first (certified
        cache, then stores), else the newest commit at/below it —
        `height=0` means the chain tip."""
        tip = self.block_store.height if self.block_store is not None else 0
        if height <= 0:
            height = max(
                tip, self.cache.latest_height() if self.cache is not None else 0
            )
            if height <= 0:
                return None
        if self.cache is not None:
            fc = self.cache.get_exact(height)
            if fc is not None:
                return fc
        fc = self._serve_from_stores(height)
        if fc is not None:
            return fc
        # floor fallbacks: the tip commit for an ahead-of-us request,
        # else the newest certified commit below the request
        if 0 < tip < height:
            fc = self._serve_from_stores(tip)
            if fc is not None:
                return fc
        if self.cache is not None:
            return self.cache.get_by_height(height)
        return None

    def announce(self, fc: FullCommit) -> None:
        """Push one (locally committed or freshly certified) FullCommit
        to every subscriber. Monotonic: never re-push old heights, so a
        forwarding replica cannot loop with its upstream."""
        with self._mtx:
            if fc.height() <= self._last_pushed_height:
                return
            self._last_pushed_height = fc.height()
            subs = set(self._subscribers)
        if not subs:
            return
        frame = _enc_fc_announce(fc)
        for p in self.switch.peers() if self.switch is not None else []:
            if p.id in subs:
                p.try_send(LIGHTCLIENT_CHANNEL, frame)

    def announce_height(self, height: int) -> None:
        """Serving-node hook (wired to EVENT_NEW_BLOCK in node.py):
        build + push the FullCommit for a height this node just
        committed. Cheap when nobody subscribed."""
        with self._mtx:
            has_subs = bool(self._subscribers)
        if not has_subs:
            return
        fc = self._serve_from_stores(height)
        if fc is not None:
            self.announce(fc)

    # -- subscribing side ----------------------------------------------------

    def _push_loop(self) -> None:
        while True:
            item = self._push_q.get()
            if item is None or not self._running:
                return
            with self._mtx:
                self._push_pending -= 1
            peer_id, fc = item
            try:
                self._on_push(peer_id, fc)
            except Exception:
                # one bad push must not kill the follow stream
                logging.getLogger(__name__).exception(
                    "fullcommit push handling failed"
                )

    def _on_push(self, peer_id: str, fc: FullCommit) -> None:
        if self.certifier is None:
            return
        cached = (
            self.cache.get_exact(fc.height()) if self.cache is not None else None
        )
        if cached is not None:
            if cached.header.hash() == fc.header.hash():
                return  # already proven (duplicate push)
            # a DIFFERENT commit at a height we already certified is a
            # fork attempt by construction — attribution, not dedup
            # (this is what catches even a fully-signed forged header)
            self._handle_forged(
                peer_id,
                fc,
                ValidationError(
                    f"conflicts with certified commit at height {fc.height()}"
                ),
            )
            return
        try:
            self.certifier.certify(fc)
        except (ErrTooMuchChange, ErrValidatorsChanged):
            # can't bridge to this height YET (e.g. still fast-syncing
            # through a valset rotation) — drop, a later push will land
            return
        except (ErrTrustExpired, ErrNoSourceCommit) as e:
            # CLIENT-side failure (stale local pin, bisection fetch
            # timed out mid-walk) — the pushing peer did nothing wrong;
            # scoring it here would ban honest peers and can partition
            # a replica fleet
            kv(
                _log,
                logging.DEBUG,
                "fullcommit push dropped (environmental)",
                height=fc.height(),
                from_peer=peer_id[:12],
                error=str(e)[:80],
            )
            return
        except ValidationError as e:
            self._handle_forged(peer_id, fc, e)
            return
        with self._mtx:
            self._last_push_mono = time.monotonic()
        if self.cache is not None:
            self.cache.put_certified(fc)
        kv(
            _log,
            logging.DEBUG,
            "fullcommit certified",
            height=fc.height(),
            from_peer=peer_id[:12],
        )
        # fan the proven tip onward to OUR subscribers (replica chains)
        self.announce(fc)

    def _handle_forged(self, peer_id: str, fc: FullCommit, err: Exception) -> None:
        """The attribution half: score the serving peer AND extract any
        genuinely double-signed votes into committed evidence."""
        _metrics.LIGHTCLIENT_BISECTIONS.labels(result="forged").inc()
        kv(
            _log,
            logging.WARNING,
            "forged fullcommit",
            height=fc.height(),
            from_peer=peer_id[:12],
            error=str(err)[:80],
        )
        if self.switch is not None:
            self.switch.report_misbehavior(
                peer_id, "forged_fullcommit", detail=str(err)
            )
        if self.evidence_pool is None:
            return
        honest = self.serve_commit(fc.height())
        if honest is None or honest.height() != fc.height():
            return
        from tendermint_tpu.lightclient.evidence import (
            extract_double_sign_evidence,
        )

        try:
            evs = extract_double_sign_evidence(
                fc, honest, self.chain_id, verifier=self.verifier
            )
        except Exception:
            logging.getLogger(__name__).exception("evidence extraction failed")
            return
        for ev in evs:
            try:
                self.evidence_pool.add_evidence(ev, val_set=honest.validators)
            except ValidationError:
                continue  # unprovable under this valset: drop

    # -- request/response client (PeerProvider's transport) ------------------

    def request_commit(self, height: int) -> FullCommit | None:
        """Fetch one FullCommit from any connected peer (each peer gets
        one `request_timeout_s` shot, like the statesync commit fetch)."""
        if self.switch is None:
            return None
        ev = threading.Event()
        box: list = []
        waiter = (ev, box)
        with self._mtx:
            self._waits.setdefault(height, []).append(waiter)
        try:
            for peer in self.switch.peers():
                ev.clear()
                peer.try_send(LIGHTCLIENT_CHANNEL, _enc_fc_request(height))
                if ev.wait(self.request_timeout_s) and box and box[-1] is not None:
                    return box[-1]
            return None
        finally:
            with self._mtx:
                waiters = self._waits.get(height)
                if waiters is not None:
                    try:
                        waiters.remove(waiter)
                    except ValueError:
                        pass
                    if not waiters:
                        self._waits.pop(height, None)

    # -- health --------------------------------------------------------------

    def serving_stats(self) -> dict:
        """The `/health` serving section's raw material (reported, not
        folded — docs/OBSERVABILITY.md "Health & SLO" conventions)."""
        with self._mtx:
            subs = len(self._subscribers)
            last_push = self._last_push_mono
        tip = self.block_store.height if self.block_store is not None else 0
        certified = self.cache.latest_height() if self.cache is not None else 0
        out = {
            "subscribers": subs,
            "subscribed": self.subscribe,
            "chain_tip": tip,
            "certified_tip": certified,
            # proof-serving lag: heights the chain is ahead of what this
            # node can prove to a light client
            "serving_lag": max(0, tip - certified) if certified else None,
            "last_push_age_s": (
                round(time.monotonic() - last_push, 3)
                if last_push is not None
                else None
            ),
        }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out


class PeerProvider(Provider):
    """Certifier `Provider` over the 0x68 request/response channel —
    candidates come from ANY connected peer/replica, not one full node.
    `store_commit` is a no-op (persistence belongs to the certified
    cache in front of this)."""

    def __init__(self, reactor: LightClientReactor) -> None:
        self._reactor = reactor

    def store_commit(self, fc: FullCommit) -> None:  # noqa: B027
        pass

    def get_by_height(self, height: int) -> FullCommit | None:
        fc = self._reactor.request_commit(height)
        if fc is not None and fc.height() <= height:
            return fc
        return None

    def latest_commit(self) -> FullCommit | None:
        return self._reactor.request_commit(0)
