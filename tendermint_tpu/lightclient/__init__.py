"""Planet-scale light-client serving layer (ROADMAP item 1).

Three cooperating pieces turn the sequential `InquiringCertifier` walk
into a horizontally scalable read path:

* `bisect.BisectingCertifier` — skipping verification: jump straight to
  the target height while the trusted valset still vouches for >1/3 of
  the new commit's power (trust-period rule), bisect on
  ErrTooMuchChange, and batch every bisection round's commit verifies
  into ONE device launch through the `VerifyCoalescer`
  (consumer="lightclient", the verify spine's sixth consumer);
* `cache.CertifiedCommitCache` — sharded, POSITIVES-ONLY cache of
  certified FullCommits (same never-cache-a-negative discipline as the
  VerifiedSigCache), durable through `db/fullcommit.FullCommitStore`;
* `reactor.LightClientReactor` — p2p channel 0x68: FullCommit
  request/response + a subscription push stream, so certifiers fetch
  proofs from any peer/replica instead of one full node, and stateless
  read replicas follow the chain tip without joining consensus.

The attribution half (PR 9): a peer caught serving a forged FullCommit
is scored (`forged_fullcommit`, instant ban) AND any genuinely
double-signed vote embedded in the forgery becomes committed
`DuplicateVoteEvidence` (`evidence.extract_double_sign_evidence`) —
not just a client-side rejection.

docs/LIGHTCLIENT.md covers the trust model, the bisection rule, the
replica topology, and every knob.
"""

from tendermint_tpu.lightclient.bisect import BisectingCertifier
from tendermint_tpu.lightclient.cache import CertifiedCommitCache
from tendermint_tpu.lightclient.evidence import extract_double_sign_evidence
from tendermint_tpu.lightclient.reactor import (
    LIGHTCLIENT_CHANNEL,
    LightClientReactor,
    PeerProvider,
)

__all__ = [
    "BisectingCertifier",
    "CertifiedCommitCache",
    "LightClientReactor",
    "PeerProvider",
    "LIGHTCLIENT_CHANNEL",
    "extract_double_sign_evidence",
]
