"""Forged-FullCommit attribution: turn a forgery into evidence.

A peer serving a FullCommit that fails certification is lying — the
client-side rejection (and the `forged_fullcommit` scorer debit) stops
the immediate attack, but PR 9's lesson is that rejection without
attribution lets a compromised VALIDATOR hide behind a disposable
relay: the interesting forgeries embed genuinely double-signed votes
(the compromised signer re-signed a fake header at a height the chain
already committed). Those are slashable, chain-committable proof.

`extract_double_sign_evidence` compares the forged commit against the
honest commit the client already trusts at the same height: every
precommit in the forgery that (a) names a DIFFERENT block than the
honest chain, (b) matches a validator of the honest set at that
height, (c) carries a GENUINE signature (verified — a garbage sig is
peer noise, not validator fault), and (d) has a conflicting honest
counterpart at the same (height, round), becomes a
`DuplicateVoteEvidence` ready for the evidence pool -> 0x38 gossip ->
block commitment pipeline.
"""

from __future__ import annotations

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT


def extract_double_sign_evidence(
    forged: FullCommit,
    honest: FullCommit,
    chain_id: str,
    verifier=None,
) -> list[DuplicateVoteEvidence]:
    """Double-sign proofs embedded in a rejected FullCommit.

    `honest` is the client's own certified commit at the same height
    (from its trusted cache/store); returns [] when heights/rounds
    cannot pair (no same-step conflict exists), when the forged sigs
    are all garbage, or when the forged block is actually the honest
    one. Never raises on malformed forgeries — the caller already
    rejected them; this is best-effort attribution.
    """
    if forged.height() != honest.height():
        return []
    try:
        forged_round = forged.commit.round()
        honest_round = honest.commit.round()
    except (ValidationError, ValueError, IndexError):
        return []
    if forged_round != honest_round:
        # DuplicateVoteEvidence requires one (height, round, type) step;
        # a different-round forgery cannot pair with the honest commit
        return []
    if forged.commit.block_id == honest.commit.block_id:
        return []
    honest_vals = honest.validators
    # honest precommits by validator address (index-aligned to the
    # honest set; the forged commit's own index alignment is untrusted)
    honest_by_addr = {}
    for idx, pc in enumerate(honest.commit.precommits):
        if pc is None or pc.type != VOTE_TYPE_PRECOMMIT:
            continue
        val = honest_vals.get_by_index(idx)
        if val is not None:
            honest_by_addr[val.address] = pc
    candidates = []  # (forged_vote, honest_vote, pubkey)
    for pc in forged.commit.precommits:
        if pc is None or pc.type != VOTE_TYPE_PRECOMMIT:
            continue
        if pc.height != forged.height() or pc.round != forged_round:
            continue
        if not pc.signature:
            continue
        hpc = honest_by_addr.get(pc.validator_address)
        if hpc is None or hpc.block_id == pc.block_id:
            continue
        _, val = honest_vals.get_by_address(pc.validator_address)
        if val is None:
            continue
        candidates.append((pc, hpc, val.pub_key.data))
    if not candidates:
        return []
    # only GENUINE forged-side signatures convict a validator; verify
    # the whole candidate set as one batch (the honest side was already
    # proven when the client certified `honest`)
    triples = [
        (pk, fv.sign_bytes(chain_id), fv.signature)
        for fv, _hv, pk in candidates
    ]
    from tendermint_tpu.types.validator_set import _verify_triples

    mask = _verify_triples(triples, verifier, consumer="lightclient")
    out: list[DuplicateVoteEvidence] = []
    for ok, (fv, hv, _pk) in zip(mask, candidates):
        if not ok:
            continue
        ev = DuplicateVoteEvidence.make(fv, hv)
        try:
            ev.validate_basic()
        except ValidationError:
            continue  # structurally unpairable (index mismatch etc.)
        out.append(ev)
    return out
