"""BisectingCertifier: skipping verification with batched bisection.

The read path's hot loop. The seed-era `InquiringCertifier`
(certifiers/certifier.py) bridges validator-set changes by walking
provider commits one `update` at a time — O(heights) sequential commit
verifies, each paying its own device launch. Per PAPERS.md ("Practical
Light Clients for Committee-Based Blockchains", "A Tendermint Light
Client") the walk collapses to O(log n):

* **skip rule** — trust jumps straight from height T to target H when
  the commit at H carries (a) >2/3 of H's OWN validator power (every
  honestly committed block does) and (b) >1/3 of the power of the set
  trusted at T (the trust-period rule: a third of the old set would
  have to be byzantine — and slashable — to vouch for a fork while
  their unbonding period lasts);
* **bisect on failure** — when the old-set overlap has decayed below
  1/3, probe a geometric ladder of intermediate heights between T and
  H, ALL verified in one batch: every bisection round is exactly ONE
  coalesced device launch (`consumer="lightclient"` — the verify
  spine's sixth consumer, riding the same `VerifyCoalescer`
  drain-order discipline as the other five), not one launch per probed
  height;
* **hard vs soft failure** — insufficient old overlap is the soft,
  expected signal (bisect denser); an invalid signature or a commit
  that cannot certify its own header is a FORGED candidate and fails
  the walk immediately (the provider is lying — callers route that to
  the peer scorer, `lightclient/reactor.py`).

Trust persistence: every candidate that passes is certified and stored
into `trusted` (a `CertifiedCommitCache` / `FullCommitStore` /
`MemProvider`), so later walks restart from the closest proven height
— the positives-only cache is the walk's memoization.

Telemetry: tendermint_lightclient_bisections_total{result},
tendermint_lightclient_walk_seconds{mode="bisect"}, span
`lightclient.walk` (rounds/launch count attrs).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.types.errors import (
    ErrNoSourceCommit,
    ErrTooMuchChange,
    ErrTrustExpired,
    ErrValidatorsChanged,
    ValidationError,
)
from tendermint_tpu.types.validator_set import ValidatorSet, _verify_triples
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT

# candidate heights probed per ladder round: lo + span/2^k for k=1..D.
# 6 gives 1/64 span resolution per round — a 256-height jump reaches
# span 4 in one failed ladder, and every round is one launch.
DEFAULT_LADDER_DEPTH = 6
# safety valve: a walk can only narrow so many times before the span
# hits 1; anything past this is a provider feeding us junk
_MAX_ROUNDS = 64


@dataclass
class _SkipPrep:
    """One candidate's host-side verification walk, pre-launch."""

    fc: FullCommit
    triples: list = field(default_factory=list)
    old_powers: list = field(default_factory=list)
    new_powers: list = field(default_factory=list)


class BisectingCertifier:
    """Self-updating light-client certifier with skipping verification.

    Subjective initialization: seed with either a trusted `FullCommit`
    (`seed=`) or a bare (validators, height) pair — the operator's
    social-consensus input, exactly like `TrustAnchor`'s pin.

    `trusted` stores PROVEN commits (certified here before any store);
    `source` supplies untrusted candidates (NodeProvider over RPC,
    PeerProvider over the 0x68 channel, MemProvider in tests) with the
    floor-lookup contract `get_by_height(h) -> newest commit <= h`.

    `trust_period_ns` bounds how stale the trusted state may be before
    the skip rule loses its slashing backstop (0 disables — in-process
    tests use deterministic far-past genesis times).
    """

    def __init__(
        self,
        chain_id: str,
        validators: ValidatorSet | None = None,
        height: int = 0,
        seed: FullCommit | None = None,
        trusted=None,
        source=None,
        verifier=None,
        consumer: str = "lightclient",
        trust_period_ns: int = 0,
        now_ns=None,
        ladder_depth: int = DEFAULT_LADDER_DEPTH,
    ) -> None:
        self.chain_id = chain_id
        self.trusted = trusted
        self.source = source
        self.verifier = verifier
        self.consumer = consumer
        self.trust_period_ns = trust_period_ns
        self._now_ns = now_ns or time.time_ns
        self.ladder_depth = max(1, ladder_depth)
        if seed is not None:
            seed.validate_basic(chain_id)
            self._valset = seed.validators
            self._height = seed.height()
            self._time_ns = seed.header.time
            if trusted is not None:
                trusted.store_commit(seed)
        elif validators is not None:
            self._valset = validators
            self._height = height
            self._time_ns = 0  # bare init: freshness starts on first jump
        else:
            raise ValidationError("BisectingCertifier needs a seed or a valset")
        # per-walk instrumentation (read by tests/bench): batched launch
        # rounds and total commit-signature verifies of the LAST walk
        self.last_walk_rounds = 0
        self.last_walk_verifies = 0
        # the last jump span that passed the skip rule — seeds the next
        # round's probe cluster (adaptive hop sizing)
        self._hop_hint = 0

    # -- public surface ------------------------------------------------------

    @property
    def validators(self) -> ValidatorSet:
        return self._valset

    @property
    def last_height(self) -> int:
        return self._height

    def certify(self, fc: FullCommit) -> None:
        """Certify one FullCommit, skipping/bisecting trust to its
        height first when the valset changed (the `InquiringCertifier.
        certify` contract, minus the sequential walk)."""
        fc.validate_basic(self.chain_id)
        # the trust-period rule gates EVERY extension of trust, the
        # direct same-valset path included: past the unbonding window
        # the pinned validators can sign anything without slashing risk
        self._check_trust_fresh()
        if fc.header.validators_hash != self._valset.hash():
            self.verify_to_height(fc.height())
            if fc.header.validators_hash != self._valset.hash():
                raise ErrValidatorsChanged(
                    f"cannot establish validators for height {fc.height()}"
                )
        # direct certification under the (now current) trusted set:
        # old == new, so the skip tally degenerates to the plain >2/3
        # quorum plus full-overlap check
        if not self._verify_candidates([fc])[0]:
            raise ErrTooMuchChange(
                f"trusted set cannot certify height {fc.height()}"
            )
        self._adopt(fc)

    def verify_to_height(self, target: int) -> None:
        """Move trust to the newest source commit at/below `target` —
        O(log n) batched rounds instead of the sequential walk."""
        t0 = time.perf_counter()
        self.last_walk_rounds = 0
        self.last_walk_verifies = 0
        try:
            with TRACER.span(
                "lightclient.walk", target=target, from_height=self._height
            ):
                self._walk(target)
        except ErrTooMuchChange:
            _metrics.LIGHTCLIENT_BISECTIONS.labels(result="too_much_change").inc()
            raise
        except ErrTrustExpired:
            _metrics.LIGHTCLIENT_BISECTIONS.labels(result="trust_expired").inc()
            raise
        except ErrNoSourceCommit:
            _metrics.LIGHTCLIENT_BISECTIONS.labels(result="no_source").inc()
            raise
        except ValidationError:
            # only genuine candidate defects (bad signature, impossible
            # quorum, malformed votes) land here — the forgery signal
            # operators alert on must not be polluted by client-side
            # staleness or fetch failures (the typed errors above)
            _metrics.LIGHTCLIENT_BISECTIONS.labels(result="forged").inc()
            raise
        _metrics.LIGHTCLIENT_BISECTIONS.labels(result="ok").inc()
        _metrics.LIGHTCLIENT_WALK_SECONDS.labels(mode="bisect").observe(
            time.perf_counter() - t0
        )

    # -- the walk ------------------------------------------------------------

    def _restart_from_trusted(self, target: int) -> None:
        """Resume from the closest PROVEN commit at/below the target
        (the cache memoization — same restart rule as the inquirer)."""
        if self.trusted is None:
            return
        tfc = self.trusted.get_by_height(target)
        if tfc is not None and tfc.height() > self._height:
            self._valset = tfc.validators
            self._height = tfc.height()
            self._time_ns = tfc.header.time

    def _check_trust_fresh(self) -> None:
        if self.trust_period_ns <= 0 or not self._time_ns:
            return
        age = self._now_ns() - self._time_ns
        if age > self.trust_period_ns:
            raise ErrTrustExpired(
                f"light-client trust expired: trusted header is "
                f"{age / 1e9:.0f}s old, trust period "
                f"{self.trust_period_ns / 1e9:.0f}s — re-initialize the pin"
            )

    def _walk(self, target: int) -> None:
        if self.source is None:
            raise ErrNoSourceCommit("no source provider to walk")
        self._restart_from_trusted(target)
        self._check_trust_fresh()
        if target <= self._height:
            return
        sfc = self.source.get_by_height(target)
        if sfc is None:
            raise ErrNoSourceCommit(
                f"no source commit at/below height {target}"
            )
        if sfc.height() <= self._height:
            return  # source lags our trust: nothing newer to learn
        target = sfc.height()
        hi = target
        include_hi = True
        rounds = 0
        while self._height < target:
            rounds += 1
            if rounds > _MAX_ROUNDS:
                raise ErrTooMuchChange(
                    f"bisection did not converge between "
                    f"{self._height} and {target}"
                )
            fcs = self._fetch(self._probe_heights(self._height, hi, include_hi))
            if not fcs:
                raise ErrTooMuchChange(
                    f"no intermediate commit between {self._height} and {hi}"
                )
            self.last_walk_rounds += 1
            verdicts = self._verify_candidates(fcs)  # ONE launch
            passing = [fc for fc, ok in zip(fcs, verdicts) if ok]
            if passing:
                # every passing candidate is certified — persist them
                # all (ascending, so the trusted store's floor lookups
                # can restart anywhere along the bridge), then retry
                # the remaining span from the highest
                prev = self._height
                for fc in sorted(passing, key=lambda f: f.height()):
                    self._adopt(fc)
                self._hop_hint = self._height - prev  # a span that WORKED
                hi = target
                include_hi = True
            else:
                lowest = min(fc.height() for fc in fcs)
                if lowest <= self._height + 1:
                    raise ErrTooMuchChange(
                        f"cannot bridge validator change between "
                        f"{self._height} and {lowest}"
                    )
                hi = lowest  # narrow; hi itself just failed, skip it
                include_hi = False

    def _probe_heights(self, lo: int, hi: int, include_hi: bool) -> list[int]:
        """One round's candidate heights, highest first — ALL verified
        in a single launch: the remaining span's endpoint, a cluster
        around the last jump size that worked (`_hop_hint` ratchets the
        hop toward the trust-rule limit on uniformly-rotating chains),
        and the geometric bisection ladder underneath as the fallback
        bridge."""
        span = hi - lo
        spans: set[int] = set()
        if include_hi:
            spans.add(span)
        if self._hop_hint:
            for m in (2.0, 1.5, 1.25, 1.0):
                s = int(self._hop_hint * m)
                if 0 < s < span:
                    spans.add(s)
        for k in range(1, self.ladder_depth + 1):
            s = span >> k
            if s > 0:
                spans.add(s)
        return sorted((lo + s for s in spans if 0 < s <= span), reverse=True)

    def _fetch(self, heights: list[int]) -> list[FullCommit]:
        """Source lookups for the probe heights; the floor contract may
        return lower heights — dedup, keep only ones above trust."""
        seen: set[int] = set()
        out: list[FullCommit] = []
        for h in heights:
            fc = self.source.get_by_height(h)
            if fc is None:
                continue
            fh = fc.height()
            if fh <= self._height or fh in seen:
                continue
            seen.add(fh)
            out.append(fc)
        return out

    # -- skip verification (the batched hot path) ----------------------------

    def _collect_skip(self, fc: FullCommit) -> _SkipPrep:
        """Host-side walk of one candidate commit: triples under the
        candidate's OWN valset (the signatures are the new set's), with
        per-lane old-set power credit for validators the trusted set
        also contains. Malformed votes fail hard — a legit provider
        never serves them.

        Trusted-set credit requires the trusted validator's KEY, not
        just its address: the lane signature is verified under
        `new_val.pub_key`, and the untrusted candidate valset binds
        addresses to whatever pubkeys its author chose. Crediting by
        address alone would let a forger reuse every trusted address
        with attacker keys and fake the >1/3 overlap (the same rule
        `verify_commit_any` enforces by verifying overlap signatures
        under `old_val.pub_key`). Each trusted validator is credited at
        most once per candidate, so a replayed signature in duplicate
        lanes cannot double-count old power."""
        old = self._valset
        new = fc.validators
        commit = fc.commit
        height = fc.height()
        if len(new.validators) != len(commit.precommits):
            raise ValidationError("commit size != valset size")
        round_ = commit.round()
        prep = _SkipPrep(fc=fc)
        seen_old: set[bytes] = set()
        for idx, precommit in enumerate(commit.precommits):
            if precommit is None:
                continue
            if precommit.height != height or precommit.round != round_:
                raise ValidationError("commit vote height/round mismatch")
            if precommit.type != VOTE_TYPE_PRECOMMIT:
                raise ValidationError("commit vote is not a precommit")
            if precommit.block_id != commit.block_id:
                continue  # nil/other votes carry no power
            new_val = new.validators[idx]
            _, old_val = old.get_by_address(new_val.address)
            prep.triples.append(
                (
                    new_val.pub_key.data,
                    precommit.sign_bytes(self.chain_id),
                    precommit.signature,
                )
            )
            prep.new_powers.append(new_val.voting_power)
            old_credit = 0
            if (
                old_val is not None
                and old_val.pub_key.data == new_val.pub_key.data
                and old_val.address not in seen_old
            ):
                seen_old.add(old_val.address)
                old_credit = old_val.voting_power
            prep.old_powers.append(old_credit)
        return prep

    def _verify_candidates(self, fcs: list[FullCommit]) -> list[bool]:
        """Verify a whole round of candidates as ONE flat signature
        batch (the coalescer merges it into a single launch; cache hits
        are withheld). Returns per-candidate skip verdicts: True iff
        >2/3 new-set quorum AND >1/3 trusted-set overlap."""
        preps = []
        all_triples = []
        for fc in fcs:
            fc.validate_basic(self.chain_id)
            prep = self._collect_skip(fc)
            preps.append(prep)
            all_triples.extend(prep.triples)
        self.last_walk_verifies += len(all_triples)
        mask = _verify_triples(all_triples, self.verifier, consumer=self.consumer)
        out: list[bool] = []
        at = 0
        old_total = self._valset.total_voting_power
        for prep in preps:
            k = len(prep.triples)
            sub = mask[at : at + k]
            at += k
            new_tallied = 0
            old_tallied = 0
            for ok, np_, op in zip(sub, prep.new_powers, prep.old_powers):
                if not ok:
                    # an invalid signature inside a served commit is a
                    # forgery, never a bisection trigger
                    raise ValidationError(
                        f"invalid commit signature at height "
                        f"{prep.fc.height()} (forged candidate)"
                    )
                new_tallied += np_
                old_tallied += op
            new_total = prep.fc.validators.total_voting_power
            if not new_tallied * 3 > new_total * 2:
                raise ValidationError(
                    f"candidate at height {prep.fc.height()} lacks its own "
                    f"+2/3 quorum ({new_tallied} of {new_total})"
                )
            # the skip rule: strictly more than 1/3 of TRUSTED power
            out.append(old_tallied * 3 > old_total)
        return out

    def _adopt(self, fc: FullCommit) -> None:
        if fc.height() <= self._height:
            return
        self._valset = fc.validators
        self._height = fc.height()
        self._time_ns = fc.header.time
        if self.trusted is not None:
            self.trusted.store_commit(fc)
