"""CertifiedCommitCache: sharded, positives-only FullCommit cache.

The proof cache in front of the certifier walk. Discipline mirrors the
`VerifiedSigCache` (services/batcher.py): ONLY commits that passed
certification enter (`put_certified` is the single write path, called
after a walk/skip verification succeeded), so a forged FullCommit can
never pin trust — a lookup hit means "this exact commit was proven by
this process (or a previous run, via the durable store)".

Layout: height-sharded entry maps under per-shard locks (concurrent
readers on the serving path never contend on one lock) + one compact
sorted height index for the floor-lookup contract
(`get_by_height(h)` -> largest cached height <= h, the provider
primitive bisection restarts from). An optional `FullCommitStore`
(db/fullcommit.py) makes the cache write-through durable: a restarted
replica reloads exactly the trust it had proven.

Telemetry: tendermint_lightclient_cache_{hits,misses}_total.
"""

from __future__ import annotations

import bisect as _bisect

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.certifiers.provider import Provider
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.utils.lockrank import ranked_lock

DEFAULT_CACHE_SIZE = 2048


class CertifiedCommitCache(Provider):
    """Thread-safe LRU-ish cache of CERTIFIED FullCommits by height.

    Provider-compatible so it slots straight in as a certifier's
    `trusted` store; `store_commit` is an alias of `put_certified` —
    certifiers only store commits they proved, which is exactly the
    positives-only contract.
    """

    SHARDS = 8

    def __init__(self, capacity: int | None = None, store=None) -> None:
        self.capacity = DEFAULT_CACHE_SIZE if capacity is None else capacity
        self.store = store
        self._shards = [
            (ranked_lock("lightclient.cache", seq=i), {})
            for i in range(self.SHARDS)
        ]
        # sorted height index for floor lookups; guarded by shard 0's
        # lock sibling (its own lock instance, same rank — never nested
        # with the shard locks)
        self._index_lock = ranked_lock("lightclient.cache", seq=self.SHARDS)
        self._heights: list[int] = []
        if store is not None:
            # warm from the durable half: everything in the store was
            # certified before it was persisted
            for h in store.heights():
                self._heights.append(h)
            self._heights.sort()

    def _shard(self, height: int):
        return self._shards[height % self.SHARDS]

    # -- write path (certified commits ONLY) -------------------------------

    def put_certified(self, fc: FullCommit) -> None:
        """Admit one PROVEN FullCommit. Callers must only pass commits
        whose certification succeeded — there is deliberately no way to
        cache a rejection, so a forged commit is re-verified (and
        re-rejected) on every offer."""
        h = fc.height()
        lock, entries = self._shard(h)
        with lock:
            entries[h] = fc
        self._index_insert(h)
        if self.store is not None:
            self.store.store_commit(fc)
        self._evict_over_capacity()

    def _index_insert(self, h: int) -> None:
        with self._index_lock:
            i = _bisect.bisect_left(self._heights, h)
            if i >= len(self._heights) or self._heights[i] != h:
                self._heights.insert(i, h)

    def store_commit(self, fc: FullCommit) -> None:
        self.put_certified(fc)

    def _evict_over_capacity(self) -> None:
        """Oldest-height eviction: the hot heights on a serving replica
        are the recent ones (hot-height skew), and floor lookups stay
        correct — an evicted height just restarts a walk lower."""
        if self.capacity <= 0:
            return
        while True:
            with self._index_lock:
                if len(self._heights) <= self.capacity:
                    return
                h = self._heights.pop(0)
            lock, entries = self._shard(h)
            with lock:
                entries.pop(h, None)

    # -- read path ----------------------------------------------------------

    def get_exact(self, height: int) -> FullCommit | None:
        """Exact-height lookup (the proof-serving path)."""
        lock, entries = self._shard(height)
        with lock:
            fc = entries.get(height)
        if fc is not None:
            _metrics.LIGHTCLIENT_CACHE_HITS.inc()
            return fc
        if self.store is not None:
            fc = self.store.get_exact(height)
            if fc is not None:
                # re-admit the durable entry to the hot tier — and back
                # into the height index, or the evictor (which only
                # drops heights it pops from the index) never sees it
                # and the shard grows without bound
                with lock:
                    entries[height] = fc
                self._index_insert(height)
                self._evict_over_capacity()
                _metrics.LIGHTCLIENT_CACHE_HITS.inc()
                return fc
        _metrics.LIGHTCLIENT_CACHE_MISSES.inc()
        return None

    def get_by_height(self, height: int) -> FullCommit | None:
        """Floor lookup (provider contract): newest certified commit at
        or below `height`."""
        with self._index_lock:
            i = _bisect.bisect_right(self._heights, height)
            h = self._heights[i - 1] if i > 0 else None
        if h is None:
            _metrics.LIGHTCLIENT_CACHE_MISSES.inc()
            return None
        return self.get_exact(h)

    def latest_commit(self) -> FullCommit | None:
        with self._index_lock:
            h = self._heights[-1] if self._heights else None
        return self.get_exact(h) if h is not None else None

    def latest_height(self) -> int:
        with self._index_lock:
            return self._heights[-1] if self._heights else 0

    def __len__(self) -> int:
        with self._index_lock:
            return len(self._heights)

    def stats(self) -> dict:
        """Cache-warmth view for `/health`'s serving section."""
        return {
            "capacity": self.capacity,
            "entries": len(self),
            "latest_height": self.latest_height(),
            "hits": _metrics.LIGHTCLIENT_CACHE_HITS.value,
            "misses": _metrics.LIGHTCLIENT_CACHE_MISSES.value,
        }
