"""Light client: certify headers without replaying the chain
(reference `certifiers/`).

A light client holds a trusted validator set and certifies incoming
(header, commit) pairs against it; validator-set changes are followed
with the >2/3-continuity rule (`VerifyCommitAny`), bisecting through
stored intermediate commits when one jump changes too much.

TPU angle (BASELINE config 2): commit replay is embarrassingly
batchable — `StaticCertifier.certify_batch` verifies K same-valset
commits in one device call through the valset-table kernel.
"""

from tendermint_tpu.certifiers.certifier import (
    DynamicCertifier,
    FullCommit,
    InquiringCertifier,
    StaticCertifier,
)
from tendermint_tpu.certifiers.provider import (
    FileProvider,
    MemProvider,
    Provider,
)

__all__ = [
    "DynamicCertifier",
    "FileProvider",
    "FullCommit",
    "InquiringCertifier",
    "MemProvider",
    "Provider",
    "StaticCertifier",
]
