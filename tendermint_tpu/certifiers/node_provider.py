"""Light-client Provider backed by a live node's RPC (reference
`certifiers/client/provider.go`).

Fetches FullCommits over the `/commit` + `/validators` routes so an
external light client can feed directly from a running node — the
missing half that made Mem/File providers test-only. `store_commit` is
a no-op (the node is the source of truth); compose with a caching
provider (Mem/File) via the Inquiring certifier for persistence.
"""

from __future__ import annotations

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.certifiers.provider import Provider
from tendermint_tpu.crypto.keys import PubKey
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote


def _block_id_from_json(d: dict) -> BlockID:
    return BlockID(
        hash=bytes.fromhex(d["hash"]),
        parts_header=PartSetHeader(
            total=int(d["parts"]["total"]),
            hash=bytes.fromhex(d["parts"]["hash"]),
        ),
    )


def header_from_json(d: dict) -> Header:
    return Header(
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time=int(d["time"]),
        num_txs=int(d["num_txs"]),
        last_block_id=_block_id_from_json(d["last_block_id"]),
        last_commit_hash=bytes.fromhex(d["last_commit_hash"]),
        data_hash=bytes.fromhex(d["data_hash"]),
        validators_hash=bytes.fromhex(d["validators_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
    )


def commit_from_json(d: dict) -> Commit:
    precommits: list[Vote | None] = []
    for v in d["precommits"]:
        if v is None:
            precommits.append(None)
            continue
        precommits.append(
            Vote(
                validator_address=bytes.fromhex(v["validator_address"]),
                validator_index=int(v["validator_index"]),
                height=int(v["height"]),
                round=int(v["round"]),
                timestamp=int(v["timestamp"]),
                type=int(v["type"]),
                block_id=_block_id_from_json(v["block_id"]),
                signature=bytes.fromhex(v["signature"]),
            )
        )
    return Commit(block_id=_block_id_from_json(d["block_id"]), precommits=precommits)


def validator_set_from_json(vals: list[dict]) -> ValidatorSet:
    return ValidatorSet(
        [
            Validator(
                address=bytes.fromhex(v["address"]),
                pub_key=PubKey(bytes.fromhex(v["pub_key"])),
                voting_power=int(v["voting_power"]),
            )
            for v in vals
        ]
    )


class NodeProvider(Provider):
    """Provider over a node RPC client (HTTPClient or LocalClient)."""

    def __init__(self, client) -> None:
        self._client = client

    def store_commit(self, fc: FullCommit) -> None:  # noqa: B027
        pass  # the node already has it; persistence belongs to a cache

    def _fetch(self, height: int) -> FullCommit | None:
        res = self._client.commit(height)
        if "header" not in res:
            return None
        return FullCommit(
            header=header_from_json(res["header"]),
            commit=commit_from_json(res["commit"]),
            validators=validator_set_from_json(
                self._client.validators(height)["validators"]
            ),
        )

    def get_by_height(self, height: int) -> FullCommit | None:
        from tendermint_tpu.rpc.client import RPCClientError

        try:
            return self._fetch(height)
        except RPCClientError:
            # node answered "no commit at that exact height" — fall back to
            # the newest one not above it (the provider contract). Transport
            # and parse failures propagate: a flaky node must not be
            # indistinguishable from a missing height.
            latest = self.latest_commit()
            if latest is not None and latest.height() <= height:
                return latest
            return None

    def latest_commit(self) -> FullCommit | None:
        from tendermint_tpu.rpc.client import RPCClientError

        try:
            h = int(self._client.status()["sync_info"]["latest_block_height"])
        except RPCClientError:
            return None
        if h < 1:
            return None
        return self._fetch(h)
