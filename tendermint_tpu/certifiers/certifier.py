"""Static / Dynamic / Inquiring certifiers.

Reference `certifiers/static.go:22,49-65` (fixed valset),
`dynamic.go:20-93` (follows valset changes via VerifyCommitAny), and
`inquirer.go:9,40-120` (auto-fetches missing valsets from providers,
bisecting over heights when one update changes more than 2/3).
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.errors import (
    ErrTooMuchChange,
    ErrValidatorsChanged,
    ValidationError,
)
from tendermint_tpu.types.validator_set import Validator, ValidatorSet


@dataclass
class FullCommit:
    """A header + the commit that sealed it + the validator set that
    signed (reference `certifiers/commit.go` FullCommit)."""

    header: Header
    commit: Commit
    validators: ValidatorSet

    def height(self) -> int:
        return self.header.height

    def validate_basic(self, chain_id: str) -> None:
        if self.header.chain_id != chain_id:
            raise ValidationError(
                f"wrong chain id: {self.header.chain_id} != {chain_id}"
            )
        if self.commit.height() != self.header.height:
            raise ValidationError("commit height != header height")
        if self.commit.block_id.hash != self.header.hash():
            raise ValidationError("commit is not for this header")
        if self.header.validators_hash != self.validators.hash():
            raise ValidationError("validator set does not match header")
        self.commit.validate_basic()

    def encode(self) -> bytes:
        w = Writer().bytes(self.header.encode()).bytes(self.commit.encode())
        w.uvarint(len(self.validators.validators))
        for v in self.validators.validators:
            w.bytes(v.address).bytes(v.pub_key.data)
            w.uvarint(v.voting_power).svarint(v.accum)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "FullCommit":
        from tendermint_tpu.crypto import PubKey

        r = Reader(data)
        header = Header.decode_from(Reader(r.bytes()))
        commit = Commit.decode_from(Reader(r.bytes()))
        vals = []
        for _ in range(r.uvarint()):
            addr, pub = r.bytes(), r.bytes()
            power, accum = r.uvarint(), r.svarint()
            vals.append(
                Validator(
                    address=addr,
                    pub_key=PubKey(pub),
                    voting_power=power,
                    accum=accum,
                )
            )
        return cls(header=header, commit=commit, validators=ValidatorSet(vals))


class StaticCertifier:
    """Certify against one fixed validator set (reference
    `static.go:49-65`). Raises ErrValidatorsChanged when the header
    names a different set — the dynamic/inquiring layers react to that.

    `consumer` tags this walk's verify requests for the coalescer
    (`services/batcher.py`): light-client walks default to "rpc", the
    statesync trust anchor re-tags its certifiers "statesync" — so a
    certifier re-walk over overlapping valsets both hits the dedup
    cache and merges its novel signatures into whatever launch the
    consensus/fast-sync pipelines have in flight."""

    def __init__(
        self,
        chain_id: str,
        validators: ValidatorSet,
        verifier=None,
        consumer: str = "rpc",
    ):
        self.chain_id = chain_id
        self.validators = validators
        self.verifier = verifier
        self.consumer = consumer

    def certify(self, fc: FullCommit) -> None:
        self.certify_batch([fc])

    def certify_batch(self, fcs: list[FullCommit]) -> None:
        """Certify K commits of this one valset as a single device batch
        (BASELINE config 2's 10k-commit replay shape; the reference
        loops `certifiers/performance_test.go:10-80` one at a time)."""
        entries = []
        trusted_hash = self.validators.hash()
        for fc in fcs:
            fc.validate_basic(self.chain_id)
            if fc.header.validators_hash != trusted_hash:
                raise ErrValidatorsChanged(
                    f"validator hash changed at height {fc.height()}"
                )
            entries.append((fc.commit.block_id, fc.height(), fc.commit))
        self.validators.verify_commit_batched(
            self.chain_id, entries, verifier=self.verifier, consumer=self.consumer
        )


class DynamicCertifier:
    """Static + the ability to follow validator-set changes: `update`
    accepts a new FullCommit if >2/3 of the CURRENT trusted set signed
    it (reference `dynamic.go:49-93`)."""

    def __init__(
        self,
        chain_id: str,
        validators: ValidatorSet,
        height: int = 0,
        verifier=None,
        consumer: str = "rpc",
    ):
        self.cert = StaticCertifier(chain_id, validators, verifier, consumer=consumer)
        self.last_height = height

    @property
    def chain_id(self) -> str:
        return self.cert.chain_id

    @property
    def validators(self) -> ValidatorSet:
        return self.cert.validators

    def certify(self, fc: FullCommit) -> None:
        self.cert.certify(fc)

    def update(self, fc: FullCommit) -> None:
        """Reference `Update dynamic.go:60-93`: the new set is trusted
        only if the old one vouches for it with >2/3 of its power."""
        if fc.height() <= self.last_height:
            raise ValidationError(
                f"update height {fc.height()} <= trusted {self.last_height}"
            )
        fc.validate_basic(self.chain_id)
        # raises ErrTooMuchChange when old-set overlap is below 2/3
        self.cert.validators.verify_commit_any(
            fc.validators,
            self.chain_id,
            fc.commit.block_id,
            fc.height(),
            fc.commit,
            verifier=self.cert.verifier,
            consumer=self.cert.consumer,
        )
        self.cert = StaticCertifier(
            self.chain_id, fc.validators, self.cert.verifier,
            consumer=self.cert.consumer,
        )
        self.last_height = fc.height()


class InquiringCertifier:
    """Self-updating certifier: walks provider-stored FullCommits to
    bridge validator-set changes, bisecting when one jump exceeds the
    2/3 continuity rule (reference `inquirer.go:40-120`).

    `trusted` holds commits we have verified (seeded with one trusted
    FullCommit); `source` supplies untrusted candidates (e.g. fetched
    from a full node) which become trusted only after `update` succeeds.
    """

    def __init__(
        self,
        chain_id: str,
        seed: FullCommit,
        trusted,
        source,
        verifier=None,
        consumer: str = "rpc",
    ):
        self.chain_id = chain_id
        self.trusted = trusted
        self.source = source
        self.verifier = verifier
        self.consumer = consumer
        trusted.store_commit(seed)
        self.cert = DynamicCertifier(
            chain_id, seed.validators, seed.height(), verifier, consumer=consumer
        )

    @property
    def validators(self) -> ValidatorSet:
        return self.cert.validators

    def certify(self, fc: FullCommit) -> None:
        """Certify, auto-updating the trusted valset if it changed."""
        fc.validate_basic(self.chain_id)
        if fc.header.validators_hash != self.cert.validators.hash():
            self.update_to_height(fc.height())
            if fc.header.validators_hash != self.cert.validators.hash():
                raise ErrValidatorsChanged(
                    f"cannot establish validators for height {fc.height()}"
                )
        self.cert.certify(fc)
        self.trusted.store_commit(fc)

    def update_to_height(self, height: int) -> None:
        """Move the trusted valset to the one in force at `height`.

        This is the O(heights) SEQUENTIAL walk — kept as the reference
        baseline (and the `mode="sequential"` leg of
        `tendermint_lightclient_walk_seconds`); the production read
        path is `lightclient/bisect.BisectingCertifier`, which replaces
        it with O(log n) batched skipping verification."""
        import time as _time

        from tendermint_tpu.telemetry import metrics as _metrics

        t0 = _time.perf_counter()
        try:
            self._update_to_height(height)
        finally:
            _metrics.LIGHTCLIENT_WALK_SECONDS.labels(mode="sequential").observe(
                _time.perf_counter() - t0
            )

    def _update_to_height(self, height: int) -> None:
        # restart from the closest trusted commit at/below the target
        tfc = self.trusted.get_by_height(height)
        if tfc is not None and tfc.height() > self.cert.last_height:
            self.cert = DynamicCertifier(
                self.chain_id,
                tfc.validators,
                tfc.height(),
                self.verifier,
                consumer=self.consumer,
            )
        sfc = self.source.get_by_height(height)
        if sfc is None:
            raise ValidationError(f"no source commit at/below height {height}")
        if sfc.height() <= self.cert.last_height:
            # source lags our trust store: nothing newer to learn — the
            # caller's hash recheck reports ErrValidatorsChanged
            return
        self._update_via(sfc)

    def _update_via(self, sfc: FullCommit) -> None:
        """Try one update jump; on ErrTooMuchChange bisect through an
        intermediate height (reference `updateToHeight inquirer.go:100-120`)."""
        try:
            self.cert.update(sfc)
            self.trusted.store_commit(sfc)
            return
        except ErrTooMuchChange:
            pass
        lo, hi = self.cert.last_height, sfc.height()
        mid = (lo + hi) // 2
        if mid in (lo, hi):
            raise ErrTooMuchChange(
                f"cannot bridge validator change between {lo} and {hi}"
            )
        mfc = self.source.get_by_height(mid)
        if mfc is None or mfc.height() <= lo:
            raise ErrTooMuchChange(
                f"no intermediate commit between {lo} and {hi}"
            )
        self._update_via(mfc)  # first half (recursive bisection)
        self._update_via(sfc)  # then retry the target
