"""FullCommit storage providers (reference `certifiers/provider.go`,
`memprovider.go`, `files/`).

`get_by_height(h)` returns the stored FullCommit with the LARGEST
height <= h (the bisection walk's primitive).
"""

from __future__ import annotations

import bisect
import os
import threading

from tendermint_tpu.certifiers.certifier import FullCommit


class Provider:
    def store_commit(self, fc: FullCommit) -> None:
        raise NotImplementedError

    def get_by_height(self, height: int) -> FullCommit | None:
        raise NotImplementedError

    def latest_commit(self) -> FullCommit | None:
        raise NotImplementedError


class MemProvider(Provider):
    """In-memory provider (reference `memprovider.go`)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._heights: list[int] = []
        self._by_height: dict[int, FullCommit] = {}

    def store_commit(self, fc: FullCommit) -> None:
        with self._lock:
            h = fc.height()
            if h not in self._by_height:
                bisect.insort(self._heights, h)
            self._by_height[h] = fc

    def get_by_height(self, height: int) -> FullCommit | None:
        with self._lock:
            i = bisect.bisect_right(self._heights, height)
            if i == 0:
                return None
            return self._by_height[self._heights[i - 1]]

    def latest_commit(self) -> FullCommit | None:
        with self._lock:
            if not self._heights:
                return None
            return self._by_height[self._heights[-1]]


class FileProvider(Provider):
    """Directory-backed provider, one encoded FullCommit per height
    (reference `files/provider.go`). Survives restarts — the light
    client's trust store."""

    def __init__(self, dir_path: str) -> None:
        self._dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.RLock()

    def _path(self, height: int) -> str:
        return os.path.join(self._dir, f"{height:012d}.fc")

    def _heights(self) -> list[int]:
        out = []
        for name in os.listdir(self._dir):
            if name.endswith(".fc"):
                try:
                    out.append(int(name[:-3]))
                except ValueError:
                    continue
        return sorted(out)

    def store_commit(self, fc: FullCommit) -> None:
        with self._lock:
            tmp = self._path(fc.height()) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(fc.encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(fc.height()))

    def get_by_height(self, height: int) -> FullCommit | None:
        with self._lock:
            heights = self._heights()
            i = bisect.bisect_right(heights, height)
            if i == 0:
                return None
            with open(self._path(heights[i - 1]), "rb") as f:
                return FullCommit.decode(f.read())

    def latest_commit(self) -> FullCommit | None:
        with self._lock:
            heights = self._heights()
            if not heights:
                return None
            with open(self._path(heights[-1]), "rb") as f:
                return FullCommit.decode(f.read())
