"""Async dispatch pipeline: submission-order guarantees, bounded-depth
backpressure, and breaker fallback propagating through in-flight handles
(services/dispatch.py + the verify-spine async entry points).

The ordering contract under test: for handles H1, H2 submitted in that
order to one DispatchQueue, H1's launch starts before H2's, and a
consumer joining in submission order observes verdicts in submission
order — including when device faults injected mid-pipeline
(TENDERMINT_TPU_DEVICE_FAIL) swap individual launches onto the host
fallback path.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tendermint_tpu.services import dispatch as dispatch_mod
from tendermint_tpu.services.dispatch import (
    ChainedHandle,
    CompletedHandle,
    DispatchQueue,
    VerifyHandle,
)
from tendermint_tpu.services.resilient import ResilientVerifier
from tendermint_tpu.services.verifier import (
    BatchVerifier,
    DeviceBatchVerifier,
    HostBatchVerifier,
    TableBatchVerifier,
)
from tendermint_tpu.utils import fail
from tendermint_tpu.utils.circuit import OPEN, CircuitBreaker

from tests.helpers import det_priv_keys


@pytest.fixture(autouse=True)
def _clean_faults():
    fail.clear_device_faults()
    yield
    fail.clear_device_faults()


def _triples(n, corrupt=()):
    keys = det_priv_keys(n)
    out = []
    for i, k in enumerate(keys):
        msg = bytes([i]) * 8
        sig = k.sign(msg)
        if i in corrupt:
            sig = sig[:10] + bytes([sig[10] ^ 0xFF]) + sig[11:]
        out.append((k.pub_key.data, msg, sig))
    return out


class TestDispatchQueue:
    def test_fifo_launch_order_and_results(self):
        q = DispatchQueue(depth=8, name="t-fifo")
        order = []
        handles = [
            q.submit(lambda i=i: (order.append(i), i * 10)[1]) for i in range(8)
        ]
        assert [h.result() for h in handles] == [i * 10 for i in range(8)]
        assert order == list(range(8))  # launched strictly in submission order

    def test_depth_bounds_inflight_and_submit_blocks(self):
        q = DispatchQueue(depth=2, name="t-depth")
        gate = threading.Event()
        h1 = q.submit(gate.wait)
        h2 = q.submit(lambda: "second")
        third_submitted = threading.Event()

        def submit_third():
            h = q.submit(lambda: "third")
            third_submitted.set()
            h.result()

        t = threading.Thread(target=submit_third, daemon=True)
        t.start()
        time.sleep(0.1)
        # both slots held by unjoined handles: the third submit blocks
        assert not third_submitted.is_set()
        assert q.inflight() == 2
        gate.set()
        h1.result()  # join frees a slot -> third submit proceeds
        assert third_submitted.wait(5)
        assert h2.result() == "second"
        t.join(timeout=5)
        assert q.inflight() == 0

    def test_stalled_queue_raises_instead_of_wedging(self, monkeypatch):
        monkeypatch.setattr(dispatch_mod, "_STALL_TIMEOUT_S", 0.05)
        q = DispatchQueue(depth=1, name="t-stall")
        q.submit(lambda: 1)  # never joined
        with pytest.raises(RuntimeError, match="stalled"):
            q.submit(lambda: 2)

    def test_launch_exception_delivered_at_result_and_cached(self):
        q = DispatchQueue(depth=2, name="t-exc")

        def boom():
            raise ValueError("kernel exploded")

        h = q.submit(boom)
        for _ in range(2):  # result() idempotent: cached error re-raises
            with pytest.raises(ValueError, match="kernel exploded"):
                h.result()
        # the failed handle released its slot: the queue keeps working
        assert q.submit(lambda: 7).result() == 7

    def test_finalize_runs_on_joining_thread(self):
        q = DispatchQueue(depth=2, name="t-fin")
        threads = {}

        def launch():
            threads["launch"] = threading.current_thread().name
            return 3

        def finalize(v):
            threads["finalize"] = threading.current_thread().name
            return v + 1

        assert q.submit(launch, finalize).result() == 4
        assert threads["launch"].startswith("dispatch-")
        assert threads["finalize"] == threading.current_thread().name

    def test_then_chains_and_caches(self):
        q = DispatchQueue(depth=2, name="t-then")
        calls = []

        def tally(v):
            calls.append(v)
            return v * 2

        h = q.submit(lambda: 21).then(tally)
        assert isinstance(h, ChainedHandle)
        assert h.result() == 42
        assert h.result() == 42
        assert calls == [21]  # mapping ran once
        # chained over a failure: the parent's exception propagates
        h2 = q.submit(lambda: (_ for _ in ()).throw(RuntimeError("x"))).then(tally)
        with pytest.raises(RuntimeError):
            h2.result()

    def test_completed_handle(self):
        assert CompletedHandle(5).result() == 5
        assert CompletedHandle(5).done()
        assert CompletedHandle(5).then(lambda v: v + 1).result() == 6
        with pytest.raises(KeyError):
            CompletedHandle(exc=KeyError("k")).result()

    def test_close_rejects_new_submits(self):
        q = DispatchQueue(depth=2, name="t-close")
        h = q.submit(lambda: 1)
        q.close()
        assert h.result() == 1  # in-flight stays joinable
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(lambda: 2)

    def test_inflight_gauge_and_overlap_metric(self):
        from tendermint_tpu.telemetry import REGISTRY

        q = DispatchQueue(depth=3, name="t-metrics")
        h = q.submit(lambda: time.sleep(0.02) or 1)
        time.sleep(0.005)  # overlapped host work
        assert h.result() == 1
        gauge = REGISTRY.get("tendermint_dispatch_inflight")
        assert gauge.labels(queue="t-metrics").value == 0
        hist = REGISTRY.get("tendermint_dispatch_overlap_ratio")
        assert hist.labels(queue="t-metrics").value["count"] >= 1


class TestAsyncVerifierSurface:
    def test_host_verifier_async_matches_sync(self):
        v = HostBatchVerifier()
        triples = _triples(6, corrupt=(2, 4))
        q = DispatchQueue(depth=2, name="t-host")
        got = v.verify_batch_async(triples, queue=q).result()
        np.testing.assert_array_equal(got, v.verify_batch(triples))

    def test_device_verifier_small_batch_async(self):
        # below min_device_batch the launch answers on host immediately;
        # the handle must still behave like any other
        v = DeviceBatchVerifier(min_device_batch=10**6)
        triples = _triples(5, corrupt=(1,))
        got = v.verify_batch_async(triples, queue=DispatchQueue(depth=2)).result()
        assert list(got) == [True, False, True, True, True]

    def test_table_verifier_commits_async_matches_sync(self):
        v = TableBatchVerifier(min_device_batch=10**6)  # host path, no compile
        keys = det_priv_keys(4)
        pubs = [k.pub_key.data for k in keys]
        msgs = [bytes([i]) * 8 for i in range(4)]
        # commit-lane shape: (msgs, sigs) per commit
        lanes = [(msgs, [k.sign(m) for k, m in zip(keys, msgs)])]
        sync = v.verify_commits(pubs, lanes)
        got = v.verify_commits_async(
            pubs, lanes, queue=DispatchQueue(depth=2)
        ).result()
        np.testing.assert_array_equal(got, sync)
        assert got.all()


class TestBreakerThroughHandles:
    """`ResilientVerifier` fallback must resolve THROUGH the handle — a
    faulted in-flight launch re-verifies on host at the join, never
    raising into the pipeline consumer."""

    def test_faulted_launch_resolves_via_host_fallback(self):
        v = ResilientVerifier(
            DeviceBatchVerifier(min_device_batch=10**6),
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
        )
        triples = _triples(6, corrupt=(0, 3))
        fail.set_device_fault("verify", 1)
        q = DispatchQueue(depth=2, name="t-fault")
        got = v.verify_batch_async(triples, queue=q).result()  # must not raise
        expect = HostBatchVerifier().verify_batch(triples)
        np.testing.assert_array_equal(got, expect)
        assert v.breaker.state == OPEN
        assert v._dispatch.fallback_calls >= 1

    def test_finalize_fault_resolves_via_host_fallback(self):
        class _MaterializeBomb(BatchVerifier):
            def launch_verify_batch(self, triples):
                return triples  # launch "succeeds"

            def finalize_verify_batch(self, launched):
                raise RuntimeError("transfer died mid-flight")

            def verify_batch(self, triples):
                return self.finalize_verify_batch(self.launch_verify_batch(triples))

        v = ResilientVerifier(
            _MaterializeBomb(),
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
        )
        triples = _triples(4, corrupt=(2,))
        got = v.verify_batch_async(triples, queue=DispatchQueue(depth=2)).result()
        np.testing.assert_array_equal(
            got, HostBatchVerifier().verify_batch(triples)
        )
        assert v.breaker.state == OPEN

    def test_verdicts_join_in_submission_order_under_mid_pipeline_faults(self):
        """THE ordering test: several batches in flight on one queue, a
        bounded fault budget knocking out launches mid-pipeline; every
        verdict must come back correct and in submission order."""
        v = ResilientVerifier(
            DeviceBatchVerifier(min_device_batch=10**6),
            breaker=CircuitBreaker(failure_threshold=100, reset_timeout_s=60),
        )
        q = DispatchQueue(depth=3, name="t-order")
        host = HostBatchVerifier()
        # batch i corrupts lane i -> each batch has a DISTINCT verdict
        # mask, so any reordering is visible in the joined results
        batches = [_triples(6, corrupt=(i,)) for i in range(6)]
        fail.set_device_fault("verify", 2)  # faults land mid-pipeline
        handles = []
        for i, triples in enumerate(batches):
            if i >= q.depth:
                got = handles[i - q.depth][1].result()  # join oldest first
                np.testing.assert_array_equal(
                    got, host.verify_batch(batches[i - q.depth])
                )
            handles.append((i, v.verify_batch_async(triples, queue=q)))
        for i, h in handles:
            got = h.result()  # idempotent for already-joined handles
            np.testing.assert_array_equal(got, host.verify_batch(batches[i]))
            assert not got[i] and got.sum() == 5  # the batch's own mask
        assert v._dispatch.fallback_calls == 2  # both injected faults degraded

    def test_commit_grid_fault_degrades_to_host_loop(self):
        v = ResilientVerifier(
            TableBatchVerifier(min_device_batch=10**6),
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
        )
        keys = det_priv_keys(4)
        pubs = [k.pub_key.data for k in keys]
        msgs = [bytes([i]) * 8 for i in range(4)]
        sigs = [k.sign(m) for k, m in zip(keys, msgs)]
        bad = list(sigs)
        bad[1] = bytes(64)
        lanes = [(msgs, sigs), (msgs, bad)]
        fail.set_device_fault("verify")
        got = v.verify_commits_async(
            pubs, lanes, queue=DispatchQueue(depth=2)
        ).result()
        assert got.shape == (2, 4)
        assert got[0].all()
        assert not got[1][1] and got[1].sum() == 3
        assert v.breaker.state == OPEN


class TestVotePipelineOrdering:
    def test_preverify_handles_join_in_drain_order(self):
        """The consensus drain submits batch K+1 while K is in flight;
        verdict masks must map back to their own batches when joined in
        drain order (the receive loop's only join order)."""
        from tests.helpers import make_block_id, make_validators, signed_vote
        from tendermint_tpu.types import VOTE_TYPE_PREVOTE

        vals, privs = make_validators(4)
        bid = make_block_id()

        class _CS:
            """Just enough ConsensusState surface for _preverify_votes_async."""

            VOTE_PIPELINE_DEPTH = 2
            _vote_dispatch = None
            verifier = HostBatchVerifier()

            class _State:
                chain_id = "test-chain"

            def __init__(self):
                from tendermint_tpu.consensus.state import ConsensusState

                self.state = self._State()
                self.validators = vals
                self.height = 1
                self._vote_queue = ConsensusState._vote_queue.__get__(self)
                self._preverify_votes_async = (
                    ConsensusState._preverify_votes_async.__get__(self)
                )

        cs = _CS()

        def run_votes(r):
            votes = [
                signed_vote(p, i, 1, r, VOTE_TYPE_PREVOTE, bid)
                for i, p in enumerate(privs)
            ]
            if r == 1:
                votes[2] = votes[2].with_signature(bytes(64))  # distinct mask
            return votes

        # the receive loop's join discipline: the oldest batch joins
        # before a submit would exceed the pipeline depth
        pending, masks = [], []
        for r in range(3):
            if len(pending) >= cs.VOTE_PIPELINE_DEPTH:
                masks.append(pending.pop(0).result())
            pending.append(cs._preverify_votes_async(run_votes(r)))
        masks.extend(h.result() for h in pending)
        assert masks[0] == [True, True, True, True]
        assert masks[1] == [True, True, False, True]
        assert masks[2] == [True, True, True, True]
