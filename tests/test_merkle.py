import hashlib

import pytest

from tendermint_tpu.merkle import (
    simple_hash_from_byte_slices,
    simple_hash_from_hashes,
    simple_proofs_from_byte_slices,
    verify_proof,
)
from tendermint_tpu.merkle.simple import (
    inner_hash,
    leaf_hash,
    simple_hash_from_map,
)


def test_empty_and_single():
    assert simple_hash_from_byte_slices([]) == b""
    one = simple_hash_from_byte_slices([b"x"])
    assert one == hashlib.sha256(b"\x00x").digest()


def test_two_leaves_structure():
    l0, l1 = leaf_hash(b"a"), leaf_hash(b"b")
    assert simple_hash_from_byte_slices([b"a", b"b"]) == inner_hash(l0, l1)


def test_split_rule_rfc6962_shape():
    # 5 leaves: split at 4 (largest power of two < 5 — the RFC 6962 rule,
    # a documented deviation from the reference's 3/2 ceil-split)
    items = [bytes([i]) for i in range(5)]
    lh = [leaf_hash(x) for x in items]
    left = simple_hash_from_hashes(lh[:4])
    right = lh[4]
    assert simple_hash_from_byte_slices(items) == inner_hash(left, right)


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13, 64, 100])
def test_proofs_verify(n):
    items = [f"item-{i}".encode() for i in range(n)]
    root, proofs = simple_proofs_from_byte_slices(items)
    assert root == simple_hash_from_byte_slices(items)
    for i, item in enumerate(items):
        assert verify_proof(root, item, proofs[i])


def test_tampered_proof_fails():
    items = [f"item-{i}".encode() for i in range(7)]
    root, proofs = simple_proofs_from_byte_slices(items)
    assert not verify_proof(root, b"other", proofs[3])
    # wrong index's proof for the right item
    assert not verify_proof(root, items[3], proofs[4])
    # truncated aunts
    p = proofs[3]
    p.aunts = p.aunts[:-1]
    assert not verify_proof(root, items[3], p)


def test_leaf_inner_domain_separation():
    # a leaf can't be reinterpreted as an inner node
    assert leaf_hash(b"ab") != inner_hash(b"a", b"b")


def test_hash_from_map_key_order_independent():
    a = simple_hash_from_map({"x": b"1", "y": b"2"})
    b = simple_hash_from_map({"y": b"2", "x": b"1"})
    assert a == b and len(a) == 32


def test_ripemd160_variant():
    r = simple_hash_from_byte_slices([b"a", b"b"], algo="ripemd160")
    assert len(r) == 20
