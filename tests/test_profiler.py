"""Contention observatory (PR 12): sampling profiler classification +
on-CPU/blocked split, ranked-lock contention timing under real
multi-thread contention, collapsed-stack golden output, process
resource telemetry, the unified queue-wait view — and the live-net
acceptance: a 4-node loadgen run through a breaker trip whose
`tools/contention_report.py` waterfall names the most-contended lock
and the dominant blocked subsystem."""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"),
)

from tendermint_tpu.telemetry import REGISTRY
from tendermint_tpu.telemetry.profiler import (
    PROFILER,
    ContentionProfiler,
    blocked_reason,
    classify_thread,
    collapse,
)
from tendermint_tpu.utils import lockrank


@pytest.fixture(autouse=True)
def _observatory_reset():
    """Every test leaves the process-global observatory disarmed and
    empty (the profiler + lock stats are process-wide, like FLIGHT)."""
    yield
    PROFILER.stop()
    PROFILER.reset()
    lockrank.reset_contention()


def _hist_count(name: str, **labels) -> int:
    fam = REGISTRY.get(name)
    if fam is None:
        return 0
    want = tuple(str(labels[n]) for n in fam.labelnames) if labels else ()
    for values, snap in fam.samples():
        if values == want:
            return snap["count"]
    return 0


class TestClassification:
    def test_name_map_covers_node_thread_vocabulary(self):
        expect = {
            "consensus-recv": "consensus",
            "consensus-timeout": "consensus",
            "consensus-heartbeat": "consensus",
            "gossip-votes-abcdef": "consensus",
            "mempool-ingress": "ingress",
            "mempool-ingress-join": "ingress",
            "mempool-bcast-abcdef": "p2p_send",
            "verify-coalescer": "coalescer",
            "verify-coalescer-join": "coalescer",
            "dispatch-consensus": "dispatch",
            "dispatch-default": "dispatch",
            "mconn-recv": "p2p_recv",
            "mconn-send": "p2p_send",
            "mconn-ping": "p2p_send",
            "p2p-accept": "p2p_recv",
            "p2p-handshake": "p2p_recv",
            "pex-ensure": "p2p_send",
            "persistent-dial-x": "p2p_send",
            "evidence-gossip": "p2p_send",
            "statesync": "statesync",
            "fastsync": "statesync",
            "rpc-http": "rpc",
            "abci-accept": "abci",
            "abci-conn": "abci",
            "MainThread": "main",
        }
        for name, sub in expect.items():
            assert classify_thread(name) == sub, name

    def test_stack_fallback_classifies_unnamed_threads(self):
        """An HTTP-handler-style thread (generic name) classifies by
        the innermost tendermint_tpu frame."""
        from tendermint_tpu.p2p.connection import parse_frame

        try:
            parse_frame(None)  # TypeError somewhere under p2p/
            pytest.fail("expected a TypeError")
        except Exception as e:
            tb = e.__traceback__
            while tb.tb_next is not None:
                tb = tb.tb_next
            frame = tb.tb_frame
        assert classify_thread("Thread-42 (worker)", frame) == "p2p_recv"

    def test_unknown_is_other(self):
        assert classify_thread("Thread-7") == "other"

    def test_blocked_reason_lock(self):
        cond = threading.Condition()
        seen = threading.Event()

        def waiter():
            with cond:
                seen.set()
                cond.wait(timeout=10)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        assert seen.wait(5)
        time.sleep(0.05)
        frame = sys._current_frames().get(t.ident)
        assert frame is not None
        assert blocked_reason(frame) == "lock"
        with cond:
            cond.notify_all()
        t.join(5)


class TestCollapsedStacks:
    def test_collapse_golden(self):
        """The flamegraph line format is a stable contract: subsystem
        root, file:func frames, state leaf."""
        line = collapse(
            "consensus",
            ("state.py:_receive_loop", "state.py:_handle_vote"),
            "on_cpu",
        )
        assert line == (
            "consensus;state.py:_receive_loop;state.py:_handle_vote;[on_cpu]"
        )
        assert collapse("ingress", (), "blocked:lock") == "ingress;[blocked:lock]"

    def test_collapsed_output_golden(self):
        """collapsed() is deterministic: count desc, then lexical —
        byte-stable input for flamegraph tooling."""
        p = ContentionProfiler()
        with p._lock:
            p._stacks.update(
                {
                    "consensus;a.py:f;[on_cpu]": 3,
                    "ingress;b.py:g;[blocked:lock]": 7,
                    "consensus;a.py:f;[blocked:other]": 3,
                }
            )
        assert p.collapsed() == [
            "ingress;b.py:g;[blocked:lock] 7",
            "consensus;a.py:f;[blocked:other] 3",
            "consensus;a.py:f;[on_cpu] 3",
        ]


class TestLockContention:
    def test_two_threads_fighting_one_ranked_lock(self):
        """The satellite acceptance: real contention advances the wait
        histogram and attributes holds/waits to the acquiring site."""
        lk = lockrank.RankedLock("profiler.test.lock")
        before = _hist_count(
            "tendermint_lock_wait_seconds", lock="profiler.test.lock"
        )
        lockrank.set_timing(True)
        try:

            def fight():
                for _ in range(60):
                    with lk:
                        time.sleep(0.001)

            ts = [threading.Thread(target=fight) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
        finally:
            lockrank.set_timing(False)

        snap = lockrank.contention_snapshot()
        rows = {r["lock"]: r for r in snap["locks"]}
        row = rows["profiler.test.lock"]
        assert row["wait_count"] == 120
        assert row["hold_count"] == 120
        assert row["wait_s"] > 0.01  # two threads serialized on 1ms holds
        assert row["hold_s"] > 0.1
        # per-site attribution points at the `with lk:` line above
        assert row["top_sites"], "contended waits must carry a site"
        assert row["top_sites"][0]["site"].startswith("test_profiler.py:")
        # the exported histogram advanced (contended waits >= the floor)
        after = _hist_count(
            "tendermint_lock_wait_seconds", lock="profiler.test.lock"
        )
        assert after > before

    def test_disarmed_records_nothing(self):
        lk = lockrank.RankedLock("profiler.test.idle")
        assert not lockrank.timing_enabled()
        for _ in range(10):
            with lk:
                pass
        rows = {r["lock"] for r in lockrank.contention_snapshot()["locks"]}
        assert "profiler.test.idle" not in rows

    def test_condition_integration_times_reacquire(self):
        """Condition(ranked_lock) keeps working with timing armed (the
        wait() release/reacquire cycle records a hold pair, never
        corrupts the hold stack)."""
        cond = threading.Condition(lockrank.RankedLock("profiler.test.cond"))
        lockrank.set_timing(True)
        try:
            done = threading.Event()

            def waiter():
                with cond:
                    cond.wait(timeout=5)
                done.set()

            t = threading.Thread(target=waiter, daemon=True)
            t.start()
            time.sleep(0.05)
            with cond:
                cond.notify_all()
            assert done.wait(5)
            t.join(5)
        finally:
            lockrank.set_timing(False)
        rows = {r["lock"]: r for r in lockrank.contention_snapshot()["locks"]}
        assert rows["profiler.test.cond"]["hold_count"] >= 2


from tendermint_tpu.telemetry import profiler as profiler_mod


@pytest.mark.skipif(
    not profiler_mod._CPU_CLOCKS,
    reason="per-thread CPU clocks unavailable",
)
class TestOnCpuSplit:
    def test_spinner_on_cpu_sleeper_blocked(self):
        """The GIL-pressure signal: a busy-spinning thread samples
        on-CPU, a sleeping one blocked — measured via per-thread CPU
        clocks, attributed via thread names."""
        stop = threading.Event()

        def spin():
            x = 0
            while not stop.is_set():
                x += 1

        def sleeper():
            while not stop.is_set():
                time.sleep(0.005)

        threading.Thread(target=spin, name="dispatch-bench-spin", daemon=True).start()
        threading.Thread(target=sleeper, name="mconn-recv-bench", daemon=True).start()
        p = ContentionProfiler()
        p.start(hz=100)
        try:
            time.sleep(0.7)
        finally:
            p.stop()
            stop.set()
        snap = p.snapshot()
        assert snap["cpu_clock"] is True
        assert snap["samples"] > 10
        # the subsystem buckets exist (they also absorb parked worker
        # threads left over from earlier tests in a full-suite run, so
        # the on-CPU/blocked story is asserted on the uniquely-named
        # per-thread table below)
        assert "dispatch" in snap["subsystems"]
        assert "p2p_recv" in snap["subsystems"]
        spin_th = snap["threads"]["dispatch-bench-spin"]
        assert spin_th["subsystem"] == "dispatch"
        assert spin_th["samples"] > 5
        assert spin_th["on_cpu"] > spin_th["samples"] * 0.5, spin_th
        sleep_th = snap["threads"]["mconn-recv-bench"]
        assert sleep_th["subsystem"] == "p2p_recv"
        assert sleep_th["samples"] > 5
        assert sleep_th["on_cpu"] < sleep_th["samples"] * 0.5, sleep_th

    def test_boost_window_auto_disarms(self):
        p = ContentionProfiler()
        p.boost(duration_s=0.3, hz=50)
        assert p.running()
        assert lockrank.timing_enabled()
        deadline = time.monotonic() + 5
        while p.running() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not p.running()
        # the expiring sampler thread disarms the lock timers too
        deadline = time.monotonic() + 5
        while lockrank.timing_enabled() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not lockrank.timing_enabled()

    def test_env_arming(self, monkeypatch):
        from tendermint_tpu.telemetry.profiler import maybe_start_env

        monkeypatch.setenv("TENDERMINT_TPU_PROFILE_HZ", "0")
        assert maybe_start_env() is False
        monkeypatch.setenv("TENDERMINT_TPU_PROFILE_HZ", "53")
        try:
            assert maybe_start_env() is True
            assert PROFILER.running()
            assert PROFILER.hz() == 53
        finally:
            PROFILER.stop()


class TestProcessTelemetry:
    def test_gauges_read_live_values(self):
        assert REGISTRY.counter_value("tendermint_process_rss_bytes") > 1e6
        assert REGISTRY.counter_value("tendermint_process_open_fds") > 0
        assert REGISTRY.counter_value("tendermint_process_threads") >= 1

    def test_gc_pause_timing(self):
        import gc

        from tendermint_tpu.telemetry.process import install_gc_telemetry

        assert install_gc_telemetry()
        assert install_gc_telemetry()  # idempotent
        before = _hist_count("tendermint_process_gc_pause_seconds")
        gen2 = REGISTRY.counter_value(
            "tendermint_process_gc_collections_total", gen="2"
        )
        gc.collect()
        assert _hist_count("tendermint_process_gc_pause_seconds") > before
        assert (
            REGISTRY.counter_value(
                "tendermint_process_gc_collections_total", gen="2"
            )
            > gen2
        )


class TestQueueWaitView:
    def test_unified_queue_table(self):
        """The queue-wait unification: waits the subsystems already
        measure fold into one table keyed by the profiler vocabulary."""
        import numpy as np

        from tendermint_tpu.services.batcher import CoalescingVerifier
        from tendermint_tpu.telemetry import views

        class _Fake:
            def verify_batch(self, triples):
                return np.ones(len(triples), dtype=bool)

        v = CoalescingVerifier(_Fake(), cache_size=0, window_s=0.001)
        try:
            h = v.verify_batch_async(
                [(b"p" * 32, b"m", b"s" * 64)], consumer="consensus"
            )
            assert bool(h.result(timeout=10).all())
        finally:
            v.close()
        table = views.queue_wait_summary(None)
        assert set(table) >= {
            "dispatch",
            "coalescer",
            "ingress",
            "consensus",
            "p2p_send",
        }
        assert table["coalescer"]["consensus"]["count"] >= 1
        row = table["coalescer"]["consensus"]
        assert row["p99_ms"] >= row["p50_ms"] >= 0

    def test_profile_view_shape(self):
        from tendermint_tpu.telemetry import views

        out = views.collect(None, ["profile"])
        prof = out["profile"]
        assert set(prof) == {"profiler", "locks", "queues"}
        assert "subsystems" in prof["profiler"]
        assert "locks" in prof["locks"]


def _resilient_factory(threshold=2, reset_s=0.5):
    from tendermint_tpu.services.resilient import ResilientVerifier
    from tendermint_tpu.services.verifier import HostBatchVerifier
    from tendermint_tpu.utils.circuit import CircuitBreaker

    def factory(_i):
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(
                failure_threshold=threshold, reset_timeout_s=reset_s
            ),
            max_retries=0,
        )

    return factory


def _rpc(port, method, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


class TestContentionAcceptance:
    """ISSUE 12 acceptance: a live 4-node net under loadgen traffic,
    profiled through a breaker trip — the profiler thread survives and
    stays bounded, and `tools/contention_report.py` over the node's
    `dump_telemetry?profile=1` produces the per-subsystem on-CPU vs
    blocked waterfall naming the most-contended lock, the dominant
    blocked subsystem, and the move-out-first verdict."""

    def test_live_net_loadgen_contention_report(self, tmp_path):
        import itertools

        import contention_report as cr

        from tendermint_tpu.crypto.keys import gen_priv_key
        from tendermint_tpu.mempool import make_signed_tx
        from tendermint_tpu.testing.nemesis import Nemesis
        from tendermint_tpu.utils import fail

        priv = gen_priv_key(b"\x55" * 32)
        PROFILER.reset()
        lockrank.reset_contention()
        PROFILER.start(hz=97)
        try:
            with Nemesis(
                4,
                home=str(tmp_path),
                node_factory=Nemesis.full_node_factory(),
                verifier_factory=_resilient_factory(),
            ) as net:
                net.wait_height(2, timeout=90)
                stop = threading.Event()
                seq = itertools.count()

                def pump():
                    for i in seq:
                        if stop.is_set() or i >= 1500:
                            return
                        tx = make_signed_tx(priv, b"prof-%d=%d" % (i, i))
                        net.nodes[i % 2].node.mempool.check_tx_async(
                            tx, lambda res: None
                        )
                        time.sleep(0.004)

                pump_thread = threading.Thread(target=pump, daemon=True)
                pump_thread.start()
                try:
                    time.sleep(0.5)
                    # nemesis leg: device dies under load, breaker
                    # degrades to host, heals — the profiler must ride
                    # through it
                    fail.set_device_fault("verify")
                    net.wait_progress(delta=1, timeout=90)
                    fail.clear_device_faults()
                    net.wait_progress(delta=2, timeout=90)
                finally:
                    stop.set()
                    pump_thread.join(10)
                    fail.clear_device_faults()

                # survives + bounded
                assert PROFILER.running(), "profiler thread died mid-chaos"
                snap = PROFILER.snapshot()
                assert snap["samples"] > 50
                assert len(snap["threads"]) <= PROFILER.MAX_THREADS
                with PROFILER._lock:
                    n_stacks = len(PROFILER._stacks)
                assert n_stacks <= PROFILER.MAX_STACKS

                # the report, over the RPC dump of a live node
                dump = _rpc(
                    net.nodes[0].rpc_port, "dump_telemetry", spans=0, profile=1
                )
                profile = dump["profile"]
                report = cr.build_report(profile)

                assert report["samples"] > 50
                waterfall = {r["subsystem"]: r for r in report["waterfall"]}
                assert "consensus" in waterfall, waterfall.keys()
                total_on_cpu = sum(r["on_cpu"] for r in report["waterfall"])
                total_blocked = sum(r["blocked"] for r in report["waterfall"])
                assert total_on_cpu > 0 and total_blocked > 0

                # the three named answers the issue demands
                lock = report["most_contended_lock"]
                assert lock is not None and lock["lock"], report
                assert lock["wait_count"] > 0
                dom = report["dominant_blocked_subsystem"]
                assert dom is not None and dom["subsystem"]
                verdict = report["verdict"]
                assert verdict is not None
                assert verdict["move_out_first"] not in ("main", "other")
                assert "ROADMAP item 4" in verdict["reason"]

                text = cr.render_text(report)
                assert "most-contended lock: " + lock["lock"] in text
                assert (
                    "dominant blocked subsystem: " + dom["subsystem"] in text
                )
                assert "verdict: " in text

                # flamegraph output is non-empty, well-formed lines
                lines = cr.collapsed_lines(profile)
                assert lines
                for line in lines[:5]:
                    stack, count = line.rsplit(" ", 1)
                    assert ";" in stack and int(count) > 0

                # the unified queue table rode along
                assert "queues" in profile
                assert "dispatch" in profile["queues"]
        finally:
            PROFILER.stop()
            PROFILER.reset()
            lockrank.reset_contention()
