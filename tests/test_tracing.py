"""Cross-node distributed tracing + consensus flight recorder.

Covers the tracing subsystem bottom-up: TraceContext mint/sampling and
wire codec (old frames decode unchanged — golden bytes), thread-ambient
propagation across a multi-switch relay chain, trace capture through
the coalescer/dispatch spine, mempool admission traces, the flight
recorder (ring, atomic dumps, SIGUSR2), `tools/trace_timeline.py`
merging, the nemesis dump-on-violation wiring, and THE acceptance
scenario: one tx driven through a live 4-validator net whose single
trace_id timeline contains admission, gossip hops on ≥2 nodes, a
coalescer flush, a dispatch launch, and the commit — with
`tendermint_tx_e2e_seconds` observed and the flight recorder replaying
that height's round transitions.
"""

from __future__ import annotations

import glob
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from tendermint_tpu.codec.binary import Reader, encode_bytes, encode_uvarint
from tendermint_tpu.telemetry import REGISTRY, TRACER
from tendermint_tpu.telemetry import tracectx as tc
from tendermint_tpu.telemetry.flightrec import (
    FLIGHT,
    FlightRecorder,
    install_signal_dump,
)
from tendermint_tpu.telemetry.tracectx import TraceContext
from tendermint_tpu.telemetry.tracer import Tracer


@pytest.fixture(autouse=True)
def _clean_sampling_state():
    """Each test starts with sampling un-forced: boost() windows (from
    breaker trips in this or earlier tests) and force_all must not leak
    across test boundaries."""
    tc.force_all(False)
    with tc._boost_lock:
        tc._boost_until = 0.0
    yield


def _load_timeline_tool():
    spec = importlib.util.spec_from_file_location(
        "trace_timeline",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
            "trace_timeline.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTraceContext:
    def test_mint_rate_is_exact(self, monkeypatch):
        monkeypatch.setenv(tc.SAMPLE_ENV, "4")
        minted = [tc.mint("n0") for _ in range(40)]
        assert sum(1 for c in minted if c is not None) == 10

    def test_rate_zero_disables_and_one_samples_all(self, monkeypatch):
        monkeypatch.setenv(tc.SAMPLE_ENV, "0")
        assert all(tc.mint("n0") is None for _ in range(8))
        monkeypatch.setenv(tc.SAMPLE_ENV, "1")
        assert all(tc.mint("n0") is not None for _ in range(8))

    def test_force_and_boost_override_rate(self, monkeypatch):
        monkeypatch.setenv(tc.SAMPLE_ENV, "0")
        tc.force_all(True)
        try:
            assert tc.mint("n0") is not None
        finally:
            tc.force_all(False)
        assert tc.mint("n0") is None
        tc.boost(duration_s=5.0)
        assert tc.sampling_forced()
        assert tc.mint("n0") is not None
        tc.boost(duration_s=-1.0)  # cannot shrink an armed window
        assert tc.sampling_forced()

    def test_wire_round_trip(self):
        ctx = TraceContext(b"\x01" * 8, b"\x02" * 8, "node-zero")
        r = Reader(ctx.encode_wire())
        assert TraceContext.decode_wire(r) == ctx
        assert r.done()

    def test_rehop_keeps_trace_and_origin(self):
        ctx = TraceContext(b"\x01" * 8, b"\x02" * 8, "n0")
        hop = ctx.rehop()
        assert hop.trace_id == ctx.trace_id and hop.origin == ctx.origin
        assert hop.span_id != ctx.span_id

    def test_ambient_use_restores_even_to_none(self):
        ctx = TraceContext(b"\x03" * 8, b"\x04" * 8, "n0")
        assert tc.current() is None
        with tc.use(ctx):
            assert tc.current() is ctx
            with tc.use(None):  # explicit clear must not leak outer ctx
                assert tc.current() is None
            assert tc.current() is ctx
        assert tc.current() is None


class TestWireCodec:
    """Satellite: codec-backward-compatible trace field."""

    def test_old_wire_frames_decode_unchanged_golden_bytes(self):
        from tendermint_tpu.p2p.connection import build_frame, parse_frame

        golden = encode_uvarint(0x30) + encode_bytes(b"hello wire")
        assert parse_frame(golden) == (0x30, b"hello wire", None)
        # sampled-out messages build the EXACT legacy bytes: no
        # context ⇒ no context bytes on the wire
        assert build_frame(0x30, b"hello wire", None) == golden

    def test_traced_frame_round_trips(self):
        from tendermint_tpu.p2p.connection import build_frame, parse_frame

        ctx = TraceContext(b"\xaa" * 8, b"\xbb" * 8, "origin-node")
        frame = build_frame(0x22, b"vote-bytes", ctx)
        chan, payload, got = parse_frame(frame)
        assert (chan, payload) == (0x22, b"vote-bytes")
        assert got == ctx
        # and the traced frame is strictly the legacy frame + the block
        assert frame.startswith(build_frame(0x22, b"vote-bytes", None))

    def test_garbage_trailer_drops_context_not_frame(self):
        from tendermint_tpu.p2p.connection import build_frame, parse_frame

        base = build_frame(0x22, b"payload", None)
        before = REGISTRY.counter_value("tendermint_trace_dropped_total")
        chan, payload, ctx = parse_frame(base + b"\xff\xff")
        assert (chan, payload, ctx) == (0x22, b"payload", None)
        assert REGISTRY.counter_value("tendermint_trace_dropped_total") == before + 1


class _RelayReactor:
    """Test reactor: records received contexts; optionally re-sends the
    payload to all OTHER peers (trace context re-attaches from the
    ambient slot the recv loop installed)."""

    CHAN = 0x51

    def __init__(self, relay: bool) -> None:
        self.relay = relay
        self.got: list = []  # (payload, ambient ctx)
        self.switch = None

    def set_switch(self, switch) -> None:
        self.switch = switch

    def get_channels(self):
        from tendermint_tpu.p2p.connection import ChannelDescriptor

        return [ChannelDescriptor(self.CHAN, priority=1)]

    def add_peer(self, peer) -> None:
        pass

    def remove_peer(self, peer, reason) -> None:
        pass

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    def receive(self, chan_id, peer, payload) -> None:
        self.got.append((payload, tc.current()))
        if self.relay:
            for p in self.switch.peers():
                if p.id != peer.id:
                    p.try_send(self.CHAN, payload)


class TestGossipPropagation:
    """Satellite: context survives gossip across a 4-node in-process
    net — node0 → node1 → node2 → node3 over real switches/pipes, the
    context re-attaching at each hop from the ambient slot alone."""

    def test_context_survives_three_hops(self):
        from tendermint_tpu.p2p.peer import NodeInfo
        from tendermint_tpu.p2p.switch import Switch, connect_switches

        reactors = [_RelayReactor(relay=True) for _ in range(4)]
        reactors[3].relay = False
        switches = []
        for i in range(4):
            sw = Switch(
                NodeInfo(node_id=f"hop{i}", moniker=f"hop{i}", chain_id="t")
            )
            sw.ping_interval = 0
            sw.add_reactor("relay", reactors[i])
            sw.start()
            switches.append(sw)
        # a line topology: 0-1, 1-2, 2-3 — three real hops
        for i in range(3):
            connect_switches(switches[i], switches[i + 1])
        ctx = TraceContext(os.urandom(8), os.urandom(8), "hop0")
        try:
            with tc.use(ctx):
                assert switches[0].peers()[0].send(_RelayReactor.CHAN, b"msg")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not reactors[3].got:
                time.sleep(0.01)
            assert reactors[3].got, "payload never reached the last hop"
            _payload, end_ctx = reactors[3].got[0]
            assert end_ctx is not None and end_ctx.trace == ctx.trace
            assert end_ctx.origin == "hop0"
            # each traversed hop recorded a p2p.hop span with its own
            # node id — the cross-node part of the timeline
            hop_nodes = {
                s["attrs"].get("node")
                for s in TRACER.recent(prefix="p2p.hop")
                if s["attrs"].get("trace") == ctx.trace
            }
            assert {"hop1", "hop2", "hop3"} <= hop_nodes
        finally:
            for sw in switches:
                sw.stop()


class TestTracerConcurrency:
    """Satellite: Tracer.span() attrs mutated mid-span under concurrent
    readers — attrs are snapshot at completion and to_dict copies."""

    def test_span_attrs_mutated_from_another_thread_never_raise(self):
        tr = Tracer(capacity=64)
        stop = threading.Event()
        reader_errors: list = []

        def reader():
            while not stop.is_set():
                try:
                    for d in tr.recent():
                        json.dumps(d)
                except Exception as e:  # pragma: no cover - the regression
                    reader_errors.append(e)
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        try:
            for i in range(200):
                with tr.span("mempool.admission", i=i) as attrs:
                    mut = threading.Thread(
                        target=lambda a=attrs: [
                            a.__setitem__(f"k{j}", j) for j in range(50)
                        ],
                    )
                    mut.start()
                    # span exit races the mutator copying attrs
                mut.join()
        finally:
            stop.set()
            t.join(timeout=5)
        assert not reader_errors

    def test_to_dict_isolates_readers_from_attr_mutation(self):
        tr = Tracer(capacity=4)
        tr.add("mempool.admission", 0.0, 1.0, height=7)
        d = tr.recent()[0]
        d["attrs"]["height"] = 999  # a reader scribbling on its copy
        assert tr.recent()[0]["attrs"]["height"] == 7

    def test_multiple_sinks_each_observe_and_detach_independently(self):
        tr = Tracer(capacity=4)
        a, b = [], []
        tr.add_sink(a.append)
        tr.add_sink(b.append)
        tr.add("mempool.admission", 0.0, 1.0)
        assert len(a) == 1 and len(b) == 1
        tr.remove_sink(a.append)  # bound-method equality must match
        tr.add("mempool.admission", 1.0, 2.0)
        assert len(a) == 1 and len(b) == 2


class _OnesVerifier:
    """Minimal sync inner backend for coalescer tests."""

    def verify_batch(self, triples):
        return np.ones(len(triples), dtype=bool)


class TestVerifySpineTraceSpans:
    def test_coalesced_launch_records_flush_and_launch_spans(self, monkeypatch):
        from tendermint_tpu.services.batcher import CoalescingVerifier

        monkeypatch.setenv(tc.SAMPLE_ENV, "1")
        ctx = tc.mint("spine-test")
        v = CoalescingVerifier(_OnesVerifier(), cache_size=0, window_s=0.001)
        try:
            with tc.use(ctx):
                handle = v.verify_batch_async(
                    [(b"pk", b"msg", b"sig")], consumer="consensus"
                )
            assert bool(handle.result(timeout=30).all())
        finally:
            v.close()
        flushes = [
            s
            for s in TRACER.recent(prefix="batcher.flush")
            if s["attrs"].get("trace") == ctx.trace
        ]
        launches = [
            s
            for s in TRACER.recent(prefix="dispatch.launch")
            if s["attrs"].get("trace") == ctx.trace
        ]
        assert flushes and flushes[0]["attrs"]["requests"] >= 1
        assert launches and launches[0]["attrs"]["queue"] == "coalescer"
        # and the black box saw both the flush and the launch
        assert FLIGHT.recent(kind="coalescer_flush")
        assert FLIGHT.recent(kind="dispatch_launch")

    def test_untraced_launch_records_no_trace_spans(self):
        from tendermint_tpu.services.batcher import CoalescingVerifier

        before = len(
            [s for s in TRACER.recent(prefix="dispatch.launch")]
        )
        v = CoalescingVerifier(_OnesVerifier(), cache_size=0, window_s=0.001)
        try:
            assert bool(
                v.verify_batch_async([(b"p", b"m", b"s")], consumer="rpc")
                .result(timeout=30)
                .all()
            )
        finally:
            v.close()
        assert len(TRACER.recent(prefix="dispatch.launch")) == before


class TestMempoolAdmissionTrace:
    def _mempool(self, **kw):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.mempool.mempool import Mempool

        conns = local_client_creator(KVStoreApp())()
        return Mempool(conns.mempool, node_id="mp-node", **kw)

    def test_minted_on_local_submit_and_consumed_at_commit(self, monkeypatch):
        from tendermint_tpu.types.tx import tx_hash

        monkeypatch.setenv(tc.SAMPLE_ENV, "1")
        mp = self._mempool()
        tx = b"trace-me=1"
        assert mp.check_tx(tx).is_ok
        ctx = mp.trace_for(tx)
        assert ctx is not None and ctx.origin == "mp-node"
        spans = [
            s
            for s in TRACER.recent(prefix="mempool.admission")
            if s["attrs"].get("tx") == tx_hash(tx).hex()[:16]
        ]
        assert spans and spans[-1]["attrs"]["trace"] == ctx.trace
        assert spans[-1]["attrs"]["node"] == "mp-node"
        entry = mp.take_trace(tx)
        assert entry is not None and entry[0] is ctx
        assert mp.take_trace(tx) is None  # consumed exactly once

    def test_gossiped_tx_adopts_ambient_context(self, monkeypatch):
        monkeypatch.setenv(tc.SAMPLE_ENV, "0")  # no local minting
        mp = self._mempool()
        ctx = TraceContext(os.urandom(8), os.urandom(8), "remote-node")
        with tc.use(ctx):
            assert mp.check_tx(b"gossiped=1").is_ok
        got = mp.trace_for(b"gossiped=1")
        assert got is not None and got.trace == ctx.trace

    def test_unsampled_tx_registers_nothing(self, monkeypatch):
        monkeypatch.setenv(tc.SAMPLE_ENV, "0")
        mp = self._mempool()
        assert mp.check_tx(b"plain=1").is_ok
        assert mp.trace_for(b"plain=1") is None


class TestFlightRecorder:
    def test_ring_bounds_and_filters(self):
        fr = FlightRecorder(capacity=4)
        for h in range(10):
            fr.record("round_step", height=h, round=0, step="propose")
        assert len(fr) == 4
        assert [e["height"] for e in fr.recent()] == [6, 7, 8, 9]
        assert fr.recent(kind="round_step", height=8)[0]["height"] == 8
        assert fr.recent(kind="nope") == []

    def test_dump_is_atomic_parseable_and_sequenced(self, tmp_path):
        fr = FlightRecorder(capacity=8)
        fr.set_node_id("fr-node")
        fr.record("commit", height=3, txs=1)
        assert fr.dump("no-dir-wired") is None  # nowhere to write: no-op
        p1 = fr.dump("unit test!", dir=str(tmp_path))
        p2 = fr.dump("unit test!", dir=str(tmp_path))
        assert p1 and p2 and p1 != p2
        data = FlightRecorder.load(p1)
        assert data["node"] == "fr-node"
        assert data["reason"] == "unit test!"
        assert data["events"][0]["kind"] == "commit"
        assert not glob.glob(str(tmp_path / "*.tmp"))

    def test_sigusr2_dumps_the_global_ring(self, tmp_path):
        import signal

        if not hasattr(signal, "SIGUSR2"):
            pytest.skip("no SIGUSR2 on this platform")
        assert install_signal_dump()
        FLIGHT.set_dump_dir(str(tmp_path))
        FLIGHT.record("round_step", height=1, round=0, step="propose")
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 5
        hits = []
        while time.monotonic() < deadline and not hits:
            hits = glob.glob(str(tmp_path / "flightrec-sigusr2-*.json"))
            time.sleep(0.01)
        assert hits
        assert FlightRecorder.load(hits[0])["reason"] == "sigusr2"


class TestNemesisFlightDump:
    """Satellite: a chaos invariant violation dumps the flight recorder
    and attaches the dump path to the assertion error."""

    def test_violation_attaches_parseable_dump(self, tmp_path, monkeypatch):
        from tendermint_tpu.testing.nemesis import InvariantViolation, Nemesis

        net = Nemesis(2, home=str(tmp_path))
        try:
            FLIGHT.record("round_step", height=1, round=0, step="propose")

            def broken_invariant():
                # the deliberately-broken invariant: always violated
                raise net._violation("synthetic fork (test-only scenario)")

            monkeypatch.setattr(net, "check_no_fork", broken_invariant)
            with pytest.raises(InvariantViolation) as ei:
                net.assert_invariants()
        finally:
            net.stop(check=False)
        msg = str(ei.value)
        assert "synthetic fork" in msg
        assert "[flight recorder: " in msg
        path = msg.rsplit("[flight recorder: ", 1)[1].split("]", 1)[0]
        data = FlightRecorder.load(path)
        assert data["reason"] == "invariant-violation"
        assert any(e["kind"] == "round_step" for e in data["events"])
        # the height-ledger forensic dump rides the same message
        assert "[height ledger: " in msg
        hpath = msg.rsplit("[height ledger: ", 1)[1].split("]", 1)[0]
        assert json.load(open(hpath))["reason"] == "invariant-violation"


class TestTraceTimelineTool:
    def test_merge_filter_and_dedupe(self, tmp_path):
        tt = _load_timeline_tool()
        spans = [
            {"name": "mempool.admission", "start": 1.0, "end": 1.1,
             "attrs": {"trace": "t1", "node": "n0", "tx": "ab"}},
            {"name": "p2p.hop", "start": 1.2, "end": 1.2,
             "attrs": {"trace": "t1", "node": "n1", "origin": "n0"}},
            {"name": "tx.e2e", "start": 1.0, "end": 2.0,
             "attrs": {"trace": "t1", "height": 7}},
            {"name": "p2p.hop", "start": 1.3, "end": 1.3,
             "attrs": {"trace": "OTHER", "node": "n2"}},
        ]
        for i, name in enumerate(("a.jsonl", "b.jsonl")):
            with open(tmp_path / name, "w") as f:
                for s in spans:  # identical content in both: dedupe
                    f.write(json.dumps(s) + "\n")
                f.write("torn{")
        dump = tmp_path / "flightrec-test-1.json"
        dump.write_text(
            json.dumps(
                {
                    "node": "n0",
                    "reason": "test",
                    "events": [
                        {"t": 1.5, "kind": "round_step", "height": 7,
                         "round": 0, "step": "commit"},
                        {"t": 9.9, "kind": "round_step", "height": 8},
                    ],
                }
            )
        )
        loaded = tt.load_spans([str(tmp_path / "*.jsonl")])
        assert len(loaded) == 4  # deduped across the two logs
        tl = tt.build_timeline(
            loaded, tt.load_flight([str(dump)]), trace_id="t1", height=7
        )
        assert tl["span_count"] == 3
        assert tl["event_count"] == 1  # only height 7's round_step
        assert {"admission", "hop", "commit", "flight"} <= set(tl["stages"])
        assert [e["t"] for e in tl["entries"]] == sorted(
            e["t"] for e in tl["entries"]
        )
        text = tt.render_text(tl)
        assert "mempool.admission" in text and "round_step" in text
        # CLI end-to-end
        rc = tt.main(
            ["--spans", str(tmp_path / "*.jsonl"), "--flight", str(dump),
             "--trace", "t1", "--height", "7", "--json"]
        )
        assert rc == 0


class TestDumpTelemetryTraceQuery:
    def test_trace_filter_and_flight_window(self):
        from tendermint_tpu.rpc.core import make_routes

        class _Obj:
            pass

        node = _Obj()
        node.consensus = None
        node.hasher = None
        node.switch = _Obj()
        node.switch.send_queue_depths = lambda: {}
        node.config = _Obj()
        node.config.rpc = _Obj()
        node.config.rpc.unsafe = False
        routes = {}
        # make_routes needs more node surface than this fake has; build
        # just the handler we need via the real module-level route table
        try:
            routes = make_routes(node)
        except Exception:
            pytest.skip("fake node too thin for make_routes")
        dump = routes["dump_telemetry"]
        TRACER.add("tx.e2e", 1.0, 2.0, trace="feedface", height=3)
        TRACER.add("tx.e2e", 1.0, 2.0, trace="cafef00d", height=4)
        out = dump(trace_id="feedface")
        assert out["spans"]
        assert all(
            (s.get("attrs") or {}).get("trace") == "feedface"
            for s in out["spans"]
        )
        FLIGHT.record("commit", height=3, txs=0)
        out = dump(flight=4)
        assert out["flight"]


class TestClusterTraceAcceptance:
    """THE acceptance scenario (ISSUE 7): drive a tx through a live
    4-validator in-process net and reconstruct — via
    `tools/trace_timeline.py` over the nodes' span logs — one trace_id
    whose timeline contains admission, gossip hops on ≥2 nodes, a
    coalescer flush, a dispatch launch, and the commit; with
    `tendermint_tx_e2e_seconds` observed and the flight recorder
    replaying the same height's round transitions."""

    @staticmethod
    def _rpc(port, method, **params):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.load(resp)
        if "error" in out:
            raise RuntimeError(out["error"])
        return out["result"]

    @staticmethod
    def _trace_spans(trace: str) -> dict:
        by_name: dict = {}
        for s in TRACER.recent():
            if (s.get("attrs") or {}).get("trace") == trace:
                by_name.setdefault(s["name"], []).append(s)
        return by_name

    def test_tx_trace_stitches_across_the_cluster(self, tmp_path, monkeypatch):
        from tendermint_tpu.consensus.state import ConsensusState
        from tendermint_tpu.testing.nemesis import Nemesis
        from tendermint_tpu.types.tx import tx_hash

        # small validator sets never see ≥8-vote runs — let every run
        # take the batched preverify path (coalescer + dispatch)
        monkeypatch.setattr(ConsensusState, "VOTE_DRAIN_MIN", 1)
        tt = _load_timeline_tool()
        tx_e2e_before = REGISTRY.get("tendermint_tx_e2e_seconds").value["count"]

        trace = None
        commit_height = None
        with Nemesis(
            4, home=str(tmp_path), node_factory=Nemesis.full_node_factory()
        ) as net:
            net.wait_height(2, timeout=120)
            # retry loop: each tx-carrying height is one chance for a
            # vote batch to coalesce under the block's trace; a quiet
            # height just means we submit the next tx. The in-memory
            # ring churns fast under forced sampling, so ACCUMULATE
            # sightings across polls — the span logs on disk keep
            # everything for the offline reconstruction below.
            for attempt in range(4):
                tx = b"trace-k%d=trace-v%d" % (attempt, attempt)
                res = self._rpc(
                    net.nodes[0].rpc_port, "broadcast_tx_sync", tx=tx.hex()
                )
                assert res["code"] == 0
                want_tx = tx_hash(tx).hex()[:16]
                deadline = time.monotonic() + 60
                cand = None
                cand_height = None  # per-attempt: a stale height would
                # pair this attempt's trace with long-evicted flight events
                seen: set = set()
                while time.monotonic() < deadline:
                    if cand is None:
                        adm = [
                            s
                            for s in TRACER.recent(prefix="mempool.admission")
                            if s["attrs"].get("tx") == want_tx
                        ]
                        if adm:
                            cand = adm[0]["attrs"]["trace"]
                    if cand is not None:
                        spans = self._trace_spans(cand)
                        seen |= set(spans)
                        if "tx.e2e" in spans and cand_height is None:
                            cand_height = spans["tx.e2e"][0]["attrs"][
                                "height"
                            ]
                        if {
                            "mempool.admission",
                            "tx.e2e",
                            "batcher.flush",
                            "dispatch.launch",
                        } <= seen and cand_height is not None:
                            trace = cand
                            commit_height = cand_height
                            break
                    time.sleep(0.1)
                if trace is not None:
                    break
            assert trace is not None, (
                "no tx trace accumulated admission+flush+launch+commit "
                f"(last candidate {cand}: {sorted(seen)})"
            )
            dump_path = FLIGHT.dump(reason="acceptance", dir=str(tmp_path))
            assert dump_path is not None

        # nodes stopped: their span logs are flushed — reconstruct the
        # timeline the way an operator would, from files alone
        logs = glob.glob(str(tmp_path / "fullnode*" / "data" / "spans.jsonl"))
        assert len(logs) == 4
        timeline = tt.build_timeline(
            tt.load_spans(logs),
            tt.load_flight([dump_path]),
            trace_id=trace,
            height=commit_height,
        )
        stages = set(timeline["stages"])
        assert {"admission", "hop", "flush", "launch", "commit"} <= stages, stages
        # the gossip hop crossed ≥2 distinct nodes
        hop_nodes = {
            e["node"] for e in timeline["entries"] if e["stage"] == "hop"
        }
        assert len(hop_nodes) >= 2, hop_nodes
        # the flight recorder replays the commit height's transitions
        steps = [
            e
            for e in timeline["entries"]
            if e["kind"] == "event" and e["name"] == "round_step"
        ]
        assert steps, "no round_step events for the commit height"
        assert {"commit"} <= {e["attrs"].get("step") for e in steps} | {"commit"}
        # e2e latency histogram observed (exemplar links back to traces)
        fam = REGISTRY.get("tendermint_tx_e2e_seconds")
        assert fam.value["count"] > tx_e2e_before
        assert "exemplar" in fam.value
        # the text rendering is usable output, not just data
        text = tt.render_text(timeline)
        assert "admission" in text and "flush" in text
