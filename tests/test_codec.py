import pytest

from tendermint_tpu.codec import (
    Reader,
    Writer,
    canonical_dumps,
    decode_svarint,
    decode_uvarint,
    encode_svarint,
    encode_uvarint,
)


@pytest.mark.parametrize("n", [0, 1, 127, 128, 300, 2**32, 2**63 - 1, 2**64])
def test_uvarint_roundtrip(n):
    enc = encode_uvarint(n)
    dec, off = decode_uvarint(enc)
    assert dec == n and off == len(enc)


@pytest.mark.parametrize("n", [0, 1, -1, 63, -64, 2**40, -(2**40), 2**62, -(2**62)])
def test_svarint_roundtrip(n):
    dec, off = decode_svarint(encode_svarint(n))
    assert dec == n


def test_uvarint_negative_raises():
    with pytest.raises(ValueError):
        encode_uvarint(-1)


def test_truncated_uvarint():
    with pytest.raises(ValueError):
        decode_uvarint(b"\x80")


def test_writer_reader_roundtrip():
    w = (
        Writer()
        .uvarint(42)
        .svarint(-7)
        .bytes(b"hello")
        .string("wörld")
        .bool(True)
        .bool(False)
        .raw(b"\xff\x00")
    )
    r = Reader(w.build())
    assert r.uvarint() == 42
    assert r.svarint() == -7
    assert r.bytes() == b"hello"
    assert r.string() == "wörld"
    assert r.bool() is True
    assert r.bool() is False
    assert r.raw(2) == b"\xff\x00"
    r.expect_done()


def test_reader_trailing_bytes_detected():
    r = Reader(b"\x00\x01")
    r.uvarint()
    with pytest.raises(ValueError):
        r.expect_done()


def test_canonical_json_deterministic_and_sorted():
    a = canonical_dumps({"b": 1, "a": b"\xde\xad", "c": {"z": 2, "y": [1, 2]}})
    b = canonical_dumps({"c": {"y": [1, 2], "z": 2}, "a": b"\xde\xad", "b": 1})
    assert a == b
    assert a == b'{"a":"DEAD","b":1,"c":{"y":[1,2],"z":2}}'


def test_canonical_json_rejects_floats():
    with pytest.raises(TypeError):
        canonical_dumps({"x": 1.5})
