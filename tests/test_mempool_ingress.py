"""Traffic-scale mempool ingress: sharded tx lanes + batched admission
windows through the verify coalescer (`mempool/ingress.py`)."""

import threading
import time

import pytest

from tendermint_tpu.abci.apps import CounterApp, KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.abci.types import CodeType
from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.mempool import Mempool, make_signed_tx, parse_signed_tx
from tendermint_tpu.services.batcher import CoalescingVerifier
from tendermint_tpu.services.verifier import HostBatchVerifier
from tendermint_tpu.telemetry import REGISTRY
from tendermint_tpu.types.tx import Txs


def _mempool(app=None, **kw):
    conns = local_client_creator(app or KVStoreApp())()
    return Mempool(conns.mempool, **kw), conns


PRIV = gen_priv_key(b"\x42" * 32)


def _signed(payload: bytes, priv=PRIV) -> bytes:
    return make_signed_tx(priv, payload)


class TestSignedTxEnvelope:
    def test_roundtrip(self):
        tx = _signed(b"k=v")
        parsed = parse_signed_tx(tx)
        assert parsed is not None
        pk, sig, payload = parsed
        assert pk == PRIV.pub_key.data and payload == b"k=v"
        assert PRIV.pub_key.verify(payload, sig)

    def test_plain_and_short_txs_are_not_envelopes(self):
        assert parse_signed_tx(b"k=v") is None
        assert parse_signed_tx(b"\xed\x01short") is None
        # magic alone is not enough: header must be complete
        assert parse_signed_tx(b"\xed\x01" + b"\x00" * 95) is None

    def test_tampered_payload_fails_verify(self):
        tx = bytearray(_signed(b"k=v"))
        tx[-1] ^= 0xFF
        pk, sig, payload = parse_signed_tx(bytes(tx))
        assert not PRIV.pub_key.verify(payload, sig)


class TestLanes:
    def test_reap_merges_lanes_in_counter_order(self):
        mp, _ = _mempool(lanes=4, ingress_batch=False)
        txs = [b"k%d=v%d" % (i, i) for i in range(24)]
        for tx in txs:
            mp.check_tx(tx)
        # txs spread over multiple lanes...
        occupied = [lane for lane in mp._lanes if lane.txs]
        assert len(occupied) > 1
        # ...but reap returns global admission order, counter-monotonic
        reaped = [bytes(t) for t in mp.reap(-1)]
        assert reaped == txs
        counters = [c for c, _ in mp.get_after(0)]
        assert counters == sorted(counters) == list(range(1, 25))
        assert [bytes(t) for t in mp.reap(5)] == txs[:5]

    def test_update_removes_committed_and_rechecks_across_lanes(self):
        app = CounterApp(serial=True)
        mp, conns = _mempool(app, lanes=4, ingress_batch=False)
        txs = [i.to_bytes(1, "big") if i else b"\x00" for i in range(6)]
        for tx in txs:
            mp.check_tx(tx)
        assert mp.size() == 6
        # app advances past nonce 3 -> nonces 2,3 go stale on recheck
        for i in range(4):
            conns.consensus.deliver_tx_async(txs[i])
        mp.lock()
        try:
            mp.update(1, Txs(txs[:2]))  # 0,1 committed
        finally:
            mp.unlock()
        survivors = [bytes(t) for t in mp.reap(-1)]
        assert survivors == txs[4:]  # 2,3 rechecked stale, 4,5 survive

    def test_dup_cache_hits_land_on_the_right_lane(self):
        mp, _ = _mempool(lanes=8, ingress_batch=False)
        txs = [b"dup%d=%d" % (i, i) for i in range(16)]
        for tx in txs:
            assert mp.check_tx(tx).is_ok
        for tx in txs:
            res = mp.check_tx(tx)
            assert res.code == CodeType.TX_IN_CACHE
        assert mp.size() == 16
        # eviction from the owning lane's segment makes the tx re-offerable
        mp.lock()
        try:
            mp.update(1, Txs(txs))
        finally:
            mp.unlock()
        assert mp.size() == 0
        # committed txs stay in their lane's dup cache (a gossip
        # re-arrival of a committed tx is still a duplicate)
        assert mp.check_tx(txs[0]).code == CodeType.TX_IN_CACHE

    def test_wal_replay_restores_every_lane(self, tmp_path):
        txs = [b"wal%d=%d" % (i, i) for i in range(12)]
        mp, _ = _mempool(lanes=4, ingress_batch=False, wal_dir=str(tmp_path))
        for tx in txs:
            mp.check_tx(tx)
        mp.close()
        mp2, _ = _mempool(lanes=4, ingress_batch=False, wal_dir=str(tmp_path))
        assert mp2.replay_wal() == 12
        assert [bytes(t) for t in mp2.reap(-1)] == txs
        # every lane that should hold a tx holds exactly its txs
        for tx in txs:
            lane = mp2._lane_for(tx)
            assert any(m.tx == tx for m in lane.txs)
        mp2.close()

    def test_lock_freezes_all_lanes(self):
        mp, _ = _mempool(lanes=4, ingress_batch=False)
        mp.check_tx(b"a=1")
        mp.lock()
        try:
            blocked = threading.Event()
            done = threading.Event()

            def admit():
                blocked.set()
                mp.check_tx(b"b=2")
                done.set()

            t = threading.Thread(target=admit, daemon=True)
            t.start()
            blocked.wait(2)
            time.sleep(0.1)
            # admission can't complete while consensus holds the pool
            assert not done.is_set()
        finally:
            mp.unlock()
        assert done.wait(5)
        assert mp.size() == 2


class TestWALConcurrentWriters:
    def test_concurrent_appends_keep_framing_parseable(self, tmp_path):
        """Pre-fix, check_tx appended outside any lock: interleaved
        writes from RPC + gossip threads corrupted the length framing
        load_wal replays. The dedicated WAL lock serializes appends."""
        mp, _ = _mempool(lanes=4, ingress_batch=False, wal_dir=str(tmp_path))
        n_threads, per_thread = 8, 40
        # variable-length payloads make torn frames visible
        txs = [
            b"t%d-%d=%s" % (k, i, b"x" * (1 + (k * per_thread + i) % 97))
            for k in range(n_threads)
            for i in range(per_thread)
        ]

        def worker(k):
            for tx in txs[k * per_thread : (k + 1) * per_thread]:
                mp.check_tx(tx)

        threads = [
            threading.Thread(target=worker, args=(k,)) for k in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        records = mp.load_wal()
        assert len(records) == n_threads * per_thread
        assert set(records) == set(txs)
        mp.close()

    def test_concurrent_appends_preserve_admission_order(self, tmp_path):
        """WAL record order must equal counter (admission) order even
        with concurrent writers: the counter is assigned under the same
        _wal_lock hold as the WAL append, so crash replay re-admits txs
        in exactly the order the pool held them (nonce-style serial
        apps depend on this)."""
        mp, _ = _mempool(lanes=4, ingress_batch=False, wal_dir=str(tmp_path))
        n_threads, per_thread = 8, 25

        def worker(k):
            for i in range(per_thread):
                mp.check_tx(b"ord-%d-%d=1" % (k, i))

        threads = [
            threading.Thread(target=worker, args=(k,)) for k in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        counter_of = {tx: c for c, tx in mp.get_after(0)}
        wal_counters = [counter_of[r] for r in mp.load_wal()]
        assert len(wal_counters) == n_threads * per_thread
        assert wal_counters == sorted(wal_counters)
        mp.close()


class TestGetAfterWait:
    def test_spurious_wakeup_does_not_return_empty(self):
        mp, _ = _mempool(lanes=4, ingress_batch=False)
        mp.check_tx(b"a=1")
        cursor = max(c for c, _ in mp.get_after(0))
        got = []

        def waiter():
            got.extend(mp.get_after(cursor, wait=True, timeout=10))

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        # spurious notify: no newer tx exists — the waiter must re-wait
        for _ in range(3):
            with mp._avail:
                mp._avail.notify_all()
            time.sleep(0.02)
        assert t.is_alive(), "waiter returned on a spurious wakeup"
        mp.check_tx(b"b=2")
        t.join(5)
        assert [tx for _, tx in got] == [b"b=2"]

    def test_timeout_expires_empty(self):
        mp, _ = _mempool(lanes=4, ingress_batch=False)
        mp.check_tx(b"a=1")
        cursor = max(c for c, _ in mp.get_after(0))
        t0 = time.monotonic()
        out = mp.get_after(cursor, wait=True, timeout=0.3)
        assert out == []
        assert time.monotonic() - t0 >= 0.25

    def test_spurious_wakeup_respects_deadline(self):
        """A storm of notifies without new txs must neither return
        early nor spin past the deadline."""
        mp, _ = _mempool(lanes=4, ingress_batch=False)
        mp.check_tx(b"a=1")
        cursor = max(c for c, _ in mp.get_after(0))
        stop = threading.Event()

        def noise():
            while not stop.is_set():
                with mp._avail:
                    mp._avail.notify_all()
                time.sleep(0.01)

        t = threading.Thread(target=noise, daemon=True)
        t.start()
        try:
            t0 = time.monotonic()
            out = mp.get_after(cursor, wait=True, timeout=0.3)
            dt = time.monotonic() - t0
        finally:
            stop.set()
            t.join(2)
        assert out == []
        assert 0.25 <= dt < 5.0


class TestGossipCursorConsistency:
    def test_mid_scan_admissions_withheld_never_skipped(self):
        """The gossip reactor advances its cursor to the max returned
        counter, so `get_after` must never return counter N while an
        unreturned counter < N exists. Pre-fix, the lane-by-lane scan
        could do exactly that: a tx admitted into an already-scanned
        lane was masked by a higher-counter tx in a later lane, and the
        cursor skipped it forever (the tx was never gossiped). The
        counter snapshot withholds BOTH mid-scan admissions until the
        next scan."""
        mp, _ = _mempool(lanes=4, ingress_batch=False)
        mp.check_tx(b"seed=1")
        cursor = max(c for c, _ in mp.get_after(0))

        def tx_for_lane(idx, tag):
            for i in range(100_000):
                tx = b"%s-%d=1" % (tag, i)
                if mp._lane_for(tx) is mp._lanes[idx]:
                    return tx
            raise AssertionError("no payload found for lane")

        early_lane_tx = tx_for_lane(0, b"early")  # lane scanned pre-pause
        late_lane_tx = tx_for_lane(3, b"late")  # lane scanned post-pause

        mid_scan = threading.Event()
        resume = threading.Event()
        state = {"armed": True}
        real_lanes = mp._lanes

        class PausingLanes(list):
            """Pauses the FIRST iteration (the scan under test) after
            yielding lane 0; every other iteration is pass-through."""

            def __iter__(self):
                it = list.__iter__(self)
                if not state["armed"]:
                    return it
                state["armed"] = False

                def gen():
                    yield next(it)
                    mid_scan.set()
                    resume.wait(5)
                    yield from it

                return gen()

        mp._lanes = PausingLanes(real_lanes)
        try:
            got = []
            t = threading.Thread(
                target=lambda: got.extend(mp.get_after(cursor)), daemon=True
            )
            t.start()
            assert mid_scan.wait(5)
            # admitted while the scan sits between lanes: "early" lands
            # on the lane already walked, "late" on one still to come
            assert mp.check_tx(early_lane_tx).is_ok
            assert mp.check_tx(late_lane_tx).is_ok
            resume.set()
            t.join(5)
            assert not t.is_alive()
        finally:
            mp._lanes = real_lanes
        # neither counter is returned (both post-snapshot) — returning
        # only the late one would advance the cursor past the early one
        assert got == []
        # the next scan sees both, in counter order, with no gap
        after = mp.get_after(cursor)
        assert [tx for _, tx in after] == [early_lane_tx, late_lane_tx]
        assert [c for c, _ in after] == [cursor + 1, cursor + 2]
        mp.close()


class TestSignedTxsOptOut:
    # >= 98 bytes and starts with the envelope magic, but is NOT a real
    # envelope — an app payload colliding with the reserved prefix
    COLLIDER = b"\xed\x01" + b"x" * 96

    def test_reserved_prefix_rejected_by_default(self):
        mp, _ = _mempool(lanes=2, ingress_batch=False)
        assert mp.check_tx(self.COLLIDER).code == CodeType.UNAUTHORIZED
        mp.close()

    def test_constructor_opt_out_restores_pass_through(self):
        mp, _ = _mempool(lanes=2, ingress_batch=False, signed_txs=False)
        assert mp.check_tx(self.COLLIDER).is_ok
        assert mp.size() == 1
        mp.close()

    def test_env_opt_out_covers_batched_path(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_SIGNED_TXS", "0")
        v = _coalescing()
        mp, _ = _mempool(lanes=2, ingress_batch=True, verifier=v)
        assert mp.check_tx(self.COLLIDER).is_ok
        mp.close()
        v.close()


def _coalescing(cache_size=4096, window_s=0.001):
    return CoalescingVerifier(
        HostBatchVerifier(), cache_size=cache_size, window_s=window_s
    )


class TestIngressBatcher:
    def test_batched_results_match_legacy(self):
        seq = [
            _signed(b"s1=1"),
            b"plain=1",
            _signed(b"s2=2"),
            _signed(b"s1=1"),  # duplicate
        ]
        forged = bytearray(_signed(b"s3=3"))
        forged[40] ^= 0xFF
        seq.append(bytes(forged))

        def run(batch_on):
            v = _coalescing()
            mp, _ = _mempool(lanes=4, ingress_batch=batch_on, verifier=v)
            codes = [mp.check_tx(tx).code for tx in seq]
            size = mp.size()
            mp.close()
            v.close()
            return codes, size

        legacy, batched = run(False), run(True)
        assert legacy == batched
        assert legacy[0] == (
            [CodeType.OK, CodeType.OK, CodeType.OK, CodeType.TX_IN_CACHE,
             CodeType.UNAUTHORIZED]
        )

    def test_forged_sig_rejected_evicted_and_retryable(self):
        v = _coalescing()
        mp, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        good = _signed(b"k=v")
        forged = bytearray(good)
        forged[34] ^= 0x01  # flip one sig bit
        res = mp.check_tx(bytes(forged))
        assert res.code == CodeType.UNAUTHORIZED
        assert mp.size() == 0
        # the forged bytes were evicted from the dup cache: the CORRECT
        # envelope is admissible (a bad sig can't poison the tx)
        assert mp.check_tx(good).is_ok
        assert mp.size() == 1
        mp.close()
        v.close()

    def test_concurrent_callers_share_verify_windows(self):
        v = _coalescing(cache_size=0)
        mp, _ = _mempool(
            lanes=4, ingress_batch=True, verifier=v, ingress_window_s=0.02
        )
        fam = REGISTRY.get("tendermint_mempool_ingress_window_txs")
        n0, s0 = fam.value["count"], fam.value["sum"]
        txs = [_signed(b"w%d=%d" % (i, i)) for i in range(48)]
        threads = [
            threading.Thread(target=mp.check_tx, args=(tx,)) for tx in txs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert mp.size() == 48
        snap = fam.value
        windows = snap["count"] - n0
        assert snap["sum"] - s0 == 48
        assert windows < 48, "no admission coalescing happened"
        mp.close()
        v.close()

    def test_mempool_is_a_coalescer_consumer(self):
        v = _coalescing(cache_size=0)
        mp, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        fam = REGISTRY.get("tendermint_batcher_wait_seconds")
        before = fam.labels(consumer="mempool").value["count"]
        for i in range(4):
            assert mp.check_tx(_signed(b"c%d=%d" % (i, i))).is_ok
        assert fam.labels(consumer="mempool").value["count"] > before
        mp.close()
        v.close()

    def test_gossip_rearrival_is_near_free_via_sig_cache(self):
        """Two nodes' mempools share one verifier stack (the in-process
        nemesis shape): the second admission of the same signed tx hits
        the VerifiedSigCache instead of re-verifying."""
        v = _coalescing()
        mp_a, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        mp_b, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        tx = _signed(b"gossip=1")
        assert mp_a.check_tx(tx).is_ok
        h0 = REGISTRY.counter_value("tendermint_verify_cache_hits_total")
        assert mp_b.check_tx(tx).is_ok
        assert (
            REGISTRY.counter_value("tendermint_verify_cache_hits_total") - h0
            >= 1
        )
        mp_a.close()
        mp_b.close()
        v.close()

    def test_callbacks_fire_in_submission_order(self):
        v = _coalescing(cache_size=0)
        mp, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        order = []
        done = threading.Event()
        n = 20

        def cb_for(i):
            def cb(res):
                order.append(i)
                if len(order) == n:
                    done.set()

            return cb

        for i in range(n):
            mp.check_tx_async(_signed(b"f%d=%d" % (i, i)), cb_for(i))
        assert done.wait(10)
        assert order == list(range(n))
        mp.close()
        v.close()

    def test_window_degrades_to_host_verify_when_verifier_faults(self):
        class ExplodingVerifier(HostBatchVerifier):
            def verify_batch_async(self, triples, queue=None, consumer="default"):
                raise RuntimeError("device gone")

        mp, _ = _mempool(
            lanes=4, ingress_batch=True, verifier=ExplodingVerifier()
        )
        good = _signed(b"h=1")
        forged = bytearray(_signed(b"h2=2"))
        forged[40] ^= 0xFF
        assert mp.check_tx(good).is_ok
        assert mp.check_tx(bytes(forged)).code == CodeType.UNAUTHORIZED
        assert mp.size() == 1
        mp.close()

    def test_window_degrades_when_handle_result_faults(self):
        class FaultyHandle:
            def result(self, timeout=None):
                raise RuntimeError("launch lost")

        class FaultyVerifier(HostBatchVerifier):
            def verify_batch_async(self, triples, queue=None, consumer="default"):
                return FaultyHandle()

        mp, _ = _mempool(lanes=4, ingress_batch=True, verifier=FaultyVerifier())
        assert mp.check_tx(_signed(b"h3=3")).is_ok
        mp.close()

    def test_env_opt_out_keeps_synchronous_semantics(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_INGRESS_BATCH", "0")
        mp, _ = _mempool(lanes=4)
        assert mp._ingress is None
        assert mp.check_tx(_signed(b"sync=1")).is_ok
        # check_tx_async falls back to the synchronous path
        res = mp.check_tx_async(b"plain=2")
        assert res.is_ok and mp.size() == 2
        mp.close()

    def test_env_lane_override(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_MEMPOOL_LANES", "2")
        mp, _ = _mempool(lanes=8, ingress_batch=False)
        assert mp.n_lanes == 2
        mp.close()

    def test_admission_latency_histogram_observed(self):
        fam = REGISTRY.get("tendermint_mempool_admission_seconds")
        before = fam.value["count"]
        v = _coalescing()
        mp, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        mp.check_tx(_signed(b"lat=1"))
        mp.check_tx(b"plain-lat=1")
        assert fam.value["count"] >= before + 2
        mp.close()
        v.close()

    def test_flush_invalidates_inflight_ingress_admissions(self):
        """unsafe_flush_mempool must also cover txs sitting in ingress
        windows: pre-fix a tx queued for admission when the operator
        flushed re-entered the pool right after the flush."""
        v = _coalescing()
        mp, _ = _mempool(
            lanes=4, ingress_batch=True, verifier=v, ingress_window_s=5.0
        )
        adm = mp.check_tx_async(b"inflight=1")
        mp.flush()  # tx still queued (5 s window, no barrier yet)
        res = mp._ingress.wait(adm)  # barrier-flush and join NOW
        assert res.code == CodeType.INTERNAL_ERROR
        assert mp.size() == 0
        # caches were reset by the flush: the same tx is re-offerable
        assert mp.check_tx(b"inflight=1").is_ok
        assert mp.size() == 1
        mp.close()
        v.close()

    def test_close_drains_windows_enqueued_behind_stop(self):
        """A flusher stuck past close()'s join timeout can enqueue its
        window AFTER the _STOP sentinel; the joiner exits without
        resolving it and _Admission.wait() has no timeout — close()'s
        drain must resolve the batch so no blocked caller hangs."""
        from tendermint_tpu.mempool.ingress import IngressBatcher, _Admission

        mp, _ = _mempool(lanes=2, ingress_batch=False)
        b = IngressBatcher(mp)
        adm = _Admission(b"late=1", None, None, time.time(), None)
        b._join_q.put((None, [adm], []))  # a window the joiner never saw
        b.close()
        assert adm.event.is_set()
        assert adm.result.code == CodeType.INTERNAL_ERROR
        mp.close()

    def test_close_resolves_queued_admissions(self):
        """A closing pool must not wedge blocked callers: queued
        admissions resolve with an internal error."""
        v = _coalescing()
        mp, _ = _mempool(
            lanes=4, ingress_batch=True, verifier=v, ingress_window_s=5.0
        )
        adm = mp.check_tx_async(_signed(b"late=1"))
        mp.close()
        v.close()
        res = adm.wait(5) if hasattr(adm, "wait") else adm
        assert res is not None

    def test_no_empty_block_wakeup_fires_from_window_join(self):
        v = _coalescing()
        mp, _ = _mempool(lanes=4, ingress_batch=True, verifier=v)
        fired = []
        mp.set_on_txs_available(lambda: fired.append(1))
        mp.check_tx(_signed(b"wake=1"))
        mp.check_tx(_signed(b"wake2=2"))
        assert len(fired) == 1  # once per height
        mp.lock()
        try:
            mp.update(1, Txs([_signed(b"wake=1")]))
        finally:
            mp.unlock()
        # recheck left wake2 pending -> fires again for the next height
        assert len(fired) == 2
        mp.close()
        v.close()
