"""Evidence subsystem: DuplicateVoteEvidence proofs, the WAL-backed
EvidencePool, evidence in blocks (wire + hash + validation), and the
BeginBlock reporting path — the accountability pipeline of ISSUE 9.
"""

from __future__ import annotations

import os

import pytest

from tendermint_tpu.crypto import PrivKey
from tendermint_tpu.services.verifier import HostBatchVerifier
from tendermint_tpu.types.block import Block, Commit, EvidenceData
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    decode_evidence,
    evidence_hash,
    verify_evidence_batch,
)
from tendermint_tpu.types.params import ConsensusParams, EvidenceParams
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.tx import Txs
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote
from tendermint_tpu.evidence.pool import EvidencePool

from tests.helpers import CHAIN_ID, ChainSim

PRIV = PrivKey(b"\x07" * 32)


def signed_vote(
    priv=PRIV,
    height=3,
    round_=0,
    type_=VOTE_TYPE_PRECOMMIT,
    block_hash=b"\xaa" * 20,
    index=0,
    chain_id=CHAIN_ID,
    timestamp=123,
):
    vote = Vote(
        validator_address=priv.pub_key.address,
        validator_index=index,
        height=height,
        round=round_,
        timestamp=timestamp,
        type=type_,
        block_id=BlockID(block_hash, PartSetHeader.zero()),
    )
    return vote.with_signature(priv.sign(vote.sign_bytes(chain_id)))


def duplicate_vote_evidence(priv=PRIV, height=3, chain_id=CHAIN_ID):
    return DuplicateVoteEvidence.make(
        signed_vote(priv, height=height, block_hash=b"\xaa" * 20, chain_id=chain_id),
        signed_vote(priv, height=height, block_hash=b"\xbb" * 20, chain_id=chain_id),
    )


class _ValSet:
    """Minimal validator-set stand-in for unit verification."""

    def __init__(self, privs):
        import types as _t

        self._vals = {
            p.pub_key.address: _t.SimpleNamespace(
                address=p.pub_key.address, pub_key=p.pub_key, voting_power=10
            )
            for p in privs
        }

    def size(self):
        return len(self._vals)

    def get_by_address(self, address):
        val = self._vals.get(address)
        if val is None:
            return -1, None
        return 0, val


class TestDuplicateVoteEvidence:
    def test_canonical_order_makes_detection_order_irrelevant(self):
        a = signed_vote(block_hash=b"\xaa" * 20)
        b = signed_vote(block_hash=b"\xbb" * 20)
        assert (
            DuplicateVoteEvidence.make(a, b).hash()
            == DuplicateVoteEvidence.make(b, a).hash()
        )

    def test_roundtrip(self):
        ev = duplicate_vote_evidence()
        assert decode_evidence(ev.encode()) == ev

    def test_unknown_tag_rejected(self):
        with pytest.raises(ValidationError):
            decode_evidence(b"\x7f\x00")

    def test_validate_rejects_agreeing_votes(self):
        a = signed_vote(block_hash=b"\xaa" * 20)
        with pytest.raises(ValidationError, match="no conflict"):
            DuplicateVoteEvidence(vote_a=a, vote_b=a).validate_basic()

    def test_validate_rejects_cross_validator_pairs(self):
        other = PrivKey(b"\x08" * 32)
        with pytest.raises(ValidationError, match="different validators"):
            DuplicateVoteEvidence.make(
                signed_vote(PRIV, block_hash=b"\xaa" * 20),
                signed_vote(other, block_hash=b"\xbb" * 20),
            ).validate_basic()

    def test_validate_rejects_cross_step_pairs(self):
        with pytest.raises(ValidationError, match="different steps"):
            DuplicateVoteEvidence.make(
                signed_vote(height=3, block_hash=b"\xaa" * 20),
                signed_vote(height=4, block_hash=b"\xbb" * 20),
            ).validate_basic()

    def test_verify_runs_one_two_lane_batch(self):
        """The proof's two signatures verify as ONE 2-lane batch through
        the BatchVerifier seam (ISSUE 9 tentpole requirement)."""
        calls = []

        class Recorder(HostBatchVerifier):
            def verify_batch(self, triples):
                calls.append(len(triples))
                return super().verify_batch(triples)

        ev = duplicate_vote_evidence()
        ev.verify(CHAIN_ID, _ValSet([PRIV]), verifier=Recorder())
        assert calls == [2]

    def test_verify_rejects_forged_signature(self):
        a = signed_vote(block_hash=b"\xaa" * 20)
        b = signed_vote(block_hash=b"\xbb" * 20)
        forged = Vote(
            validator_address=b.validator_address,
            validator_index=b.validator_index,
            height=b.height,
            round=b.round,
            timestamp=b.timestamp,
            type=b.type,
            block_id=b.block_id,
            signature=bytes(64),
        )
        ev = DuplicateVoteEvidence.make(a, forged)
        with pytest.raises(ValidationError, match="forged"):
            ev.verify(CHAIN_ID, _ValSet([PRIV]), verifier=HostBatchVerifier())

    def test_verify_rejects_unknown_validator(self):
        ev = duplicate_vote_evidence()
        with pytest.raises(ValidationError, match="not in validator set"):
            ev.verify(CHAIN_ID, _ValSet([PrivKey(b"\x09" * 32)]))

    def test_batch_verify_many_proofs_one_launch(self):
        calls = []

        class Recorder(HostBatchVerifier):
            def verify_batch(self, triples):
                calls.append(len(triples))
                return super().verify_batch(triples)

        evs = [duplicate_vote_evidence(height=h) for h in (2, 3, 4)]
        verify_evidence_batch(
            CHAIN_ID, evs, [_ValSet([PRIV])], verifier=Recorder()
        )
        assert calls == [6]  # 3 proofs x 2 lanes, ONE launch


class TestEvidenceParams:
    def test_dict_roundtrip(self):
        p = ConsensusParams()
        p.evidence = EvidenceParams(max_age=7, max_evidence=3)
        again = ConsensusParams.from_dict(p.to_dict())
        assert again.evidence.max_age == 7
        assert again.evidence.max_evidence == 3

    def test_legacy_dict_defaults(self):
        p = ConsensusParams.from_dict({"block_size": {"max_txs": 5}})
        assert p.evidence.max_age == EvidenceParams().max_age

    def test_validate_rejects_nonpositive_age(self):
        p = ConsensusParams()
        p.evidence = EvidenceParams(max_age=0)
        with pytest.raises(ValidationError):
            p.validate()


class TestBlockEvidence:
    def _block(self, evidence=None):
        return Block.make_block(
            height=1,
            chain_id=CHAIN_ID,
            txs=Txs([b"t1"]),
            last_commit=Commit.empty(),
            last_block_id=BlockID.zero(),
            time=1,
            validators_hash=b"\x01" * 20,
            app_hash=b"",
            evidence=evidence,
        )

    def test_evidence_free_block_keeps_legacy_wire_and_hash(self):
        """Backward compatibility: no evidence -> byte-identical wire
        form and header hash, so stored history stays decodable and
        hash-stable across this PR."""
        b = self._block()
        assert b.header.evidence_hash == b""
        decoded = Block.decode(b.encode())
        assert decoded.hash() == b.hash()
        assert len(decoded.evidence) == 0

    def test_evidence_changes_header_hash_and_roundtrips(self):
        ev = duplicate_vote_evidence(height=1)
        b = self._block(evidence=[ev])
        assert b.header.evidence_hash == evidence_hash([ev])
        assert b.hash() != self._block().hash()
        decoded = Block.decode(b.encode())
        assert decoded.hash() == b.hash()
        assert list(decoded.evidence) == [ev]
        decoded.validate_basic()

    def test_tampered_evidence_fails_validate_basic(self):
        ev = duplicate_vote_evidence(height=1)
        b = self._block(evidence=[ev])
        b.evidence = EvidenceData(evidence=[])  # strip after header fill
        with pytest.raises(ValidationError, match="evidence_hash"):
            b.validate_basic()


class TestEvidencePool:
    def test_add_dedup_and_callback(self, tmp_path):
        pool = EvidencePool(verifier=HostBatchVerifier(), chain_id=CHAIN_ID)
        seen = []
        pool.on_evidence_added = seen.append
        ev = duplicate_vote_evidence()
        assert pool.add_evidence(ev, val_set=_ValSet([PRIV]))
        assert not pool.add_evidence(ev, val_set=_ValSet([PRIV]))  # dup
        assert pool.depth() == 1 and seen == [ev]
        assert pool.pending_evidence() == [ev]

    def test_invalid_evidence_raises(self):
        pool = EvidencePool(verifier=HostBatchVerifier(), chain_id=CHAIN_ID)
        a = signed_vote(block_hash=b"\xaa" * 20)
        bad = Vote(
            validator_address=a.validator_address,
            validator_index=0,
            height=a.height,
            round=0,
            timestamp=9,
            type=a.type,
            block_id=BlockID(b"\xbb" * 20, PartSetHeader.zero()),
            signature=bytes(64),
        )
        with pytest.raises(ValidationError):
            pool.add_evidence(
                DuplicateVoteEvidence.make(a, bad), val_set=_ValSet([PRIV])
            )
        assert pool.depth() == 0

    def test_update_retires_committed_and_prunes_expired(self):
        pool = EvidencePool(
            params=EvidenceParams(max_age=5),
            verifier=HostBatchVerifier(),
            chain_id=CHAIN_ID,
        )
        committed = duplicate_vote_evidence(height=9)
        stale = duplicate_vote_evidence(height=2)
        vs = _ValSet([PRIV])
        pool.add_evidence(committed, val_set=vs)
        pool.add_evidence(stale, val_set=vs)
        assert pool.depth() == 2
        pool.update(10, [committed])  # height 2 is now > max_age old
        assert pool.depth() == 0
        assert pool.has(committed)  # remembered, not re-addable
        assert not pool.add_evidence(committed, val_set=vs)

    def test_wal_survives_restart_and_skips_committed(self, tmp_path):
        wal = str(tmp_path / "evidence.wal")
        vs = _ValSet([PRIV])
        pool = EvidencePool(wal_path=wal, verifier=HostBatchVerifier(), chain_id=CHAIN_ID)
        keep = duplicate_vote_evidence(height=4)
        done = duplicate_vote_evidence(height=3)
        pool.add_evidence(keep, val_set=vs)
        pool.add_evidence(done, val_set=vs)
        pool.update(5, [done])
        pool.close()

        reopened = EvidencePool(
            wal_path=wal, verifier=HostBatchVerifier(), chain_id=CHAIN_ID
        )
        assert reopened.pending_evidence() == [keep]
        assert reopened.has(done)  # committed marker replayed
        reopened.close()

    def test_torn_wal_tail_truncated(self, tmp_path):
        wal = str(tmp_path / "evidence.wal")
        pool = EvidencePool(wal_path=wal, verifier=HostBatchVerifier(), chain_id=CHAIN_ID)
        ev = duplicate_vote_evidence(height=4)
        pool.add_evidence(ev, val_set=_ValSet([PRIV]))
        pool.close()
        size = os.path.getsize(wal)
        with open(wal, "ab") as f:
            f.write(b"\x01\xff\xff")  # torn partial record
        reopened = EvidencePool(
            wal_path=wal, verifier=HostBatchVerifier(), chain_id=CHAIN_ID
        )
        assert reopened.pending_evidence() == [ev]
        reopened.close()
        assert os.path.getsize(wal) == size  # tail healed

    def test_expired_at_admission_is_dropped_not_error(self):
        pool = EvidencePool(
            params=EvidenceParams(max_age=3),
            verifier=HostBatchVerifier(),
            chain_id=CHAIN_ID,
            best_height_fn=lambda: 100,
        )
        assert not pool.add_evidence(
            duplicate_vote_evidence(height=2), val_set=_ValSet([PRIV])
        )
        assert pool.depth() == 0


class TestBlockValidationAndBeginBlock:
    """Evidence through the real execution pipeline: validate_block's
    policy gate + batched proof verify, and BeginBlock reporting."""

    def _sim_with_evidence(self):
        sim = ChainSim(n_vals=4)
        sim.advance()
        # the proposing validator double-signs height 1
        offender = sim.privs[0]
        ev = DuplicateVoteEvidence.make(
            signed_vote(offender._signer._priv_key, height=1, block_hash=b"\xaa" * 20),
            signed_vote(offender._signer._priv_key, height=1, block_hash=b"\xbb" * 20),
        )
        return sim, ev

    def test_block_with_valid_evidence_applies_and_reports_to_app(self):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.state.execution import apply_block

        class RecordingApp(KVStoreApp):
            def __init__(self):
                super().__init__()
                self.byzantine = []

            def begin_block(self, block_hash, header, evidence=()):
                self.byzantine.extend(evidence)
                return super().begin_block(block_hash, header)

        sim, ev = self._sim_with_evidence()
        app = RecordingApp()
        conns = local_client_creator(app)()
        block, parts = sim.make_next_block(evidence=[ev])
        apply_block(
            sim.state,
            block,
            parts.header,
            conns.consensus,
            verifier=HostBatchVerifier(),
        )
        assert app.byzantine == [ev]

    def test_legacy_two_arg_app_still_works(self):
        """Apps overriding the pre-evidence begin_block(hash, header)
        signature keep working — the client only passes evidence to apps
        that accept it."""
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.state.execution import apply_block

        class LegacyApp(KVStoreApp):
            def __init__(self):
                super().__init__()
                self.began = 0

            def begin_block(self, block_hash, header):
                self.began += 1

        sim, ev = self._sim_with_evidence()
        app = LegacyApp()
        conns = local_client_creator(app)()
        block, parts = sim.make_next_block(evidence=[ev])
        apply_block(
            sim.state,
            block,
            parts.header,
            conns.consensus,
            verifier=HostBatchVerifier(),
        )
        assert app.began == 1

    def test_validate_block_rejects_forged_evidence(self):
        from tendermint_tpu.state.execution import validate_block

        sim, ev = self._sim_with_evidence()
        forged = DuplicateVoteEvidence(
            vote_a=ev.vote_a,
            vote_b=ev.vote_b.with_signature(bytes(64)),
        )
        block, _ = sim.make_next_block(evidence=[forged])
        with pytest.raises(ValidationError):
            validate_block(sim.state, block, verifier=HostBatchVerifier())

    def test_validate_block_rejects_expired_evidence(self):
        from tendermint_tpu.state.execution import validate_block

        sim, ev = self._sim_with_evidence()
        sim.state.consensus_params.evidence = EvidenceParams(max_age=0)
        sim.advance()  # evidence height 1, block height 3: age > 0
        block, _ = sim.make_next_block(evidence=[ev])
        with pytest.raises(ValidationError, match="expired evidence"):
            validate_block(sim.state, block, verifier=HostBatchVerifier())

    def test_validate_block_rejects_over_cap_evidence(self):
        from tendermint_tpu.state.execution import validate_block

        sim, ev = self._sim_with_evidence()
        sim.state.consensus_params.evidence = EvidenceParams(max_evidence=0)
        block, _ = sim.make_next_block(evidence=[ev])
        with pytest.raises(ValidationError, match="max 0"):
            validate_block(sim.state, block, verifier=HostBatchVerifier())
