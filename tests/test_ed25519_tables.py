"""Table-driven ed25519 fast path: correctness vs host + generic kernel.

Covers the VERDICT/ADVICE round-2 gaps: the tables path must be wired,
cross-checked against `verify_kernel`, handle non-power-of-two validator
counts (fe_batch_invert pads internally), and localize planted bad
signatures. The TPU matmul-precision regression (one-hot selection at
default precision truncates table limbs to bf16) is guarded by running
the same kernel on whatever backend is active — the driver's bench run
exercises it on the real chip.
"""

import numpy as np
import pytest

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.ops import ed25519_kernel as ed
from tendermint_tpu.ops import ed25519_tables as tb

# Device-kernel compiles dominate runtime (~minutes per bucket shape);
# excluded from the default selection (pytest.ini addopts) — run with
#   pytest -m kernel
# kernel suites are also 'slow': tier-1 CI selects -m 'not slow' (which
# overrides the ini's 'not kernel' default), and these compile device
# kernels on XLA:CPU for minutes. 'pytest -m kernel' still runs them.
pytestmark = [pytest.mark.kernel, pytest.mark.slow]


def _keyed_batch(n, seed=1):
    privs = [gen_priv_key(bytes([seed + i]) * 32) for i in range(n)]
    pubs = [p.pub_key.data for p in privs]
    msgs = [b"vote-%d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    return privs, pubs, msgs, sigs


class TestBatchInvert:
    @pytest.mark.parametrize("m", [1, 2, 3, 5, 8, 13])
    def test_any_row_count(self, m):
        import jax.numpy as jnp

        vals = [pow(7, i + 1, ed.P) for i in range(m)]
        z = jnp.asarray(np.stack([ed._int_to_limbs(v) for v in vals]))
        inv = np.asarray(ed.fe_canon(tb.fe_batch_invert(z)))
        assert inv.shape[0] == m
        for i, v in enumerate(vals):
            assert ed._limbs_to_int(inv[i]) == pow(v, ed.P - 2, ed.P)


class TestBTable:
    def test_b_table_windows_match_host_scalar_mul(self):
        t = tb.b_table()
        # entry [w*256 + j] must be j * 2^(8w) * B in precomp form
        for w, j in [(0, 1), (0, 255), (3, 7), (31, 2)]:
            expect = tb.host_affine(
                tb.host_scalar_mul(j * (1 << (8 * w)), tb._B_EXT)
            )
            np.testing.assert_array_equal(
                t[w * 256 + j], tb._precomp_limbs(*expect)
            )


class TestVerifyTablesKernel:
    def test_valid_batch_odd_n(self):
        # N=3 is deliberately not a power of two (the round-2 advisor
        # reproduced a crash here) and not a multiple of any tile size.
        _, pubs, msgs, sigs = _keyed_batch(3)
        pub = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(3, 32)
        tables, ok = tb.build_key_tables(pub)
        assert ok.all()
        s, h, r, pre = tb.prepare_commit_lanes(pubs, [(msgs, sigs)])
        assert pre.all()
        out = np.asarray(tb.verify_tables_kernel(tables, s, h, r))
        assert out.all()

    def test_bad_signature_localizes(self):
        _, pubs, msgs, sigs = _keyed_batch(5, seed=9)
        sigs = list(sigs)
        corrupt = bytearray(sigs[2])
        corrupt[3] ^= 0x40
        sigs[2] = bytes(corrupt)
        pub = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(5, 32)
        tables, _ = tb.build_key_tables(pub)
        s, h, r, pre = tb.prepare_commit_lanes(pubs, [(msgs, sigs)])
        out = np.asarray(tb.verify_tables_kernel(tables, s, h, r)) & pre
        assert list(out) == [True, True, False, True, True]

    def test_cross_check_vs_generic_kernel(self):
        # same verdicts as the round-1 ladder kernel on a mixed batch
        _, pubs, msgs, sigs = _keyed_batch(4, seed=20)
        sigs = list(sigs)
        bad = bytearray(sigs[1])
        bad[40] ^= 1  # corrupt S
        sigs[1] = bytes(bad)
        msgs2 = list(msgs)
        msgs2[3] = b"tampered"  # msg/sig mismatch

        generic = ed.batch_verify(pubs, msgs2, sigs)

        pub = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(4, 32)
        tables, _ = tb.build_key_tables(pub)
        s, h, r, pre = tb.prepare_commit_lanes(pubs, [(msgs2, sigs)])
        fast = np.asarray(tb.verify_tables_kernel(tables, s, h, r)) & pre
        assert list(fast) == list(generic) == [True, False, True, False]

    def test_stacked_commits_and_absent_lanes(self):
        privs, pubs, msgs, sigs = _keyed_batch(3, seed=30)
        msgs2 = [b"commit-2-%d" % i for i in range(3)]
        sigs2 = [p.sign(m) for p, m in zip(privs, msgs2)]
        # absent vote in commit 2, lane 1
        msgs2[1] = None
        sigs2[1] = None
        pub = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(3, 32)
        tables, _ = tb.build_key_tables(pub)
        s, h, r, pre = tb.prepare_commit_lanes(
            pubs, [(msgs, sigs), (msgs2, sigs2)]
        )
        out = (np.asarray(tb.verify_tables_kernel(tables, s, h, r)) & pre).reshape(2, 3)
        assert out[0].all()
        assert list(out[1]) == [True, False, True]

    def test_fused_kernel_matches_xla_path(self):
        """The fused select+accumulate pallas kernel (interpret mode off
        TPU) must agree with the portable XLA path, localize a planted
        bad signature, and round-trip the lane permutation."""
        n, k = 128, 8
        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        pubs = [p.pub_key.data for p in privs]
        tables, ok = tb.host_build_key_tables(pubs)
        assert ok.all()
        commits = []
        for c in range(k):
            msgs = [b"c%d-%d" % (c, i) for i in range(n)]
            sigs = [p.sign(m) for p, m in zip(privs, msgs)]
            commits.append((msgs, sigs))
        _, s1 = commits[3]
        s1[17] = s1[17][:5] + bytes([s1[17][5] ^ 1]) + s1[17][6:]
        s, h, r, pre = tb.prepare_commit_lanes(pubs, commits)
        assert tb._fused_tile_geometry(k * n, n) == (128, 8)
        fused = np.asarray(tb.verify_tables_kernel(tables, s, h, r, impl="fused"))
        xla = np.asarray(tb.verify_tables_kernel(tables, s, h, r, impl="xla"))
        expect = np.ones(k * n, dtype=bool)
        expect[3 * n + 17] = False
        assert fused.tolist() == expect.tolist()
        assert xla.tolist() == expect.tolist()

    def test_host_build_matches_device_build(self):
        _, pubs, _, _ = _keyed_batch(3, seed=77)
        pub = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(3, 32)
        dev_t, dev_ok = tb.build_key_tables(pub)
        host_t, host_ok = tb.host_build_key_tables(pubs)
        assert dev_ok.tolist() == host_ok.tolist()
        np.testing.assert_array_equal(np.asarray(dev_t), host_t)

    def test_invalid_pubkey_rejected_at_build(self):
        _, pubs, msgs, sigs = _keyed_batch(2, seed=40)
        bad_pub = b"\xff" * 32  # not a curve point
        pub = np.frombuffer(
            b"".join([pubs[0], bad_pub]), dtype=np.uint8
        ).reshape(2, 32)
        _, ok = tb.build_key_tables(pub)
        assert list(ok) == [True, False]


class TestTableBatchVerifier:
    def test_verify_commits_caches_tables(self):
        from tendermint_tpu.services.verifier import TableBatchVerifier

        privs, pubs, msgs, sigs = _keyed_batch(3, seed=50)
        v = TableBatchVerifier(min_device_batch=1)
        out1 = v.verify_commits(pubs, [(msgs, sigs)])
        assert out1.shape == (1, 3) and out1.all()
        assert len(v._tables) == 1
        # second commit, same valset: no new table entry
        msgs2 = [b"h2-%d" % i for i in range(3)]
        sigs2 = [p.sign(m) for p, m in zip(privs, msgs2)]
        out2 = v.verify_commits(pubs, [(msgs2, sigs2)])
        assert out2.all()
        assert len(v._tables) == 1

    def test_generic_triples_fall_back(self):
        from tendermint_tpu.services.verifier import TableBatchVerifier

        _, pubs, msgs, sigs = _keyed_batch(2, seed=60)
        v = TableBatchVerifier(min_device_batch=1)
        out = v.verify_batch(list(zip(pubs, msgs, sigs)))
        assert out.all()
        assert len(v._tables) == 0  # ad-hoc triples skip the table cache

    def test_validator_set_verify_commit_routes_through_tables(self):
        from tendermint_tpu.services.verifier import TableBatchVerifier
        from tendermint_tpu.types.errors import ValidationError

        from tests.helpers import make_block_id, make_commit, make_validators

        vs, privs = make_validators(4)
        block_id = make_block_id()
        commit = make_commit(vs, privs, height=3, round_=0, block_id=block_id)
        v = TableBatchVerifier(min_device_batch=1)
        vs.verify_commit("test-chain", block_id, 3, commit, verifier=v)
        assert len(v._tables) == 1  # commit path used the table cache

        # plant a corrupted signature: error must name the validator index
        bad = commit.precommits[2]
        sig = bytearray(bad.signature)
        sig[7] ^= 1
        commit.precommits[2] = bad.with_signature(bytes(sig))
        with pytest.raises(ValidationError, match="validator 2"):
            vs.verify_commit("test-chain", block_id, 3, commit, verifier=v)
