"""Wire/frame fuzz corpus (ISSUE 9 satellite): golden frames mutated by
bit flips, length-field lies, truncation, splicing, and trace-block
garbage, driven through `build_frame`/`parse_frame` and the per-reactor
codecs — no exception may escape the defined error types — plus the
live acceptance scenario: >= 10k mutated frames against a running peer
pair kill no reader thread and no node; only the fuzzing peer drops.
"""

from __future__ import annotations

import random
import time

import pytest

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.connection import build_frame, parse_frame
from tendermint_tpu.testing.byzantine import FrameFuzzer, mutate_frame
from tendermint_tpu.types.errors import TMError

# the complete set of error types wire-facing decoders may raise; a
# KeyError/IndexError/struct.error/MemoryError escaping a decoder IS
# the bug this corpus hunts
DEFINED_ERRORS = (ValueError, TMError)


def golden_frames() -> list[bytes]:
    """Real frames from every wire codec in the system."""
    from tendermint_tpu.consensus.reactor import (
        DATA_CHANNEL,
        STATE_CHANNEL,
        VOTE_CHANNEL,
        HasVoteMessage,
        NewRoundStepMessage,
        VoteMessage,
    )
    from tendermint_tpu.blockchain.reactor import BLOCKCHAIN_CHANNEL, _enc
    from tendermint_tpu.evidence.reactor import (
        EVIDENCE_CHANNEL,
        encode_evidence_list,
    )
    from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL, encode_tx_message
    from tendermint_tpu.telemetry.tracectx import TraceContext
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote

    vote = Vote(
        validator_address=b"\x01" * 20,
        validator_index=0,
        height=3,
        round=0,
        timestamp=1,
        type=VOTE_TYPE_PREVOTE,
        block_id=BlockID(b"\x02" * 20, PartSetHeader.zero()),
        signature=b"\x03" * 64,
    )
    frames = [
        build_frame(STATE_CHANNEL, NewRoundStepMessage(3, 0, 1, -1).encode()),
        build_frame(STATE_CHANNEL, HasVoteMessage(3, 0, 1, 2).encode()),
        build_frame(VOTE_CHANNEL, VoteMessage(vote).encode()),
        build_frame(DATA_CHANNEL, b"\x05" + b"\x00" * 16),
        build_frame(BLOCKCHAIN_CHANNEL, _enc(0x01, 7)),
        build_frame(MEMPOOL_CHANNEL, encode_tx_message(b"tx-payload")),
        build_frame(EVIDENCE_CHANNEL, encode_evidence_list([])),
        # traced frame: context block trailing the payload
        build_frame(
            VOTE_CHANNEL,
            VoteMessage(vote).encode(),
            ctx=TraceContext(
                trace_id=b"\x00\xff" * 8, span_id=b"\x01" * 8, origin="fuzz"
            ),
        ),
    ]
    return frames


def reactor_decoders():
    from tendermint_tpu.blockchain import reactor as bc
    from tendermint_tpu.consensus import reactor as cons
    from tendermint_tpu.evidence import reactor as evr
    from tendermint_tpu.mempool import reactor as mp

    return {
        cons.STATE_CHANNEL: cons.decode_message,
        cons.DATA_CHANNEL: cons.decode_message,
        cons.VOTE_CHANNEL: cons.decode_message,
        cons.VOTE_SET_BITS_CHANNEL: cons.decode_message,
        bc.BLOCKCHAIN_CHANNEL: lambda p: bc.decode_message(p),
        mp.MEMPOOL_CHANNEL: mp.decode_tx_message,
        evr.EVIDENCE_CHANNEL: evr.decode_evidence_list,
    }


class TestFrameFuzzCorpus:
    def test_mutated_frames_raise_only_defined_errors(self):
        """5000 deterministic mutations through parse_frame + the owning
        reactor's codec: every failure must be a defined error type."""
        rng = random.Random(0xF00D)
        golden = golden_frames()
        decoders = reactor_decoders()
        parsed_ok = 0
        decode_failures = 0
        for i in range(5000):
            frame = mutate_frame(rng.choice(golden), rng)
            try:
                chan_id, payload, _ctx = parse_frame(frame)
            except DEFINED_ERRORS:
                continue
            parsed_ok += 1
            decoder = decoders.get(chan_id)
            if decoder is None:
                continue  # unknown channel: the recv loop drops it
            try:
                decoder(payload)
            except DEFINED_ERRORS:
                decode_failures += 1
        # the corpus must actually exercise both outcomes
        assert parsed_ok > 1000
        assert decode_failures > 100

    def test_trace_block_garbage_never_kills_the_frame(self):
        """A frame with a corrupt trailing trace block still delivers
        its payload (tracing is forensic, never load-bearing)."""
        rng = random.Random(7)
        base = build_frame(0x22, b"payload-bytes")
        for _ in range(200):
            garbage = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 24)))
            chan_id, payload, ctx = parse_frame(base + garbage)
            assert chan_id == 0x22
            assert payload == b"payload-bytes"

    def test_codec_roundtrip_survives_mutation(self):
        """Writer->mutate->Reader: decoding arbitrary corruption of a
        valid document raises only defined errors."""
        rng = random.Random(99)
        doc = (
            Writer()
            .uvarint(7)
            .string("hello")
            .bytes(b"\x00" * 33)
            .svarint(-12345)
            .bool(True)
            .build()
        )
        for _ in range(2000):
            data = mutate_frame(doc, rng)
            r = Reader(data)
            try:
                r.uvarint()
                r.string()
                r.bytes()
                r.svarint()
                r.bool()
                r.expect_done()
            except DEFINED_ERRORS:
                pass


class TestLiveFrameFuzz:
    """Acceptance scenario: >= 10k mutated frames against a live peer
    pair — zero reader-thread deaths, zero node crashes, only the
    fuzzing peer disconnected."""

    def test_ten_thousand_frames_against_live_pair(self):
        import threading

        from tendermint_tpu.p2p.connection import ChannelDescriptor
        from tendermint_tpu.p2p.peer import NodeInfo
        from tendermint_tpu.p2p.switch import Reactor, Switch, connect_switches

        chain = "fuzz-chain"

        class Chatter(Reactor):
            """Keeps real traffic flowing between the honest pair so
            reader-thread health is observable DURING the fuzz."""

            def __init__(self):
                super().__init__()
                self.received = 0

            def get_channels(self):
                return [ChannelDescriptor(0x22), ChannelDescriptor(0x20)]

            def receive(self, chan_id, peer, payload):
                self.received += 1

        victim_reactor, honest_reactor = Chatter(), Chatter()
        victim = Switch(NodeInfo(node_id="victim", moniker="v", chain_id=chain))
        victim.add_reactor("chat", victim_reactor)
        honest = Switch(NodeInfo(node_id="honest", moniker="h", chain_id=chain))
        honest.add_reactor("chat", honest_reactor)
        victim.start()
        honest.start()
        threads_before = threading.active_count()
        try:
            connect_switches(victim, honest)
            fuzzer = FrameFuzzer(victim, chain, seed=0xBEEF)
            sent = fuzzer.run(10_000)
            assert sent >= 10_000
            fuzzer.stop()
            # only fuzzing identities were dropped: the honest link lives
            assert any(p.id == "honest" for p in victim.peers())
            # reader threads on the honest link still deliver frames
            base = victim_reactor.received
            honest.peers()[0].try_send(0x22, b"ping")
            deadline = time.time() + 10
            while time.time() < deadline and victim_reactor.received == base:
                time.sleep(0.01)
            assert victim_reactor.received > base, "victim reader thread died"
            # dead fuzz connections released their threads (no leak of
            # live readers: each dropped conn's threads exit)
            time.sleep(0.2)
            assert threading.active_count() < threads_before + 40
        finally:
            victim.stop()
            honest.stop()
