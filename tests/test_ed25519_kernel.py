"""Device ed25519 batch verifier vs host reference + RFC 8032 vectors.

Covers SURVEY.md §7 hard part #1 validation strategy: CPU reference
cross-check, RFC 8032 vectors, and planted-bad-signature localization.
"""

import hashlib
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.ops import ed25519_kernel as ed

# Device-kernel compiles dominate runtime (~minutes per bucket shape);
# excluded from the default selection (pytest.ini addopts) — run with
#   pytest -m kernel
# kernel suites are also 'slow': tier-1 CI selects -m 'not slow' (which
# overrides the ini's 'not kernel' default), and these compile device
# kernels on XLA:CPU for minutes. 'pytest -m kernel' still runs them.
pytestmark = [pytest.mark.kernel, pytest.mark.slow]


def _fe(x: int):
    return jnp.asarray(ed._int_to_limbs(x))[None, :]


def _to_int(limbs) -> int:
    return ed._limbs_to_int(np.asarray(limbs)[0])


@jax.jit
def _field_ops(a, b):
    return (
        ed.fe_canon(ed.fe_mul(a, b)),
        ed.fe_canon(ed.fe_sub(a, b)),
        ed.fe_canon(ed.fe_invert(a)),
        ed.fe_to_bytes(a),
        ed.fe_canon(ed.bytes_to_fe(ed.fe_to_bytes(b).astype(jnp.uint8))),
    )


class TestFieldArithmetic:
    def test_random_and_edge_values(self):
        rng = random.Random(7)
        cases = [
            (0, 1),
            (1, 1),
            (ed.P - 1, ed.P - 1),
            (ed.P - 19, 2**255 % ed.P),
            (2**254, 2**253 + 5),
        ] + [(rng.randrange(ed.P), rng.randrange(ed.P)) for _ in range(27)]
        A = jnp.asarray(np.stack([ed._int_to_limbs(a) for a, _ in cases]))
        B = jnp.asarray(np.stack([ed._int_to_limbs(b) for _, b in cases]))
        mul, sub, inv, tb, rt = (np.asarray(x) for x in _field_ops(A, B))
        for i, (a, b) in enumerate(cases):
            assert ed._limbs_to_int(mul[i]) == a * b % ed.P
            assert ed._limbs_to_int(sub[i]) == (a - b) % ed.P
            if a != 0:
                assert ed._limbs_to_int(inv[i]) == pow(a, ed.P - 2, ed.P)
            assert int.from_bytes(bytes(tb[i].tolist()), "little") == a
            assert ed._limbs_to_int(rt[i]) == b  # bytes round-trip

    def test_loose_limbs_stay_in_mul_bounds(self):
        # After fe_carry, limbs must be small enough that fe_mul's 20-term
        # column sums cannot overflow int32 (|limb| < ~2^13.7).
        rng = random.Random(3)
        vals = np.asarray(
            [[rng.randrange(-(2**29), 2**29) for _ in range(ed.NLIMBS)] for _ in range(64)],
            dtype=np.int32,
        )
        out = np.asarray(jax.jit(ed.fe_carry)(jnp.asarray(vals)))
        assert np.abs(out).max() < 2**14


# -- python affine-Edwards reference ------------------------------------------


def _ref_add(p, q):
    x1, y1 = p
    x2, y2 = q
    k = ed.D * x1 * x2 * y1 * y2 % ed.P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + k, ed.P - 2, ed.P) % ed.P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - k, ed.P - 2, ed.P) % ed.P
    return x3, y3


def _ref_mul(k, p):
    acc = (0, 1)
    while k:
        if k & 1:
            acc = _ref_add(acc, p)
        p = _ref_add(p, p)
        k >>= 1
    return acc


def _pt_dev(p):
    x, y = p
    return tuple(_fe(v) for v in (x, y, 1, x * y % ed.P))


def _bits(k):
    return jnp.asarray(
        [[(k >> i) & 1 for i in range(ed.SCALAR_BITS)]], dtype=jnp.int32
    )


@jax.jit
def _affine(pt):
    x, y, z, _ = pt
    zi = ed.fe_invert(z)
    return ed.fe_canon(ed.fe_mul(x, zi)), ed.fe_canon(ed.fe_mul(y, zi))


class TestPointOps:
    def test_add_double_vs_reference(self):
        rng = random.Random(11)
        B = (ed.BX, ed.BY)
        p = _ref_mul(rng.randrange(ed.L), B)
        q = _ref_mul(rng.randrange(ed.L), B)

        @jax.jit
        def run(pd, qd):
            return _affine(ed.pt_add(pd, qd)) + _affine(ed.pt_double(pd))

        ax, ay, dx, dy = run(_pt_dev(p), _pt_dev(q))
        assert (_to_int(ax), _to_int(ay)) == _ref_add(p, q)
        assert (_to_int(dx), _to_int(dy)) == _ref_add(p, p)

    def test_double_scalar_mul(self):
        rng = random.Random(13)
        B = (ed.BX, ed.BY)
        a = rng.randrange(ed.L)
        s, h = rng.randrange(ed.L), rng.randrange(ed.L)
        A = _ref_mul(a, B)
        expect = _ref_mul((s - h * a) % ed.L, B)

        @jax.jit
        def run(sb, hb, bp, ap):
            return _affine(ed.double_scalar_mul(sb, bp, hb, ed.pt_neg(ap)))

        gx, gy = run(_bits(s), _bits(h), _pt_dev(B), _pt_dev(A))
        assert (_to_int(gx), _to_int(gy)) == expect


class TestDecompress:
    def test_valid_and_invalid_encodings(self):
        rng = random.Random(17)
        B = (ed.BX, ed.BY)
        goods = [_ref_mul(rng.randrange(ed.L), B) for _ in range(4)]

        def encode(p):
            x, y = p
            enc = bytearray(y.to_bytes(32, "little"))
            enc[31] |= (x & 1) << 7
            return bytes(enc)

        encs = [encode(p) for p in goods]
        encs.append((ed.P + 3).to_bytes(32, "little"))  # non-canonical y
        encs.append((2).to_bytes(32, "little"))  # y=2 is not on the curve
        arr = jnp.asarray(np.frombuffer(b"".join(encs), dtype=np.uint8).reshape(-1, 32))
        (x, y, z, t), ok = jax.jit(ed.pt_decompress)(arr)
        ok = np.asarray(ok)
        assert ok.tolist() == [True] * 4 + [False, False]
        xs, ys = (np.asarray(v) for v in _affine((x, y, z, t)))
        for i, p in enumerate(goods):
            assert ed._limbs_to_int(xs[i]) == p[0]
            assert ed._limbs_to_int(ys[i]) == p[1]


class TestBatchVerify:
    def test_against_host_with_planted_failures(self):
        privs = [gen_priv_key(bytes([i]) * 32) for i in range(12)]
        msgs = [bytes([i]) * (5 + 3 * i) for i in range(12)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        pubs = [p.pub_key.data for p in privs]
        # plant failures: bad sig byte, bad msg, swapped pubkey, bad length
        sigs[2] = sigs[2][:5] + bytes([sigs[2][5] ^ 0xFF]) + sigs[2][6:]
        msgs[5] = msgs[5] + b"x"
        pubs[8], pubs[9] = pubs[9], pubs[8]
        sigs[11] = sigs[11][:40]
        verdict = ed.batch_verify(pubs, msgs, sigs)
        expect = [i not in (2, 5, 8, 9, 11) for i in range(12)]
        assert verdict.tolist() == expect

    def test_noncanonical_s_rejected(self):
        priv = gen_priv_key(b"\x01" * 32)
        msg = b"hello"
        sig = priv.sign(msg)
        s = int.from_bytes(sig[32:], "little")
        bad = sig[:32] + (s + ed.L).to_bytes(32, "little")
        verdict = ed.batch_verify(
            [priv.pub_key.data] * 2, [msg] * 2, [sig, bad]
        )
        assert verdict.tolist() == [True, False]

    def test_rfc8032_vectors(self):
        # RFC 8032 §7.1 TEST 1-3
        vectors = [
            (
                "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
                "",
                "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
                "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
            ),
            (
                "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
                "72",
                "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
                "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
            ),
            (
                "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
                "af82",
                "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
                "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
            ),
        ]
        pubs = [bytes.fromhex(v[0]) for v in vectors]
        msgs = [bytes.fromhex(v[1]) for v in vectors]
        sigs = [bytes.fromhex(v[2]) for v in vectors]
        assert ed.batch_verify(pubs, msgs, sigs).tolist() == [True, True, True]

    def test_empty_batch(self):
        assert ed.batch_verify([], [], []).shape == (0,)
