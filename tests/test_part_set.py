import os

import pytest

from tendermint_tpu.types import PartSet, ValidationError
from tendermint_tpu.types.part_set import Part


def test_roundtrip():
    data = os.urandom(4096 * 3 + 100)
    ps = PartSet.from_data(data, part_size=4096)
    assert ps.total == 4
    assert ps.is_complete()
    assert ps.assemble() == data


def test_gossip_reassembly():
    data = os.urandom(10000)
    src = PartSet.from_data(data, part_size=1024)
    dst = PartSet.from_header(src.header)
    assert not dst.is_complete()
    # deliver out of order
    order = list(range(src.total))[::-1]
    for i in order:
        assert dst.add_part(src.get_part(i))
    assert dst.is_complete()
    assert dst.assemble() == data


def test_duplicate_part_ignored():
    src = PartSet.from_data(b"x" * 5000, part_size=1024)
    dst = PartSet.from_header(src.header)
    assert dst.add_part(src.get_part(0))
    assert not dst.add_part(src.get_part(0))


def test_bad_proof_rejected():
    src = PartSet.from_data(b"y" * 5000, part_size=1024)
    dst = PartSet.from_header(src.header)
    p = src.get_part(1)
    tampered = Part(index=1, bytes_=p.bytes_ + b"!", proof=p.proof)
    with pytest.raises(ValidationError):
        dst.add_part(tampered)


def test_wrong_index_rejected():
    src = PartSet.from_data(b"z" * 5000, part_size=1024)
    dst = PartSet.from_header(src.header)
    p = src.get_part(1)
    moved = Part(index=2, bytes_=p.bytes_, proof=p.proof)
    with pytest.raises(ValidationError):
        dst.add_part(moved)


def test_part_encode_roundtrip():
    src = PartSet.from_data(b"w" * 3000, part_size=1024)
    p = src.get_part(2)
    assert Part.decode(p.encode()).bytes_ == p.bytes_


def test_empty_data_single_part():
    ps = PartSet.from_data(b"", part_size=1024)
    assert ps.total == 1
    assert ps.assemble() == b""
