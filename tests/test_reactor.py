"""Multi-node consensus over the in-memory p2p network.

The round-3 milestone the VERDICT demanded: N full ConsensusStates with
distinct priv validators replicating through the consensus reactor's
gossip (no vote injection), including late-joiner catchup and a
Byzantine equivocating proposer (reference `consensus/reactor_test.go`,
`consensus/byzantine_test.go:29-60`).
"""

import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.consensus.config import ConsensusConfig
from tendermint_tpu.consensus.reactor import (
    BlockPartMessage,
    ConsensusReactor,
    DATA_CHANNEL,
    ProposalMessage,
)
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.ticker import TimeoutTicker
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.p2p import NodeInfo, Switch, connect_switches
from tendermint_tpu.state import make_genesis_state
from tendermint_tpu.types import Txs
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.proposal import Proposal

from tests.helpers import CHAIN_ID as CHAIN
from tests.helpers import make_genesis

pytestmark = pytest.mark.slow


def wait_until(pred, timeout=60.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class Node:
    """One full in-process node: consensus state + reactor + switch."""

    def __init__(self, index: int, genesis, privs, config=None):
        self.index = index
        self.db = MemDB()
        self.store = BlockStore(MemDB())
        state = make_genesis_state(self.db, genesis)
        state.save()
        self.app = KVStoreApp()
        conns = local_client_creator(self.app)()
        self.cs = ConsensusState(
            config=config or ConsensusConfig.test_config(),
            state=state,
            app_conn=conns.consensus,
            block_store=self.store,
            priv_validator=privs[index],
            ticker=TimeoutTicker(),
        )
        self.reactor = ConsensusReactor(self.cs)
        self.switch = Switch(
            NodeInfo(node_id=f"node{index}", moniker=f"val{index}", chain_id=CHAIN)
        )
        self.switch.add_reactor("consensus", self.reactor)

    def start(self):
        self.switch.start()  # reactor.on_start starts the consensus loop

    def stop(self):
        self.switch.stop()

    @property
    def height(self) -> int:
        return self.cs.height


def make_network(n_nodes: int, n_vals: int | None = None, start=True):
    genesis, privs = make_genesis(n_vals or n_nodes, chain_id=CHAIN)
    nodes = [Node(i, genesis, privs) for i in range(n_nodes)]
    if start:
        for node in nodes:
            node.start()
        for i in range(n_nodes):
            for j in range(i + 1, n_nodes):
                connect_switches(nodes[i].switch, nodes[j].switch)
    return nodes, genesis, privs


def stop_all(nodes):
    for n in nodes:
        n.stop()


class TestHeartbeatGossip:
    def test_heartbeat_travels_the_wire(self):
        """No-empty-blocks idle chain: the proposer's signed heartbeats
        are broadcast on the STATE channel and the receiving node
        verifies them (reference reactor.go:338-349,219-222)."""
        import queue

        from tendermint_tpu.consensus.reactor import ProposalHeartbeatMessage

        genesis, privs = make_genesis(2, chain_id=CHAIN)
        cfg = ConsensusConfig.test_config()
        cfg.create_empty_blocks = False
        cfg.proposal_heartbeat_interval = 0.05
        nodes = [Node(i, genesis, privs, config=cfg) for i in range(2)]

        # spy on node1's state-channel traffic without disturbing dispatch
        seen: "queue.Queue" = queue.Queue()
        orig = nodes[1].reactor._receive_state

        def spying(peer, ps, msg):
            if isinstance(msg, ProposalHeartbeatMessage):
                seen.put(msg.heartbeat)
            return orig(peer, ps, msg)

        nodes[1].reactor._receive_state = spying
        for n in nodes:
            n.start()
        try:
            connect_switches(nodes[0].switch, nodes[1].switch)
            hb = seen.get(timeout=15)
            # signed by a validator of the live set over the chain id
            idx = hb.validator_index
            assert privs[idx].pub_key.verify(
                hb.sign_bytes(CHAIN), hb.signature
            )
            assert hb.height >= 1
            # chain is genuinely idle (no txs, no empty blocks)
            assert all(n.height == 1 for n in nodes)
        finally:
            stop_all(nodes)


class TestMultiNodeConsensus:
    def test_four_nodes_commit_ten_blocks(self):
        nodes, _, _ = make_network(4)
        try:
            wait_until(
                lambda: all(n.height >= 11 for n in nodes),
                timeout=120,
                msg="all nodes at height 11",
            )
            # identical chains: same stored block hash at every height
            for h in range(1, 11):
                hashes = {n.store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"fork at height {h}"
            # replicated app state agrees
            app_hashes = {n.cs.state.app_hash for n in nodes}
            assert len(app_hashes) == 1
        finally:
            stop_all(nodes)

    def test_late_joiner_catches_up(self):
        # 3 of 4 validators run ahead (75% power: quorum without the 4th)
        nodes, genesis, privs = make_network(3, n_vals=4, start=False)
        for n in nodes:
            n.start()
        for i in range(3):
            for j in range(i + 1, 3):
                connect_switches(nodes[i].switch, nodes[j].switch)
        late = None
        try:
            wait_until(
                lambda: all(n.height >= 5 for n in nodes),
                timeout=120,
                msg="head nodes at height 5",
            )
            late = Node(3, genesis, privs)
            late.start()
            for n in nodes:
                connect_switches(n.switch, late.switch)
            # late node must replicate past height 5 purely via catchup
            # gossip (stored seen-commit votes + stored block parts)
            wait_until(
                lambda: late.height >= 6,
                timeout=120,
                msg="late node caught up",
            )
            for h in range(1, 5):
                assert (
                    late.store.load_block(h).hash()
                    == nodes[0].store.load_block(h).hash()
                )
        finally:
            stop_all(nodes)
            if late is not None:
                late.stop()


class TestByzantineProposer:
    def test_equivocating_proposer_network_recovers(self):
        """Node 0 sends CONFLICTING proposals to different peers whenever
        it is the proposer (reference `byzantine_test.go:29-60`): no round
        it proposes can gather a polka, but honest rounds keep committing
        and every honest node stays on one chain."""
        nodes, _, _ = make_network(4)
        byz = nodes[0]

        def byzantine_decide(height, round_):
            rs = byz.cs.get_round_state()
            blocks = []
            for variant in (b"byz-a", b"byz-b"):
                block = Block.make_block(
                    height=height,
                    chain_id=CHAIN,
                    txs=Txs([variant]),
                    last_commit=rs.last_commit.make_commit()
                    if rs.last_commit is not None and height > 1
                    else Commit.empty(),
                    last_block_id=byz.cs.state.last_block_id,
                    time=time.time_ns(),
                    validators_hash=rs.validators.hash(),
                    app_hash=byz.cs.state.app_hash,
                )
                parts = block.make_part_set()
                prop = Proposal(
                    height=height,
                    round=round_,
                    block_parts_header=parts.header,
                    pol_round=-1,
                    pol_block_id=BlockID.zero(),
                    timestamp=time.time_ns(),
                )
                # sign around the double-sign guard (Byzantine behavior)
                sig = byz.cs.priv_validator._signer.sign(prop.sign_bytes(CHAIN))
                blocks.append((prop.with_signature(sig), parts))
            peers = byz.switch.peers()
            for i, peer in enumerate(peers):
                prop, parts = blocks[0] if i < len(peers) - 1 else blocks[1]
                peer.try_send(DATA_CHANNEL, ProposalMessage(prop).encode())
                for pi in range(parts.total):
                    peer.try_send(
                        DATA_CHANNEL,
                        BlockPartMessage(height, round_, parts.get_part(pi)).encode(),
                    )
            # its own consensus state gets no proposal -> prevotes nil

        byz.cs.decide_proposal_fn = byzantine_decide
        try:
            honest = nodes[1:]
            wait_until(
                lambda: all(n.height >= 6 for n in honest),
                timeout=180,
                msg="honest nodes commit despite equivocation",
            )
            for h in range(1, 6):
                hashes = {n.store.load_block(h).hash() for n in honest}
                assert len(hashes) == 1, f"fork at height {h}"
        finally:
            stop_all(nodes)
