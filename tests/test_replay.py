"""Handshake replay matrix + full-node crash/recovery at every fail index.

Reference `consensus/replay_test.go:296-317` (TestHandshakeReplay*) and
`test/persist/test_failure_indices.sh` (kill at each fail point, restart,
assert re-sync).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain import BlockStore
from tendermint_tpu.consensus.replay import Handshaker, HandshakeError
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.state import apply_block, load_state, make_genesis_state

from tests.helpers import ChainSim

N = 4  # chain length for the matrix


class _Chain:
    """A recorded 4-block chain + state snapshots at N-1 and N."""

    def __init__(self):
        self.sim = ChainSim(n_vals=3)
        self.store = BlockStore(MemDB())
        self.parts = []
        for i in range(N):
            block, ps = self.sim.make_next_block(txs=[b"h%d=%d" % (i + 1, i)])
            commit = self.sim._commit_for(block, ps)
            if i == N - 1:
                self.state_before_last = self.sim.state.copy()
            apply_block(self.sim.state, block, ps.header, self.sim.conns.consensus)
            self.store.save_block(block, ps, commit)
            self.sim.blocks.append(block)
            self.sim.commits.append(commit)
        self.final_state = self.sim.state

    def fresh_app_at(self, height: int):
        """A new app replayed to `height` (its own independent instance)."""
        app = KVStoreApp()
        conns = local_client_creator(app)()
        from tendermint_tpu.state.execution import exec_commit_block

        for h in range(1, height + 1):
            exec_commit_block(conns.consensus, self.store.load_block(h))
        return app, conns


@pytest.fixture(scope="module")
def chain():
    return _Chain()


class TestHandshakeMatrix:
    def _handshake(self, chain, state, app_height):
        app, conns = chain.fresh_app_at(app_height)
        h = Handshaker(state, chain.store)
        app_hash = h.handshake(conns)
        return app, conns, h, app_hash

    def test_replay_all(self, chain):
        state = chain.final_state.copy()
        app, conns, h, app_hash = self._handshake(chain, state, 0)
        assert h.n_blocks_replayed == N
        assert app_hash == state.app_hash
        assert conns.query.info_sync().last_block_height == N

    def test_replay_some(self, chain):
        state = chain.final_state.copy()
        app, conns, h, app_hash = self._handshake(chain, state, 2)
        assert h.n_blocks_replayed == N - 2
        assert app_hash == state.app_hash

    def test_replay_none(self, chain):
        state = chain.final_state.copy()
        app, conns, h, app_hash = self._handshake(chain, state, N)
        assert h.n_blocks_replayed == 0
        assert app_hash == state.app_hash

    def test_final_block_via_mock_app(self, chain):
        """App committed block N but state didn't save: state catches up
        from saved ABCIResponses without re-executing the real app."""
        state = chain.state_before_last.copy()
        state.db = chain.final_state.db  # responses live here
        app, conns = chain.fresh_app_at(N)
        before_txs = dict(app._data)
        h = Handshaker(state, chain.store)
        app_hash = h.handshake(conns)
        assert state.last_block_height == N
        assert app_hash == chain.final_state.app_hash
        assert app._data == before_txs  # real app was not re-mutated

    def test_final_block_via_real_replay(self, chain):
        """State saved N-1, app also behind: final block replays for real."""
        state = chain.state_before_last.copy()
        state.db = MemDB()  # fresh db; apply_block will save into it
        app, conns = chain.fresh_app_at(N - 1)
        h = Handshaker(state, chain.store)
        app_hash = h.handshake(conns)
        assert state.last_block_height == N
        assert app_hash == chain.final_state.app_hash
        assert conns.query.info_sync().last_block_height == N

    def test_store_ahead_of_state_by_two_rejected(self, chain):
        state = chain.state_before_last.copy()
        state.last_block_height = N - 3
        app, conns = chain.fresh_app_at(0)
        with pytest.raises(HandshakeError):
            Handshaker(state, chain.store).handshake(conns)

    def test_genesis_init_chain(self):
        sim = ChainSim(n_vals=3)
        store = BlockStore(MemDB())
        app = KVStoreApp()
        inited = []
        app.init_chain = lambda validators: inited.append(len(validators))
        conns = local_client_creator(app)()
        Handshaker(sim.state, store).handshake(conns)
        assert inited == [3]


_CRASH_SCRIPT = textwrap.dedent(
    """
    import os, sys, time, queue
    sys.path.insert(0, {repo!r})
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax; jax.config.update("jax_platforms", "cpu")
    os.chdir({repo!r})
    home = {home!r}
    from tendermint_tpu.db.kv import SQLiteDB
    from tendermint_tpu.abci.apps import PersistentKVStoreApp
    from tendermint_tpu.abci.client import local_client_creator
    from tendermint_tpu.blockchain import BlockStore
    from tendermint_tpu.consensus import ConsensusConfig, ConsensusState, TimeoutTicker
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.state import load_state, make_genesis_state
    from tendermint_tpu.types import events as ev
    from tests.helpers import make_genesis

    state_db = SQLiteDB(home + "/state.db")
    store = BlockStore(SQLiteDB(home + "/blockstore.db"))
    app = PersistentKVStoreApp(SQLiteDB(home + "/app.db"))
    conns = local_client_creator(app)()
    gen, privs = make_genesis(1, chain_id="crash-chain")
    state = load_state(state_db)
    if state is None:
        state = make_genesis_state(state_db, gen)
        state.save()
    state.db = state_db
    Handshaker(state, store).handshake(conns)
    cs = ConsensusState(
        config=ConsensusConfig.test_config(), state=state,
        app_conn=conns.consensus, block_store=store,
        priv_validator=privs[0], wal_path=home + "/cs.wal",
        ticker=TimeoutTicker(),
    )
    got = queue.Queue()
    cs.event_switch.add_listener("t", ev.EVENT_NEW_BLOCK, lambda d: got.put(d))
    cs.start()
    start_h = state.last_block_height
    deadline = time.time() + 30
    while time.time() < deadline:
        data = got.get(timeout=30)
        if data.block.header.height >= start_h + 2:
            print("REACHED", data.block.header.height)
            break
    cs.stop()
    """
)


class TestCrashRecoveryMatrix:
    @pytest.mark.parametrize("fail_index", range(0, 7))
    def test_kill_at_fail_point_then_recover(self, tmp_path, fail_index):
        """Run a solo node that crashes at fail point `fail_index`, then
        restart without injection and require progress (the reference's
        test_failure_indices matrix)."""
        home = str(tmp_path)
        script = tmp_path / "node.py"
        script.write_text(_CRASH_SCRIPT.format(repo=os.getcwd(), home=home))
        env = dict(os.environ, JAX_PLATFORMS="cpu", FAIL_TEST_INDEX=str(fail_index))
        p1 = subprocess.run(
            [sys.executable, str(script)], env=env, capture_output=True, text=True,
            timeout=120,
        )
        assert p1.returncode == 1, f"index {fail_index} did not crash: {p1.stdout}\n{p1.stderr}"
        # restart clean: must handshake, recover, and commit 2 more blocks
        env.pop("FAIL_TEST_INDEX")
        p2 = subprocess.run(
            [sys.executable, str(script)], env=env, capture_output=True, text=True,
            timeout=120,
        )
        assert p2.returncode == 0, f"recovery failed at index {fail_index}:\n{p2.stdout}\n{p2.stderr}"
        assert "REACHED" in p2.stdout
