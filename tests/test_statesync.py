"""State sync: manifest round-trips, chunk-tree verification (corrupt /
duplicated / out-of-order chunks), trust-anchor rejection of forged
commits, chunk-pool timeout/requeue, block-store base/prune/bootstrap,
and the end-to-end restore scenarios (two-node, and the 4-node
acceptance run with the device breaker tripped via
TENDERMINT_TPU_DEVICE_FAIL).
"""

from __future__ import annotations

import json
import time

import pytest

from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.merkle.simple import leaf_hash
from tendermint_tpu.services.hasher import TreeHasher
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.statesync.snapshot import (
    SnapshotManifest,
    SnapshotStore,
    build_payload,
    decode_payload,
    split_chunks,
    verify_chunks,
    verify_chunks_async,
)
from tendermint_tpu.statesync.reactor import ChunkPool
from tendermint_tpu.statesync.trust import TrustAnchor, TrustOptions
from tendermint_tpu.testing.nemesis import Nemesis, make_genesis
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.utils import fail

from tests.helpers import CHAIN_ID, make_validators

HOST_HASHER = TreeHasher(backend="host")


@pytest.fixture(autouse=True)
def _clean_faults():
    fail.clear_device_faults()
    yield
    fail.clear_device_faults()


def _snapshot_state(height=5, app_hash=b"\xaa" * 20, chain_id="ss-chain"):
    genesis, _ = make_genesis(4, chain_id=chain_id)
    st = make_genesis_state(MemDB(), genesis)
    st.last_block_height = height
    st.app_hash = app_hash
    return st


class TestManifest:
    def _manifest(self, payload=b"x" * 1000, chunk_size=128):
        st = _snapshot_state()
        store = SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=chunk_size)
        return store.take(st, payload), store

    def test_roundtrip(self):
        m, _ = self._manifest()
        m2 = SnapshotManifest.from_json(m.to_json())
        assert m2.to_json() == m.to_json()
        assert (m2.height, m2.chunks, m2.root) == (m.height, m.chunks, m.root)
        m2.validate_basic()
        m2.verify_root(HOST_HASHER)

    def test_validate_rejects_inconsistencies(self):
        m, _ = self._manifest()
        bad = SnapshotManifest.from_json(m.to_json())
        bad.chunks += 1
        with pytest.raises(ValidationError):
            bad.validate_basic()
        bad = SnapshotManifest.from_json(m.to_json())
        bad.payload_len = bad.chunks * bad.chunk_size + 1
        with pytest.raises(ValidationError):
            bad.validate_basic()
        bad = SnapshotManifest.from_json(m.to_json())
        bad.root = b""
        with pytest.raises(ValidationError):
            bad.validate_basic()

    def test_forged_chunk_hash_list_fails_root_check(self):
        m, _ = self._manifest()
        forged = SnapshotManifest.from_json(m.to_json())
        forged.chunk_hashes[0] = leaf_hash(b"not the chunk")
        with pytest.raises(ValidationError, match="root"):
            forged.verify_root(HOST_HASHER)


class TestChunkVerification:
    def _take(self):
        st = _snapshot_state()
        store = SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=100)
        m = store.take(st, b"app" * 400)
        chunks = [store.load_chunk(m.height, m.format, i) for i in range(m.chunks)]
        return m, chunks

    def test_clean_set_verifies(self):
        m, chunks = self._take()
        verify_chunks(m, chunks, HOST_HASHER)

    def test_corrupted_chunk_detected(self):
        m, chunks = self._take()
        chunks[1] = bytes(b ^ 0xFF for b in chunks[1])
        with pytest.raises(ValidationError, match="chunk 1"):
            verify_chunks(m, chunks, HOST_HASHER)

    def test_out_of_order_chunks_detected(self):
        m, chunks = self._take()
        chunks[0], chunks[1] = chunks[1], chunks[0]
        with pytest.raises(ValidationError):
            verify_chunks(m, chunks, HOST_HASHER)

    def test_duplicated_chunk_detected(self):
        m, chunks = self._take()
        chunks[2] = chunks[1]
        with pytest.raises(ValidationError):
            verify_chunks(m, chunks, HOST_HASHER)

    def test_wrong_count_detected(self):
        m, chunks = self._take()
        with pytest.raises(ValidationError):
            verify_chunks(m, chunks[:-1], HOST_HASHER)

    def test_payload_roundtrip(self):
        st = _snapshot_state()
        payload = build_payload(st, b"app-bytes", [])
        state_json, app, tail = decode_payload(payload)
        assert json.loads(state_json) == json.loads(st.to_json())
        assert app == b"app-bytes"
        assert tail == []
        assert b"".join(split_chunks(payload, 7)) == payload


class TestChunkVerifyAsyncGate:
    """The chunk-verify gate as a dispatch handle (ROADMAP dispatch
    follow-up): hashing launches through the hasher's async seam, the
    comparison + root fold run at the join, and device faults degrade
    to host hashlib INSIDE the handle — the restore path overlaps
    payload decode with the in-flight launch either way."""

    def _take(self):
        st = _snapshot_state()
        store = SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=100)
        m = store.take(st, b"app" * 400)
        chunks = [store.load_chunk(m.height, m.format, i) for i in range(m.chunks)]
        return m, chunks

    def test_clean_set_resolves_true_at_join(self):
        m, chunks = self._take()
        gate = verify_chunks_async(m, chunks, HOST_HASHER)
        assert gate.result() is True

    def test_corrupt_chunk_raises_at_join_not_submit(self):
        m, chunks = self._take()
        chunks[1] = bytes(b ^ 0xFF for b in chunks[1])
        gate = verify_chunks_async(m, chunks, HOST_HASHER)  # must not raise
        with pytest.raises(ValidationError, match="chunk 1"):
            gate.result()

    def test_wrong_count_is_an_error_handle(self):
        m, chunks = self._take()
        gate = verify_chunks_async(m, chunks[:-1], HOST_HASHER)
        with pytest.raises(ValidationError, match="chunks"):
            gate.result()

    def test_routes_through_the_hashers_async_seam(self):
        from tendermint_tpu.services.dispatch import DispatchQueue

        class _Recording(TreeHasher):
            def __init__(self):
                super().__init__(backend="host")
                self.async_calls = 0

            def leaf_hashes_async(self, items, queue=None):
                self.async_calls += 1
                return super().leaf_hashes_async(items, queue=queue)

        hasher = _Recording()
        m, chunks = self._take()
        q = DispatchQueue(depth=2, name="test-chunk-gate")
        try:
            assert verify_chunks_async(m, chunks, hasher, queue=q).result() is True
        finally:
            q.close()
        assert hasher.async_calls == 1

    def test_device_fault_degrades_inside_the_gate(self):
        from tendermint_tpu.services.dispatch import DispatchQueue
        from tendermint_tpu.services.resilient import ResilientTreeHasher
        from tendermint_tpu.utils.circuit import OPEN, CircuitBreaker

        rh = ResilientTreeHasher(
            TreeHasher(backend="host"),
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
            max_retries=0,
        )
        fail.set_device_fault("hash")
        m, chunks = self._take()
        q = DispatchQueue(depth=2, name="test-chunk-gate-fault")
        try:
            # faulted launch re-hashes on host inside the handle: the
            # gate still verdicts, nothing raises into the restore path
            assert verify_chunks_async(m, chunks, rh, queue=q).result() is True
        finally:
            q.close()
        assert rh.breaker.state == OPEN
        # and a corrupt chunk is still caught while degraded
        chunks[0] = b"garbage" + chunks[0][7:]
        q2 = DispatchQueue(depth=2, name="test-chunk-gate-fault2")
        try:
            with pytest.raises(ValidationError, match="chunk 0"):
                verify_chunks_async(m, chunks, rh, queue=q2).result()
        finally:
            q2.close()


class TestSnapshotStore:
    def test_prune_keeps_newest(self):
        store = SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=64, keep_recent=2)
        for h in (3, 6, 9):
            store.take(_snapshot_state(height=h), b"s" * 100)
        heights = [m.height for m in store.list_manifests()]
        assert heights == [6, 9]
        # chunks of the pruned snapshot are gone too
        assert store.load_chunk(3, 1, 0) is None
        assert store.load_chunk(9, 1, 0) is not None

    def test_corrupt_chunk_hook(self):
        store = SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=64)
        m = store.take(_snapshot_state(), b"s" * 100)
        before = store.load_chunk(m.height, m.format, 0)
        assert store.corrupt_chunk(m.height, m.format, 0)
        assert store.load_chunk(m.height, m.format, 0) != before


def _full_commit(height, valset, privs, app_hash=b"", chain_id=CHAIN_ID, forge=False):
    """A properly-signed FullCommit over a synthetic header (or a forged
    one: votes signed by keys OUTSIDE the validator set)."""
    from tendermint_tpu.certifiers.certifier import FullCommit
    from tendermint_tpu.types.block import Commit, Header
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT

    header = Header(
        chain_id=chain_id,
        height=height,
        time=1_700_000_000_000_000_000,
        num_txs=0,
        last_block_id=BlockID.zero(),
        last_commit_hash=b"\x01" * 20,
        data_hash=b"",
        validators_hash=valset.hash(),
        app_hash=app_hash,
    )
    h = header.hash()
    block_id = BlockID(hash=h, parts_header=PartSetHeader(total=1, hash=h[:20]))
    if forge:
        # claimed validator addresses are real; the SIGNATURES come from
        # attacker keys — exactly what certifier anchoring must catch
        from tendermint_tpu.crypto import PrivKey
        from tendermint_tpu.types import PrivValidator
        from tendermint_tpu.types.vote import Vote

        wrong = [
            PrivValidator(PrivKey((100 + i).to_bytes(32, "little")))
            for i in range(len(privs))
        ]
        votes = []
        for i, (real, attacker) in enumerate(zip(privs, wrong)):
            v = Vote(
                validator_address=real.address,
                validator_index=i,
                height=height,
                round=0,
                timestamp=1000,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=block_id,
            )
            votes.append(v.with_signature(attacker._signer.sign(v.sign_bytes(chain_id))))
    else:
        # bypass the double-sign guard: tests build commits at arbitrary
        # heights out of order (byzantine_signed_vote's approach)
        from tendermint_tpu.types.vote import Vote

        votes = []
        for i, p in enumerate(privs):
            v = Vote(
                validator_address=p.address,
                validator_index=i,
                height=height,
                round=0,
                timestamp=1000,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=block_id,
            )
            votes.append(v.with_signature(p._signer.sign(v.sign_bytes(chain_id))))
    return FullCommit(header=header, commit=Commit(block_id=block_id, precommits=votes), validators=valset)


def _manifest_for(height, app_hash, chain_id=CHAIN_ID):
    st = _snapshot_state(height=height, app_hash=app_hash, chain_id=chain_id)
    store = SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=128)
    return store.take(st, b"app" * 10)


class TestTrustAnchor:
    def setup_method(self):
        self.valset, self.privs = make_validators(4)
        self.anchor = TrustAnchor(CHAIN_ID, self.valset)

    def test_accepts_genuine_commit(self):
        app_hash = b"\xaa" * 20
        manifest = _manifest_for(7, app_hash)
        fc = _full_commit(8, self.valset, self.privs, app_hash=app_hash)
        self.anchor.verify_snapshot(manifest, fc)

    def test_rejects_forged_signatures(self):
        app_hash = b"\xaa" * 20
        manifest = _manifest_for(7, app_hash)
        fc = _full_commit(8, self.valset, self.privs, app_hash=app_hash, forge=True)
        with pytest.raises(ValidationError):
            self.anchor.verify_snapshot(manifest, fc)

    def test_rejects_app_hash_mismatch(self):
        manifest = _manifest_for(7, b"\xaa" * 20)
        fc = _full_commit(8, self.valset, self.privs, app_hash=b"\xbb" * 20)
        with pytest.raises(ValidationError, match="app_hash"):
            self.anchor.verify_snapshot(manifest, fc)

    def test_rejects_wrong_anchor_height(self):
        app_hash = b"\xaa" * 20
        manifest = _manifest_for(7, app_hash)
        fc = _full_commit(9, self.valset, self.privs, app_hash=app_hash)
        with pytest.raises(ValidationError, match="anchor"):
            self.anchor.verify_snapshot(manifest, fc)

    def test_rejects_wrong_chain(self):
        manifest = _manifest_for(7, b"\xaa" * 20, chain_id="other-chain")
        fc = _full_commit(8, self.valset, self.privs, app_hash=b"\xaa" * 20)
        with pytest.raises(ValidationError, match="chain"):
            self.anchor.verify_snapshot(manifest, fc)

    def test_trust_pin_must_match(self):
        app_hash = b"\xaa" * 20
        pin_fc = _full_commit(3, self.valset, self.privs)
        anchor = TrustAnchor(
            CHAIN_ID,
            self.valset,
            TrustOptions(height=3, hash_=pin_fc.header.hash()),
        )
        manifest = _manifest_for(7, app_hash)
        fc = _full_commit(8, self.valset, self.privs, app_hash=app_hash)
        anchor.verify_snapshot(manifest, fc, pin_fc)  # genuine pin OK
        bad = TrustAnchor(
            CHAIN_ID, self.valset, TrustOptions(height=3, hash_=b"\x13" * 32)
        )
        with pytest.raises(ValidationError, match="pinned"):
            bad.verify_snapshot(manifest, fc, pin_fc)
        # a snapshot below the trust root can never anchor
        low = _manifest_for(2, app_hash)
        low_fc = _full_commit(3, self.valset, self.privs, app_hash=app_hash)
        anchor2 = TrustAnchor(
            CHAIN_ID, self.valset, TrustOptions(height=3, hash_=pin_fc.header.hash())
        )
        with pytest.raises(ValidationError, match="below trust root"):
            anchor2.verify_snapshot(low, low_fc, pin_fc)

    def test_trust_period_expiry(self):
        app_hash = b"\xaa" * 20
        manifest = _manifest_for(7, app_hash)
        fc = _full_commit(8, self.valset, self.privs, app_hash=app_hash)
        fresh = TrustAnchor(
            CHAIN_ID,
            self.valset,
            TrustOptions(trust_period_ns=int(3600e9)),
            now_ns=lambda: fc.header.time + int(60e9),
        )
        fresh.verify_snapshot(manifest, fc)
        stale = TrustAnchor(
            CHAIN_ID,
            self.valset,
            TrustOptions(trust_period_ns=int(3600e9)),
            now_ns=lambda: fc.header.time + int(7200e9),
        )
        with pytest.raises(ValidationError, match="trust period"):
            stale.verify_snapshot(manifest, fc)

    def test_restored_state_must_match_certified_header(self):
        app_hash = b"\xaa" * 20
        fc = _full_commit(8, self.valset, self.privs, app_hash=app_hash)
        st = _snapshot_state(height=7, app_hash=app_hash, chain_id=CHAIN_ID)
        st.validators = self.valset
        self.anchor.verify_restored_state(st, fc)
        st2 = _snapshot_state(height=7, app_hash=b"\xcc" * 20, chain_id=CHAIN_ID)
        st2.validators = self.valset
        with pytest.raises(ValidationError):
            self.anchor.verify_restored_state(st2, fc)


class TestChunkPool:
    def test_inflight_limit_and_assignment(self):
        now = [0.0]
        pool = ChunkPool(10, inflight_per_peer=2, request_timeout_s=5.0, time_fn=lambda: now[0])
        pool.add_peer("a")
        pool.add_peer("b")
        reqs, evicted = pool.schedule()
        assert not evicted
        assert len(reqs) == 4  # 2 per peer
        per_peer = {}
        for p, _i in reqs:
            per_peer[p] = per_peer.get(p, 0) + 1
        assert per_peer == {"a": 2, "b": 2}

    def test_only_assigned_peer_may_answer(self):
        pool = ChunkPool(4, inflight_per_peer=4)
        pool.add_peer("a")
        reqs, _ = pool.schedule()
        idx = reqs[0][1]
        assert not pool.add_chunk("b", idx, b"x")  # unsolicited
        assert pool.add_chunk("a", idx, b"x")
        assert not pool.add_chunk("a", idx, b"x")  # duplicate

    def test_timeout_evicts_and_requeues(self):
        now = [0.0]
        pool = ChunkPool(2, inflight_per_peer=2, request_timeout_s=5.0, time_fn=lambda: now[0])
        pool.add_peer("slow")
        pool.add_peer("ok")
        reqs, _ = pool.schedule()
        by_peer = {p: i for p, i in reqs}
        assert set(by_peer) == {"slow", "ok"}
        pool.add_chunk("ok", by_peer["ok"], b"ok-data")
        now[0] = 6.0  # the slow peer's request is now stale
        reqs2, evicted = pool.schedule()
        assert evicted == ["slow"]
        assert pool.num_peers() == 1
        # the freed chunk reassigned to the surviving peer in-tick
        assert ("ok", by_peer["slow"]) in reqs2
        pool.add_chunk("ok", by_peer["slow"], b"more")
        assert pool.is_complete()

    def test_requeue_after_bad_hash(self):
        pool = ChunkPool(1, inflight_per_peer=1)
        pool.add_peer("a")
        reqs, _ = pool.schedule()
        assert pool.add_chunk("a", 0, b"corrupt")
        pool.requeue(0)
        assert not pool.is_complete()
        reqs, _ = pool.schedule()
        assert reqs == [("a", 0)]


class TestBlockStoreBase:
    def test_fresh_store_base_zero_then_tracks(self):
        store = BlockStore(MemDB())
        assert store.base == 0 and store.height == 0
        assert store.load_block(5) is None  # no decode error, just None

    def test_prune_bounds_history(self, tmp_path):
        # build a real store via a 1-node nemesis chain
        with Nemesis(1, home=str(tmp_path)) as net:
            net.wait_height(6, timeout=60)
            store = net.nodes[0].store
            assert store.base == 1
            pruned = store.prune(4)
            assert pruned == 3
            assert store.base == 4
            for h in (1, 2, 3):
                assert store.load_block(h) is None
                assert store.load_block_meta(h) is None
                assert store.load_seen_commit(h) is None
            assert store.load_block(4) is not None
            assert store.load_block_commit(3) is not None  # kept for block 4
            # watermark round-trips base through a reopen
            store2 = BlockStore(net.nodes[0].store_db)
            assert store2.base == 4 and store2.height == store.height
            assert store2.prune(2) == 0  # no-op below base

    def test_bootstrap_from_tail(self, tmp_path):
        with Nemesis(1, home=str(tmp_path)) as net:
            net.wait_height(5, timeout=60)
            src = net.nodes[0].store
            tail = []
            for h in (4, 5):
                tail.append((src.load_block(h), src.load_seen_commit(h)))
        dst = BlockStore(MemDB())
        dst.bootstrap(tail)
        assert dst.base == 4 and dst.height == 5
        assert dst.load_block(3) is None
        assert dst.load_block(4).hash() == tail[0][0].hash()
        assert dst.load_block_commit(4) is not None  # from block 5's LastCommit
        with pytest.raises(ValidationError):
            dst.bootstrap(tail)  # non-empty store refuses


def _wait(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _counter(name, **labels):
    from tendermint_tpu.telemetry import REGISTRY

    return REGISTRY.counter_value(name, **labels)


def _serving_mutator(interval=3):
    def mutate(cfg):
        cfg.statesync.snapshot_interval = interval

    return mutate


def _join_fresh_node(net, index, trust_height=0, trust_hash=""):
    """Build a fresh full node with state_sync enabled and admit it."""
    from tendermint_tpu.testing.nemesis import FullNemesisNode

    def mutate(cfg):
        cfg.statesync.enable = True
        cfg.statesync.trust_height = trust_height
        cfg.statesync.trust_hash = trust_hash

    joiner = FullNemesisNode(
        index, net.genesis, net.privs, net.home, net.chain_id, config_mutator=mutate
    )
    net.add_node(joiner)
    return joiner


class TestStateSyncEndToEnd:
    def test_two_node_restore(self, tmp_path):
        """Solo producer + fresh joiner: the joiner restores app state
        from snapshot chunks and converges, with a pinned trust root."""
        with Nemesis(
            1,
            n_vals=1,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(
                config_mutator=_serving_mutator(interval=3)
            ),
        ) as net:
            producer = net.nodes[0]
            # commit app data BEFORE the snapshot so restore must carry it
            producer.node.mempool.check_tx(b"ss-key=ss-val")
            net.wait_height(5, timeout=60)
            assert net.nodes[0].node.snapshot_store.list_manifests()
            pin = producer.store.load_block_meta(1)
            joiner = _join_fresh_node(
                net, 1, trust_height=1, trust_hash=pin.header.hash().hex()
            )
            _wait(
                lambda: joiner.node.statesync_reactor.restored_state is not None,
                30,
                "snapshot restore",
            )
            restored = joiner.node.statesync_reactor.restored_state
            snap_height = restored.last_block_height
            assert snap_height >= 3
            # restored, not replayed: the store starts at the tail base
            _wait(lambda: joiner.store.base > 1, 10, "truncated store base")
            assert joiner.app._data.get(b"ss-key") == b"ss-val"
            # height parity: the joiner keeps up with the producer
            _wait(
                lambda: joiner.store.height >= producer.store.height - 1,
                60,
                "joiner catches the producer",
            )
            assert joiner.store.load_block(snap_height) is not None
            assert joiner.store.load_block(1) is None

    def test_four_node_acceptance(self, tmp_path):
        """THE acceptance scenario: fresh node joins a 4-node network
        with the device hasher breaker TRIPPED via fault injection —
        chunks still verify through the breaker's host fallback — and
        reaches consensus height parity; a tampered snapshot is
        certifier-rejected along the way."""
        fail.set_device_fault("hash")  # device Merkle 'dies' before composition
        try:
            with Nemesis(
                4,
                home=str(tmp_path),
                node_factory=Nemesis.full_node_factory(
                    config_mutator=_serving_mutator(interval=3)
                ),
            ) as net:
                net.nodes[0].node.mempool.check_tx(b"acc-key=acc-val")
                net.wait_height(5, timeout=90)
                assert net.nodes[0].node.snapshot_store.list_manifests()
                # node 3 additionally offers a FORGED snapshot claiming a
                # far-future height + bogus app_hash: highest on offer, so
                # the joiner tries it FIRST — and no commit can anchor it,
                # so it must be certifier-rejected before the honest one
                # restores
                evil_store = net.nodes[3].node.snapshot_store
                forged = evil_store.list_manifests()[-1]
                forged.height += 1000
                forged.app_hash = b"\xee" * 20
                evil_store._db.set(
                    evil_store._manifest_key(forged.height, forged.format),
                    forged.to_json(),
                )
                rejected_before = _counter(
                    "tendermint_statesync_snapshots_rejected_total"
                )
                restored_before = _counter(
                    "tendermint_statesync_restores_total", result="ok"
                )
                fallback_before = _counter(
                    "tendermint_device_fallback_calls_total", kind="hash"
                )
                joiner = _join_fresh_node(net, 4)
                _wait(
                    lambda: joiner.node.statesync_reactor.restored_state is not None,
                    45,
                    "snapshot restore on host fallback",
                )
                assert (
                    _counter("tendermint_statesync_restores_total", result="ok")
                    > restored_before
                )
                # breaker fallback actually carried the Merkle work
                assert (
                    _counter("tendermint_device_fallback_calls_total", kind="hash")
                    > fallback_before
                )
                assert joiner.node.hasher.degraded
                # the forged offer was attempted first and rejected: no
                # commit could anchor its claimed height/app_hash
                assert (
                    _counter("tendermint_statesync_snapshots_rejected_total")
                    > rejected_before
                )
                restored = joiner.node.statesync_reactor.restored_manifest
                assert restored.height < forged.height
                assert joiner.node.statesync_reactor.restored_state.app_hash != b"\xee" * 20
                assert joiner.app._data.get(b"acc-key") == b"acc-val"
                _wait(
                    lambda: joiner.store.height
                    >= max(n.store.height for n in net.nodes[:4]) - 2,
                    60,
                    "joiner reaches height parity",
                )
                assert joiner.store.base > 1  # restored, not replayed
        finally:
            fail.clear_device_faults()

    def test_forged_commit_rejected_end_to_end(self, tmp_path):
        """A network serving a snapshot whose anchoring commit cannot
        certify (the joiner pins a DIFFERENT trust root) never restores:
        state sync gives up and falls back to plain fast-sync — the node
        still converges, through replay."""
        with Nemesis(
            1,
            n_vals=1,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(
                config_mutator=_serving_mutator(interval=3)
            ),
        ) as net:
            net.wait_height(5, timeout=60)

            def mutate(cfg):
                cfg.statesync.enable = True
                # pin a bogus trust root: every offered snapshot must fail
                cfg.statesync.trust_height = 1
                cfg.statesync.trust_hash = "13" * 32
                cfg.statesync.giveup_time_s = 6.0

            from tendermint_tpu.testing.nemesis import FullNemesisNode

            rejected_before = _counter(
                "tendermint_statesync_snapshots_rejected_total"
            )
            joiner = FullNemesisNode(
                1, net.genesis, net.privs, net.home, net.chain_id, config_mutator=mutate
            )
            net.add_node(joiner)
            _wait(
                lambda: _counter("tendermint_statesync_snapshots_rejected_total")
                > rejected_before,
                20,
                "snapshot rejection",
            )
            # gave up -> plain fast-sync from genesis still converges
            _wait(
                lambda: joiner.store.height >= 3 and joiner.store.base == 1,
                45,
                "fallback fast-sync from genesis",
            )
            assert joiner.node.statesync_reactor.restored_state is None


class TestServingLifecycle:
    """Snapshot-serving node lifecycle: restart resumes the persisted
    cadence (no early re-take, snapshots advertised immediately) and
    `[statesync] retain_blocks` bounds the block store after each take."""

    def _reactor(self, snap_store, block_store, state, **kw):
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        return StateSyncReactor(snap_store, block_store, state, **kw)

    def test_restart_resumes_snapshot_cadence(self):
        from tests.helpers import ChainSim

        sim = ChainSim(n_vals=4)
        store = BlockStore(MemDB())

        def advance_to(height):
            while store.height < height:
                block = sim.advance()
                store.save_block(block, block.make_part_set(), sim.commits[-1])

        advance_to(10)
        db = MemDB()
        first = self._reactor(
            SnapshotStore(db, hasher=HOST_HASHER, chunk_size=64),
            store,
            sim.state,
            snapshot_interval=5,
        )
        assert first.maybe_take_snapshot(sim.state, app=sim.app) is not None

        # rebuild over the SAME db (the restart): the boot-time store
        # scan must find the persisted snapshot — advertised immediately
        # — and resume the take cadence from height 10
        reborn = SnapshotStore(db, hasher=HOST_HASHER, chunk_size=64)
        assert [m.height for m in reborn.list_manifests()] == [10]
        reactor = self._reactor(reborn, store, sim.state, snapshot_interval=5)
        assert reactor._last_snapshot_height == 10
        advance_to(12)  # interval not elapsed: no early re-take
        assert reactor.maybe_take_snapshot(sim.state, app=sim.app) is None
        advance_to(15)
        taken = reactor.maybe_take_snapshot(sim.state, app=sim.app)
        assert taken is not None and taken.height == 15

    def test_retain_blocks_prunes_store_after_snapshot(self):
        from tests.helpers import ChainSim

        sim = ChainSim(n_vals=4)
        store = BlockStore(MemDB())
        for _ in range(12):
            block = sim.advance()
            store.save_block(block, block.make_part_set(), sim.commits[-1])
        reactor = self._reactor(
            SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=64),
            store,
            sim.state,
            snapshot_interval=5,
            retain_blocks=4,
        )
        assert reactor.maybe_take_snapshot(sim.state, app=sim.app) is not None
        # pruned to head-retain+1: [9..12] kept, history below answers None
        assert store.base == 9 and store.height == 12
        assert store.load_block(8) is None
        assert store.load_block(9) is not None

    def test_retain_blocks_zero_keeps_everything(self):
        from tests.helpers import ChainSim

        sim = ChainSim(n_vals=4)
        store = BlockStore(MemDB())
        for _ in range(6):
            block = sim.advance()
            store.save_block(block, block.make_part_set(), sim.commits[-1])
        reactor = self._reactor(
            SnapshotStore(MemDB(), hasher=HOST_HASHER, chunk_size=64),
            store,
            sim.state,
            snapshot_interval=5,
            retain_blocks=0,
        )
        assert reactor.maybe_take_snapshot(sim.state, app=sim.app) is not None
        assert store.base == 1 and store.load_block(1) is not None
