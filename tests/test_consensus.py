"""Consensus state machine: progress, locking safety, WAL crash recovery.

Port of the reference harness pattern (`consensus/common_test.go`):
MockTicker fires only height-start timeouts; tests drive all other
transitions by injecting signed votes directly.
"""

import queue
import threading
import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain import BlockStore
from tendermint_tpu.consensus import (
    ConsensusConfig,
    ConsensusState,
    MockTicker,
    TimeoutTicker,
)
from tendermint_tpu.consensus.round_state import RoundStepType
from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, MsgRecord
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.state import make_genesis_state
from tendermint_tpu.types import events as ev
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.priv_validator import PrivValidator
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE, Vote

from tests.helpers import make_genesis

CHAIN = "cons-test"


class Fixture:
    """One in-process consensus node + scripted co-validators."""

    def __init__(
        self,
        n_vals=4,
        wal_path=None,
        db=None,
        store_db=None,
        config=None,
        real_ticker=False,
        verifier=None,
    ):
        self.genesis, self.privs = make_genesis(n_vals, chain_id=CHAIN)
        self.db = db if db is not None else MemDB()
        self.store = BlockStore(store_db if store_db is not None else MemDB())
        state = make_genesis_state(self.db, self.genesis)
        state.save()
        self.app = KVStoreApp()
        conns = local_client_creator(self.app)()
        self.config = config or ConsensusConfig.test_config()
        # our validator is privs[0] (valset order)
        self.cs = ConsensusState(
            config=self.config,
            state=state,
            app_conn=conns.consensus,
            block_store=self.store,
            priv_validator=self.privs[0],
            wal_path=wal_path,
            ticker=TimeoutTicker() if real_ticker else MockTicker(),
            verifier=verifier,
        )
        self.events: "queue.Queue[tuple[str, object]]" = queue.Queue()
        for name in (
            ev.EVENT_NEW_ROUND_STEP,
            ev.EVENT_NEW_BLOCK,
            ev.EVENT_LOCK,
            ev.EVENT_UNLOCK,
            ev.EVENT_RELOCK,
            ev.EVENT_POLKA,
        ):
            self.cs.event_switch.add_listener(
                "test", name, lambda data, n=name: self.events.put((n, data))
            )

    def wait_event(self, name, timeout=10.0, pred=None):
        deadline = time.time() + timeout
        while True:
            remaining = deadline - time.time()
            assert remaining > 0, f"timed out waiting for {name}"
            got, data = self.events.get(timeout=remaining)
            if got == name and (pred is None or pred(data)):
                return data

    def wait_step(self, step_name, timeout=10.0):
        return self.wait_event(
            ev.EVENT_NEW_ROUND_STEP, timeout, lambda d: d.step == step_name
        )

    def wait_height(self, height, timeout=20.0):
        while True:
            data = self.wait_event(ev.EVENT_NEW_BLOCK, timeout)
            if data.block.header.height >= height:
                return data.block

    def inject_votes(self, type_, block_id, val_indices, height=None, round_=0):
        """Sign + inject votes from co-validators (scripted signers)."""
        height = height if height is not None else self.cs.height
        for i in val_indices:
            vote = Vote(
                validator_address=self.privs[i].address,
                validator_index=i,
                height=height,
                round=round_,
                timestamp=time.time_ns(),
                type=type_,
                block_id=block_id,
            )
            vote = self.privs[i].sign_vote(CHAIN, vote)
            self.cs.add_vote(vote, peer_id=f"peer{i}")

    def proposal_block_id(self, timeout=10.0):
        """Wait until our node has a complete proposal block; return its id."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            rs = self.cs.get_round_state()
            if rs.proposal_block is not None:
                return BlockID(
                    rs.proposal_block.hash(), rs.proposal_block_parts.header
                )
            time.sleep(0.01)
        raise AssertionError("no complete proposal block")

    def stop(self):
        self.cs.stop()


class TestSoloValidator:
    def test_commits_blocks_alone(self):
        f = Fixture(n_vals=1)
        try:
            f.cs.start()
            block = f.wait_height(3)
            assert block.header.height >= 3
            assert f.store.height >= 3
            assert f.cs.state.last_block_height >= 3
        finally:
            f.stop()

    def test_raising_listener_does_not_stall_consensus(self):
        # EventSwitch.fire must isolate listener exceptions: a raising
        # NewBlock subscriber fires between commit and _schedule_round0,
        # and an escaping exception there would stall the node at the
        # new height (round-2 advisor finding).
        f = Fixture(n_vals=1, real_ticker=True)

        def bomb(_data):
            raise RuntimeError("subscriber bug")

        f.cs.event_switch.add_listener("bomb", ev.EVENT_NEW_BLOCK, bomb)
        try:
            f.cs.start()
            f.wait_height(3)  # keeps committing despite the raising listener
        finally:
            f.stop()

    def test_app_state_follows(self):
        f = Fixture(n_vals=1)
        try:
            f.cs.start()
            f.wait_height(2)
            assert f.app._height >= 2 or f.cs.state.app_hash == b""
        finally:
            f.stop()


class TestPipelinedFinalize:
    """Cross-height pipelined commit (PR 14): the apply launches as a
    dispatch handle, H+1 enters on a speculated state, and the join
    barrier swaps the applied truth in before anything reads it."""

    def test_pipelined_commit_records_overlap_and_joins(self):
        f = Fixture(n_vals=1)
        try:
            f.cs.start()
            f.wait_height(3)
            recs = f.cs.height_ledger.recent()
            pipelined = [r for r in recs if r.get("pipelined")]
            assert pipelined, "no height took the pipelined tail"
            for r in pipelined:
                assert "apply_overlap_s" in r
            assert f.cs.pipeline_stats["joins"] >= len(pipelined)
            # EVENT_NEW_BLOCK fires at the join: applied state visible
            assert f.cs.state.last_block_height >= 3
            assert f.store.height >= 3
        finally:
            f.stop()

    def test_env_opt_out_restores_serial(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_PIPELINE", "0")
        f = Fixture(n_vals=1)
        try:
            assert not f.cs.pipeline_enabled
            f.cs.start()
            f.wait_height(2)
            assert not any(
                r.get("pipelined") for r in f.cs.height_ledger.recent()
            )
        finally:
            f.stop()

    def test_endblock_valset_change_rebuilds_speculation(self):
        """EndBlock rotating the validator set mid-pipeline: the join
        barrier must rebuild the speculated H+1 round state (fresh
        HeightVoteSet against the post-EndBlock set) and consensus must
        keep committing under the new set."""
        from tendermint_tpu.abci.types import Validator as ABCIValidator

        f = Fixture(n_vals=1)
        pub = f.cs.validators.validators[0].pub_key.data
        orig_end_block = f.app.end_block

        def end_block(height):
            orig_end_block(height)
            # bump our own power from height 2 on (idempotent after the
            # first application -> exactly one speculation mismatch)
            return [ABCIValidator(pub, 20)] if height >= 2 else []

        f.app.end_block = end_block
        try:
            f.cs.start()
            f.wait_height(4)
            assert f.cs.pipeline_stats["valset_rebuilds"] >= 1
            assert f.cs.validators.validators[0].voting_power == 20
        finally:
            f.stop()


class TestQuorumProgress:
    def test_four_validators_commit_with_injected_votes(self):
        f = Fixture(n_vals=4)
        try:
            f.cs.start()
            # we are one of 4 proposers; wait for OUR proposal at h1 r0
            # (privs[0] proposes round 0 by accum rotation from genesis)
            bid = f.proposal_block_id()
            f.inject_votes(VOTE_TYPE_PREVOTE, bid, [1, 2, 3])
            f.inject_votes(VOTE_TYPE_PRECOMMIT, bid, [1, 2, 3])
            block = f.wait_height(1)
            assert block.header.height == 1
            # seen commit persisted
            assert f.store.load_seen_commit(1).is_commit()
        finally:
            f.stop()

    def test_nil_precommits_go_to_next_round(self):
        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            f.proposal_block_id()
            nil = BlockID(b"", PartSetHeader.zero())
            # everyone prevotes+precommits nil -> next round, same height
            f.inject_votes(VOTE_TYPE_PREVOTE, nil, [1, 2, 3])
            f.inject_votes(VOTE_TYPE_PRECOMMIT, nil, [1, 2, 3])
            deadline = time.time() + 10
            while time.time() < deadline:
                rs = f.cs.get_round_state()
                if rs.round >= 1:
                    break
                time.sleep(0.01)
            assert f.cs.get_round_state().round >= 1
            assert f.cs.get_round_state().height == 1
        finally:
            f.stop()


class TestLocking:
    def test_lock_held_against_different_block_next_round(self):
        """Once locked by a polka, we must keep prevoting the locked
        block in later rounds (reference TestLockNoPOL essence)."""
        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            bid = f.proposal_block_id()
            # polka for our block at round 0 -> we lock
            f.inject_votes(VOTE_TYPE_PREVOTE, bid, [1, 2, 3])
            f.wait_event(ev.EVENT_LOCK)
            rs = f.cs.get_round_state()
            assert rs.locked_round == 0
            assert rs.locked_block.hash() == bid.hash
            # our own precommit is for the locked block
            pc = f.cs.votes.precommits(0).get_by_address(f.privs[0].address)
            assert pc is not None and pc.block_id.hash == bid.hash
            # drive to round 1 with nil precommits from others
            nil = BlockID(b"", PartSetHeader.zero())
            f.inject_votes(VOTE_TYPE_PRECOMMIT, nil, [1, 2, 3])
            deadline = time.time() + 10
            while time.time() < deadline and f.cs.get_round_state().round < 1:
                time.sleep(0.01)
            # in round 1 we must have prevoted the LOCKED block again
            deadline = time.time() + 10
            pv = None
            while time.time() < deadline:
                pvs = f.cs.votes.prevotes(1)
                pv = pvs.get_by_address(f.privs[0].address) if pvs else None
                if pv is not None:
                    break
                time.sleep(0.01)
            assert pv is not None, "no round-1 prevote from locked validator"
            assert pv.block_id.hash == bid.hash
        finally:
            f.stop()

    def test_unlock_on_nil_polka(self):
        """A +2/3 nil-prevote polka in a later round releases the lock
        (reference TestLockPOLUnlock essence)."""
        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            bid = f.proposal_block_id()
            f.inject_votes(VOTE_TYPE_PREVOTE, bid, [1, 2, 3])
            f.wait_event(ev.EVENT_LOCK)
            nil = BlockID(b"", PartSetHeader.zero())
            f.inject_votes(VOTE_TYPE_PRECOMMIT, nil, [1, 2, 3])
            deadline = time.time() + 10
            while time.time() < deadline and f.cs.get_round_state().round < 1:
                time.sleep(0.01)
            # round 1: others polka nil -> we must unlock and precommit nil
            f.inject_votes(VOTE_TYPE_PREVOTE, nil, [1, 2, 3], round_=1)
            f.wait_event(ev.EVENT_UNLOCK)
            rs = f.cs.get_round_state()
            assert rs.locked_block is None and rs.locked_round == -1
        finally:
            f.stop()


class TestProposalHeartbeat:
    def test_heartbeats_fire_while_waiting_for_txs(self):
        """No-empty-blocks mode: the validator emits signed heartbeats
        while the chain idles, sequence increments, signature verifies
        (reference consensus/state.go:686,707-738)."""
        cfg = ConsensusConfig.test_config()
        cfg.create_empty_blocks = False
        cfg.proposal_heartbeat_interval = 0.05
        f = Fixture(n_vals=1, config=cfg)
        hbs: "queue.Queue" = queue.Queue()
        f.cs.event_switch.add_listener(
            "hb-test", ev.EVENT_PROPOSAL_HEARTBEAT, hbs.put
        )
        f.cs.start()
        try:
            first = hbs.get(timeout=5)
            second = hbs.get(timeout=5)
            assert second.sequence > first.sequence
            assert first.validator_address == f.privs[0].address
            assert first.validator_index == 0
            assert f.privs[0].pub_key.verify(
                first.sign_bytes(CHAIN), first.signature
            )
            # consensus is genuinely idle: no block was created
            assert f.cs.height == 1
            assert f.cs.step == RoundStepType.NEW_ROUND
        finally:
            f.cs.stop()

    def test_heartbeat_ws_event_json(self):
        """WS subscribers see heartbeats: the event payload serializes
        to the compact JSON view (height/round/sequence/validator)."""
        from tendermint_tpu.rpc.websocket import event_to_json
        from tendermint_tpu.types.heartbeat import Heartbeat

        hb = Heartbeat(
            validator_address=b"\xab" * 20,
            validator_index=1,
            height=5,
            round=0,
            sequence=3,
            signature=b"\x01" * 64,
        )
        out = event_to_json(ev.EVENT_PROPOSAL_HEARTBEAT, hb)
        assert out == {
            "event": ev.EVENT_PROPOSAL_HEARTBEAT,
            "height": 5,
            "round": 0,
            "sequence": 3,
            "validator": (b"\xab" * 20).hex(),
        }

    def test_heartbeat_message_round_trip(self):
        from tendermint_tpu.consensus.reactor import (
            ProposalHeartbeatMessage,
            decode_message,
        )
        from tendermint_tpu.types.heartbeat import Heartbeat

        hb = Heartbeat(
            validator_address=b"\x11" * 20,
            validator_index=3,
            height=7,
            round=1,
            sequence=42,
            signature=b"\x22" * 64,
        )
        msg = decode_message(ProposalHeartbeatMessage(hb).encode())
        assert isinstance(msg, ProposalHeartbeatMessage)
        assert msg.heartbeat == hb


class TestWALRecovery:
    def test_wal_records_and_endheight(self, tmp_path):
        wal_path = str(tmp_path / "cs.wal")
        f = Fixture(n_vals=1, wal_path=wal_path)
        try:
            f.cs.start()
            f.wait_height(2)
        finally:
            f.stop()
        recs = list(WAL.iter_records(wal_path))
        heights = [r.height for r in recs if isinstance(r, EndHeightMessage)]
        assert 1 in heights and 2 in heights
        votes = [r for r in recs if isinstance(r, MsgRecord) and isinstance(r.msg, Vote)]
        assert votes, "own votes must be WAL'd"

    def test_poisoned_wal_does_not_brick_restart(self, tmp_path):
        """Inputs are WAL'd BEFORE validation, so an invalid peer vote can
        be on disk; replay must tolerate it like the live loop does
        (reference replay.go logs-and-continues) instead of raising out of
        start() on every restart."""
        wal_path = str(tmp_path / "cs.wal")
        db, store_db = MemDB(), MemDB()
        f = Fixture(n_vals=1, wal_path=wal_path, db=db, store_db=store_db)
        try:
            f.cs.start()
            f.wait_height(2)
        finally:
            f.stop()
        from tendermint_tpu.state import load_state

        state = load_state(db)
        h0 = state.last_block_height
        # poison: garbage-signature vote for the in-progress height,
        # appended as if a peer sent it just before the crash
        bad = Vote(
            validator_address=f.privs[0].address,
            validator_index=0,
            height=h0 + 1,
            round=0,
            timestamp=time.time_ns(),
            type=VOTE_TYPE_PREVOTE,
            block_id=BlockID(b"", PartSetHeader.zero()),
            signature=b"\x01" * 64,
        )
        w = WAL(wal_path)
        w.save(MsgRecord(bad, "badpeer"))
        w.close()
        conns = local_client_creator(KVStoreApp())()
        from tendermint_tpu.state.execution import exec_commit_block

        store = BlockStore(store_db)
        for h in range(1, h0 + 1):
            exec_commit_block(conns.consensus, store.load_block(h))
        cs2 = ConsensusState(
            config=ConsensusConfig.test_config(),
            state=state,
            app_conn=conns.consensus,
            block_store=store,
            priv_validator=f.privs[0],
            wal_path=wal_path,
            ticker=TimeoutTicker(),
        )
        got = queue.Queue()
        cs2.event_switch.add_listener("t", ev.EVENT_NEW_BLOCK, lambda d: got.put(d))
        cs2.start()  # must NOT raise on the poisoned record
        try:
            data = got.get(timeout=10)
            assert data.block.header.height == h0 + 1
        finally:
            cs2.stop()

    def test_restart_resumes_from_wal_and_store(self, tmp_path):
        wal_path = str(tmp_path / "cs.wal")
        db, store_db = MemDB(), MemDB()
        f = Fixture(n_vals=1, wal_path=wal_path, db=db, store_db=store_db)
        try:
            f.cs.start()
            f.wait_height(2)
        finally:
            f.stop()
        # restart on the same dbs + WAL; must pick up after last ENDHEIGHT
        from tendermint_tpu.state import load_state

        state = load_state(db)
        h0 = state.last_block_height
        f2 = Fixture.__new__(Fixture)
        Fixture.__init__(f2, n_vals=1, wal_path=wal_path, db=db, store_db=store_db)
        # __init__ created a fresh genesis state; rebuild cs from saved state
        f2.stop()
        conns = local_client_creator(KVStoreApp())()
        # replay chain into the fresh app (handshake's job; done manually here)
        from tendermint_tpu.state.execution import exec_commit_block

        store = BlockStore(store_db)
        for h in range(1, h0 + 1):
            exec_commit_block(conns.consensus, store.load_block(h))
        # real ticker: if the pre-crash node signed a proposal that never
        # hit the WAL, the privval refuses to re-sign it (reference
        # `types/priv_validator.go:249-251` — proposals include time and
        # can be lost); the node then recovers via the round-1 timeout
        # path, which needs real timeouts to fire.
        cs2 = ConsensusState(
            config=ConsensusConfig.test_config(),
            state=state,
            app_conn=conns.consensus,
            block_store=store,
            priv_validator=f.privs[0],
            wal_path=wal_path,
            ticker=TimeoutTicker(),
        )
        got = queue.Queue()
        cs2.event_switch.add_listener(
            "t", ev.EVENT_NEW_BLOCK, lambda d: got.put(d)
        )
        cs2.start()
        try:
            data = got.get(timeout=10)
            assert data.block.header.height == h0 + 1
        finally:
            cs2.stop()


class CountingVerifier:
    """Host verifier that records every verify_batch size."""

    def __init__(self):
        from tendermint_tpu.services import HostBatchVerifier

        self._inner = HostBatchVerifier()
        self.calls = []

    def verify_batch(self, triples):
        self.calls.append(len(triples))
        return self._inner.verify_batch(triples)


class TestVoteStormBatchDrain:
    def test_storm_verifies_as_one_batch(self):
        """A backlog of same-(height, round, type) votes must be verified
        as one device batch through the accumulate->flush seam instead of
        N batch-of-one calls (VERDICT r4 weak #8, SURVEY §7 hard part 3);
        per-vote attribution is preserved — a planted bad signature still
        only rejects its own vote."""
        n = 1000
        v = CountingVerifier()
        f = Fixture(n_vals=n, verifier=v)
        try:
            # enqueue the full storm BEFORE the loop starts so it is one
            # consecutive backlog run (prevote nil, height 1, round 0)
            bad_index = None
            for i in range(1, n):  # privs[0] is the node itself
                vote = Vote(
                    validator_address=f.privs[i].address,
                    validator_index=i,
                    height=1,
                    round=0,
                    timestamp=time.time_ns(),
                    type=VOTE_TYPE_PREVOTE,
                    block_id=BlockID.zero(),
                )
                vote = f.privs[i].sign_vote(CHAIN, vote)
                if bad_index is None:
                    # corrupt the FIRST storm vote's signature
                    import dataclasses

                    bad_index = i
                    vote = dataclasses.replace(
                        vote,
                        signature=vote.signature[:8]
                        + bytes([vote.signature[8] ^ 1])
                        + vote.signature[9:],
                    )
                f.cs.add_vote(vote, peer_id=f"peer{i}")
            f.cs.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                pv = f.cs.votes.prevotes(0) if f.cs.votes else None
                if pv is not None and pv.bit_array().count() >= n - 2:
                    break
                time.sleep(0.05)
            pv = f.cs.votes.prevotes(0)
            # every good vote tallied; the corrupted one rejected
            assert pv.bit_array().count() >= n - 2
            assert pv.get_by_index(bad_index) is None
            assert pv.get_by_index(bad_index + 1) is not None
            # ONE big batched verify replaced ~n singles: the storm may
            # split across a few drains (loop races the enqueue tail, the
            # bad lane re-verifies solo) but must not degrade to singles
            big = [c for c in v.calls if c >= f.cs.VOTE_DRAIN_MIN]
            assert sum(big) >= (n - 1) * 0.9, (len(v.calls), v.calls[:10])
            assert len(v.calls) <= 20, f"{len(v.calls)} verify calls"
        finally:
            f.stop()
