"""RPC clients (HTTP + Local) and WAL ops tools."""

import json

import pytest

from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import Config
from tendermint_tpu.node import Node
from tendermint_tpu.rpc.client import HTTPClient, LocalClient, RPCClientError

pytestmark = pytest.mark.slow


@pytest.fixture()
def solo_node(tmp_path):
    home = str(tmp_path / "solo")
    cli_main(["init", "--home", home, "--chain-id", "client-test"])
    cfg = Config.test_config(home)
    cfg.base.fast_sync = False
    node = Node(cfg)
    node.start()
    yield node
    node.stop()


class TestClients:
    @pytest.mark.parametrize("kind", ["http", "local"])
    def test_client_interface(self, solo_node, kind):
        c = (
            HTTPClient(f"127.0.0.1:{solo_node.rpc_port}")
            if kind == "http"
            else LocalClient(solo_node)
        )
        res = c.broadcast_tx_commit(b"ck=cv")
        assert res["deliver_tx"]["code"] == 0
        st = c.status()
        assert st["sync_info"]["latest_block_height"] >= 1
        q = c.abci_query(data=b"ck")
        assert bytes.fromhex(q["value"]) == b"cv"
        blk = c.block(res["height"])
        assert blk["block"]["header"]["height"] == res["height"]
        assert len(c.validators()["validators"]) == 1
        assert c.net_info()["n_peers"] == 0
        with pytest.raises(RPCClientError):
            c.block(10_000)

    def test_genesis_round_trip(self, solo_node):
        c = LocalClient(solo_node)
        g = c.genesis()["genesis"]
        assert g["chain_id"] == "client-test"

    def test_abci_info_route(self, solo_node):
        """Reference `rpc/core/abci.go:36-42` ABCIInfo, route `routes.go:30`."""
        c = HTTPClient(f"127.0.0.1:{solo_node.rpc_port}")
        solo_node.wait_height(1)
        info = c.abci_info()
        assert "data" in info and "last_block_height" in info
        assert info["last_block_height"] >= 0

    def test_unconfirmed_txs_route(self, solo_node):
        """Reference UnconfirmedTxs (`rpc/core/mempool.go`, `routes.go:22`)."""
        c = LocalClient(solo_node)
        # park a tx that can't commit instantly by pausing consensus? simpler:
        # check_tx into the mempool directly, then list before the next block
        c.broadcast_tx_async(b"uk=uv")
        res = c.unconfirmed_txs()
        assert res["n_txs"] >= 0  # may already be reaped into a block
        if res["n_txs"]:
            assert b"uk=uv".hex() in res["txs"]

    def test_tx_prove_serves_valid_txproof(self, solo_node):
        """`tx?prove=true` returns an inclusion proof that validates against
        the block's data_hash (reference `rpc/core/tx.go` +
        `types/tx.go:71-112`)."""
        from tendermint_tpu.merkle.simple import SimpleProof
        from tendermint_tpu.types.tx import TxProof

        c = HTTPClient(f"127.0.0.1:{solo_node.rpc_port}")
        res = c.broadcast_tx_commit(b"pk=pv")
        assert res["deliver_tx"]["code"] == 0
        tx_hash = bytes.fromhex(res["hash"])
        got = c.tx(tx_hash, prove=True)
        assert got["height"] == res["height"]
        pj = got["proof"]
        proof = TxProof(
            root_hash=bytes.fromhex(pj["root_hash"]),
            data=bytes.fromhex(pj["data"]),
            proof=SimpleProof(
                index=int(pj["proof"]["index"]),
                total=int(pj["proof"]["total"]),
                leaf=bytes.fromhex(pj["proof"]["leaf"]),
                aunts=[bytes.fromhex(a) for a in pj["proof"]["aunts"]],
            ),
        )
        blk = c.block(res["height"])
        data_hash = bytes.fromhex(blk["block"]["header"]["data_hash"])
        assert proof.validate(data_hash)
        assert proof.data == b"pk=pv"
        # without prove, no proof key
        assert "proof" not in c.tx(tx_hash)

    def test_node_provider_feeds_light_client(self, solo_node):
        """An external light client certifies straight off a live node's
        RPC (reference certifiers/client/provider.go): NodeProvider
        fetches header+commit+valset, the Inquiring certifier verifies."""
        from tendermint_tpu.certifiers import InquiringCertifier
        from tendermint_tpu.certifiers.node_provider import NodeProvider
        from tendermint_tpu.certifiers.provider import MemProvider

        solo_node.wait_height(3)
        c = LocalClient(solo_node)
        prov = NodeProvider(c)
        latest = prov.latest_commit()
        assert latest is not None and latest.height() >= 1
        # the RPC round-trip must preserve hash integrity
        assert latest.header.validators_hash == latest.validators.hash()
        seed = prov.get_by_height(1)
        assert seed is not None and seed.height() >= 1
        cert = InquiringCertifier("client-test", seed, MemProvider(), prov)
        cert.certify(latest)

    def test_unsafe_flush_mempool_and_dial_seeds_routes(self, solo_node):
        from tendermint_tpu.rpc.core import make_routes

        solo_node.config.rpc.unsafe = True
        routes = make_routes(solo_node)
        assert routes["unsafe_flush_mempool"]() == {"result": "flushed"}
        with pytest.raises(Exception):
            routes["dial_seeds"](seeds="")
        # dialing an unreachable seed must not raise (background thread)
        routes["dial_seeds"](seeds="127.0.0.1:1")


class TestWALTools:
    def test_wal2json_and_cut(self, tmp_path, capsys, solo_node):
        solo_node.wait_height(3)
        wal = solo_node.config.wal_path()
        capsys.readouterr()  # drain fixture-setup output (init message)
        assert cli_main(["wal2json", wal]) == 0
        lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        kinds = {rec["type"] for rec in lines}
        assert "end_height" in kinds and "msg" in kinds
        heights = [r["height"] for r in lines if r["type"] == "end_height"]
        assert max(heights) >= 2

        out = str(tmp_path / "cut.wal")
        assert cli_main(["cut_wal_until", wal, "2", out]) == 0
        capsys.readouterr()
        assert cli_main(["wal2json", out]) == 0
        cut_lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert all(
            rec.get("height", 0) < 2 or rec["type"] == "round_state"
            for rec in cut_lines
        ), cut_lines


class TestUnsafeRoutes:
    def test_profiling_and_introspection(self, tmp_path):
        home = str(tmp_path / "unsafe")
        cli_main(["init", "--home", home, "--chain-id", "unsafe-test"])
        cfg = Config.test_config(home)
        cfg.base.fast_sync = False
        cfg.rpc.unsafe = True
        node = Node(cfg)
        node.start()
        try:
            c = LocalClient(node)
            assert c._call("unsafe_start_cpu_profiler", interval_ms=2)["started"]
            import time

            time.sleep(0.3)  # sampler sees the live node threads
            stopped = c._call("unsafe_stop_cpu_profiler")
            assert stopped["samples"] > 10
            assert stopped["profile"] and "where" in stopped["profile"][0]
            threads = c._call("unsafe_dump_threads")
            assert threads["count"] > 3  # consensus/ticker/rpc threads live
            assert any(v for v in threads["threads"].values())  # real stacks
        finally:
            node.stop()

    def test_unsafe_routes_gated(self, solo_node):
        c = LocalClient(solo_node)
        with pytest.raises(RPCClientError, match="unknown method"):
            c._call("unsafe_dump_threads")


class TestWebSocketSubscribe:
    def test_subscribe_receives_new_block_events(self, solo_node):
        from tendermint_tpu.rpc.client import WSClient

        ws = WSClient(f"127.0.0.1:{solo_node.rpc_port}")
        try:
            ws.subscribe("NewBlock")
            got = []
            for ev in ws.events(timeout=30):
                got.append(ev)
                if len(got) >= 2:
                    break
            assert len(got) >= 2
            assert got[0]["event"] == "NewBlock"
            assert got[1]["height"] > got[0]["height"]
            assert len(got[0]["hash"]) == 64
        finally:
            ws.close()

    def test_ws_client_reconnects_and_resubscribes(self, solo_node):
        """Kill the WS server mid-stream: the client must transparently
        redial with backoff, re-issue its subscriptions, and keep yielding
        events (reference `rpc/lib/client/ws_client.go:46-59`)."""
        from tendermint_tpu.rpc.client import WSClient
        from tendermint_tpu.rpc.core import make_routes
        from tendermint_tpu.rpc.server import RPCServer

        port = solo_node.rpc_port
        ws = WSClient(f"127.0.0.1:{port}", reconnect_base_backoff_s=0.05)
        try:
            ws.subscribe("NewBlock")
            first = list(_take(ws.events(timeout=30), 1))
            assert first and first[0]["event"] == "NewBlock"

            # bounce the whole RPC server on the same port
            solo_node.rpc.stop()
            solo_node.rpc = RPCServer(
                make_routes(solo_node),
                f"tcp://127.0.0.1:{port}",
                event_switch=solo_node.event_switch,
            )
            solo_node.rpc.start()

            # the dead conn must heal (resubscribe included) inside events()
            healed = list(_take(ws.events(timeout=30), 2))
            assert len(healed) == 2
            assert all(e["event"] == "NewBlock" for e in healed)
            assert healed[0]["height"] > first[0]["height"]
        finally:
            ws.close()

    def test_ws_client_reconnect_disabled_dies_with_conn(self, solo_node):
        from tendermint_tpu.rpc.client import WSClient
        from tendermint_tpu.rpc.core import make_routes
        from tendermint_tpu.rpc.server import RPCServer

        port = solo_node.rpc_port
        ws = WSClient(f"127.0.0.1:{port}", reconnect=False)
        try:
            ws.subscribe("NewBlock")
            solo_node.rpc.stop()
            solo_node.rpc = RPCServer(
                make_routes(solo_node),
                f"tcp://127.0.0.1:{port}",
                event_switch=solo_node.event_switch,
            )
            solo_node.rpc.start()
            # already-buffered frames may still drain, but the stream must
            # END promptly instead of healing into a live one (healing
            # would keep yielding new blocks until the 30s quiet timeout).
            # A dead conn on a non-closed client is a hard error, so the
            # caller can tell "no events" from "connection lost".
            import time as _t

            t0 = _t.monotonic()
            with pytest.raises(RPCClientError):
                list(ws.events(timeout=30))
            assert _t.monotonic() - t0 < 10
        finally:
            ws.close()

    def test_tx_event_subscription(self, solo_node):
        import threading

        from tendermint_tpu.rpc.client import HTTPClient, WSClient
        from tendermint_tpu.types.tx import tx_hash

        raw = b"ws-key=ws-val"
        ws = WSClient(f"127.0.0.1:{solo_node.rpc_port}")
        try:
            ws.subscribe(f"Tx:{tx_hash(raw).hex()}")
            c = HTTPClient(f"127.0.0.1:{solo_node.rpc_port}")
            threading.Thread(
                target=lambda: c.broadcast_tx_commit(raw), daemon=True
            ).start()
            events = list(_take(ws.events(timeout=30), 1))
            assert events and events[0]["code"] == 0
            assert bytes.fromhex(events[0]["tx"]) == raw
        finally:
            ws.close()


def _take(gen, n):
    out = []
    for item in gen:
        out.append(item)
        if len(out) >= n:
            break
    return out


class TestGRPCBroadcast:
    def test_ping_and_broadcast_tx(self, tmp_path):
        from tendermint_tpu.rpc.grpc_api import GRPCBroadcastClient

        home = str(tmp_path / "grpc")
        cli_main(["init", "--home", home, "--chain-id", "grpc-test"])
        cfg = Config.test_config(home)
        cfg.base.fast_sync = False
        cfg.rpc.grpc_laddr = "tcp://127.0.0.1:0"
        node = Node(cfg)
        node.start()
        try:
            c = GRPCBroadcastClient(f"127.0.0.1:{node.grpc.port}")
            assert c.ping()
            res = c.broadcast_tx(b"grpc-key=grpc-val")
            assert res["deliver_tx"]["code"] == 0
            assert res["height"] >= 1
            q = LocalClient(node).abci_query(data=b"grpc-key")
            assert bytes.fromhex(q["value"]) == b"grpc-val"
            c.close()
        finally:
            node.stop()
