"""P2P backend: transport, multiplexed connection, switch/reactor.

Mirrors the reference's `p2p/*_test.go` coverage: frame round-trips,
channel dispatch, peer lifecycle, incompatible/duplicate peer
rejection, error-driven peer drops, fuzzed links.
"""

import queue
import threading
import time

import pytest

from tendermint_tpu.p2p import (
    ChannelDescriptor,
    FuzzConfig,
    FuzzedEndpoint,
    MConnection,
    NodeInfo,
    Reactor,
    Switch,
    connect_switches,
    make_connected_switches,
    pipe_pair,
)
from tendermint_tpu.p2p.transport import EndpointClosed


def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class TestTransport:
    def test_pipe_roundtrip(self):
        a, b = pipe_pair()
        a.send(b"hello")
        assert b.recv(timeout=1) == b"hello"
        b.send(b"world")
        assert a.recv(timeout=1) == b"world"

    def test_close_wakes_receiver(self):
        a, b = pipe_pair()
        got = queue.Queue()

        def rx():
            try:
                b.recv()
            except EndpointClosed:
                got.put("closed")

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        time.sleep(0.05)
        a.close()
        assert got.get(timeout=2) == "closed"

    def test_fuzzed_drop_all(self):
        a, b = pipe_pair()
        fz = FuzzedEndpoint(a, FuzzConfig(prob_drop_rw=1.0, seed=1))
        fz.send(b"dropped")
        with pytest.raises(TimeoutError):
            b.recv(timeout=0.2)


class TestMConnection:
    def test_multiplex_two_channels(self):
        ea, eb = pipe_pair()
        got = queue.Queue()
        chans = [ChannelDescriptor(0x20), ChannelDescriptor(0x21)]
        ca = MConnection(ea, chans, lambda c, p: None)
        cb = MConnection(eb, chans, lambda c, p: got.put((c, p)))
        ca.start()
        cb.start()
        try:
            ca.send(0x20, b"state")
            ca.send(0x21, b"data")
            seen = {got.get(timeout=2), got.get(timeout=2)}
            assert seen == {(0x20, b"state"), (0x21, b"data")}
        finally:
            ca.stop()
            cb.stop()

    def test_ping_pong_keeps_idle_link_alive(self):
        """Idle-but-alive peers answer pings, so neither side times out
        (reference `p2p/connection.go:312-345` keepalive)."""
        ea, eb = pipe_pair()
        errs = queue.Queue()
        kw = dict(ping_interval=0.1, pong_timeout=0.2)
        ca = MConnection(ea, [ChannelDescriptor(1)], lambda c, p: None,
                         lambda e: errs.put(("a", e)), **kw)
        cb = MConnection(eb, [ChannelDescriptor(1)], lambda c, p: None,
                         lambda e: errs.put(("b", e)), **kw)
        ca.start()
        cb.start()
        try:
            time.sleep(0.8)  # several ping intervals with zero app traffic
            assert errs.empty(), f"keepalive failed: {errs.get_nowait()}"
            assert ca._running and cb._running
        finally:
            ca.stop()
            cb.stop()

    def test_dead_peer_detected_by_ping_timeout(self):
        """A peer that holds the socket open but never responds must be
        dropped after ping_interval + pong_timeout — without keepalive it
        would hold its slot until some send failed."""
        ea, eb = pipe_pair()  # eb: open but nobody home
        errs = queue.Queue()
        ca = MConnection(
            ea,
            [ChannelDescriptor(1)],
            lambda c, p: None,
            lambda e: errs.put(e),
            ping_interval=0.1,
            pong_timeout=0.15,
        )
        ca.start()
        try:
            exc = errs.get(timeout=3)
            assert isinstance(exc, TimeoutError)
            assert not ca._running
        finally:
            ca.stop()

    def test_ctrl_channel_id_reserved(self):
        from tendermint_tpu.p2p.connection import CTRL_CHANNEL

        ea, _eb = pipe_pair()
        with pytest.raises(ValueError, match="reserved"):
            MConnection(ea, [ChannelDescriptor(CTRL_CHANNEL)], lambda c, p: None)

    def test_on_error_fires_on_link_death(self):
        ea, eb = pipe_pair()
        errs = queue.Queue()
        ca = MConnection(ea, [ChannelDescriptor(1)], lambda c, p: None)
        cb = MConnection(
            eb, [ChannelDescriptor(1)], lambda c, p: None, lambda e: errs.put(e)
        )
        ca.start()
        cb.start()
        try:
            ca.stop()  # closes the shared pipe
            errs.get(timeout=2)  # cb notices
        finally:
            cb.stop()


class EchoReactor(Reactor):
    CH = 0x77

    def __init__(self):
        super().__init__()
        self.received = queue.Queue()
        self.peers_added = []
        self.peers_removed = []

    def get_channels(self):
        return [ChannelDescriptor(self.CH)]

    def add_peer(self, peer):
        self.peers_added.append(peer)

    def remove_peer(self, peer, reason):
        self.peers_removed.append((peer, reason))

    def receive(self, chan_id, peer, payload):
        if payload == b"explode":
            raise RuntimeError("bad message")
        self.received.put((peer.id, payload))


def _mk_switch(i, chain_id="p2p-test"):
    sw = Switch(NodeInfo(node_id=f"node{i}", moniker=f"m{i}", chain_id=chain_id))
    sw.add_reactor("echo", EchoReactor())
    return sw


class TestSwitch:
    def test_two_switches_exchange(self):
        s0, s1 = make_connected_switches(2, _mk_switch)
        try:
            r0: EchoReactor = s0.reactor("echo")
            r1: EchoReactor = s1.reactor("echo")
            assert s0.n_peers() == 1 and s1.n_peers() == 1
            s0.broadcast(EchoReactor.CH, b"ping")
            peer_id, payload = r1.received.get(timeout=2)
            assert (peer_id, payload) == ("node0", b"ping")
            s1.peers()[0].send(EchoReactor.CH, b"pong")
            assert r0.received.get(timeout=2) == ("node1", b"pong")
        finally:
            s0.stop()
            s1.stop()

    def test_chain_mismatch_rejected(self):
        s0 = _mk_switch(0)
        s1 = _mk_switch(1, chain_id="other-chain")
        s0.start()
        s1.start()
        try:
            with pytest.raises(ValueError, match="chain_id mismatch"):
                connect_switches(s0, s1)
            assert s0.n_peers() == 0
        finally:
            s0.stop()
            s1.stop()

    def test_duplicate_peer_rejected(self):
        s0, s1 = make_connected_switches(2, _mk_switch)
        try:
            with pytest.raises(ValueError, match="duplicate"):
                connect_switches(s0, s1)
        finally:
            s0.stop()
            s1.stop()

    def test_peer_filter_rejects_before_registration(self):
        """ABCI-style peer admission (reference node/node.go:259-281):
        a non-None filter verdict rejects the peer pre-registration."""
        s0 = _mk_switch(0)
        s1 = _mk_switch(1)
        s1.peer_filter = (
            lambda info, addr: "blocklisted" if info.node_id == "node0" else None
        )
        s0.start()
        s1.start()
        try:
            with pytest.raises(ValueError, match="peer filtered: blocklisted"):
                connect_switches(s0, s1)
            assert s1.n_peers() == 0
            # the filter runs per-peer: an allowed node still connects
            s2 = _mk_switch(2)
            s2.start()
            try:
                connect_switches(s2, s1)
                assert s1.n_peers() == 1
            finally:
                s2.stop()
        finally:
            s0.stop()
            s1.stop()

    def test_raising_reactor_drops_peer(self):
        s0, s1 = make_connected_switches(2, _mk_switch)
        try:
            r1: EchoReactor = s1.reactor("echo")
            s0.broadcast(EchoReactor.CH, b"explode")
            wait_until(lambda: s1.n_peers() == 0, msg="peer dropped")
            assert len(r1.peers_removed) == 1
        finally:
            s0.stop()
            s1.stop()

    def test_full_mesh(self):
        switches = make_connected_switches(4, _mk_switch)
        try:
            for s in switches:
                assert s.n_peers() == 3
            switches[0].broadcast(EchoReactor.CH, b"hello-all")
            for s in switches[1:]:
                r: EchoReactor = s.reactor("echo")
                assert r.received.get(timeout=2) == ("node0", b"hello-all")
        finally:
            for s in switches:
                s.stop()


class TestPersistentAddrMatching:
    """node.Node persistent-peer adoption: id-pinned (`id@host:port`)
    matching, with bare-host match only when unambiguous (several NAT'd
    peers can share one IP — mapping the wrong one redials the wrong
    address after a drop)."""

    @staticmethod
    def _node_with_peers(peers):
        from tendermint_tpu.node.node import Node

        class _FakeSwitch:
            def __init__(self, ps):
                self._ps = ps

            def peers(self):
                return self._ps

        n = Node.__new__(Node)
        n._peer_addr = {}
        n.switch = _FakeSwitch(peers)
        return n

    @staticmethod
    def _peer(pid, listen_addr="", remote_addr=""):
        class _P:
            id = pid

        p = _P()
        p.node_info = NodeInfo(node_id=pid, moniker=pid, chain_id="c", listen_addr=listen_addr)
        p.remote_addr = remote_addr
        return p

    def test_split_persistent_addr(self):
        from tendermint_tpu.node.node import Node

        assert Node._split_persistent_addr("abc123@10.0.0.1:46656") == (
            "abc123",
            "10.0.0.1:46656",
        )
        assert Node._split_persistent_addr("10.0.0.1:46656") == (None, "10.0.0.1:46656")
        assert Node._split_persistent_addr("tcp://10.0.0.1:46656") == (
            None,
            "tcp://10.0.0.1:46656",
        )

    def test_id_pinned_match_beats_host_match(self):
        right = self._peer("idA", remote_addr="10.0.0.1:5555")
        wrong = self._peer("idB", remote_addr="10.0.0.1:6666")  # same NAT host
        n = self._node_with_peers([wrong, right])
        n._adopt_inbound_persistent("idA@10.0.0.1:46656")
        assert n._peer_addr == {"idA": "idA@10.0.0.1:46656"}

    def test_pinned_id_absent_adopts_nothing(self):
        other = self._peer("idB", remote_addr="10.0.0.1:6666")
        n = self._node_with_peers([other])
        n._adopt_inbound_persistent("idA@10.0.0.1:46656")
        assert n._peer_addr == {}

    def test_bare_host_match_requires_single_candidate(self):
        a = self._peer("idA", remote_addr="10.0.0.1:5555")
        b = self._peer("idB", remote_addr="10.0.0.1:6666")
        n = self._node_with_peers([a, b])
        n._adopt_inbound_persistent("10.0.0.1:46656")
        assert n._peer_addr == {}  # ambiguous: refuse to guess
        n2 = self._node_with_peers([a])
        n2._adopt_inbound_persistent("10.0.0.1:46656")
        assert n2._peer_addr == {"idA": "10.0.0.1:46656"}

    def test_listen_addr_equality_match(self):
        a = self._peer("idA", listen_addr="10.0.0.1:46656", remote_addr="10.9.9.9:1")
        b = self._peer("idB", remote_addr="10.0.0.1:2")
        n = self._node_with_peers([b, a])
        n._adopt_inbound_persistent("10.0.0.1:46656")
        assert n._peer_addr == {"idA": "10.0.0.1:46656"}
