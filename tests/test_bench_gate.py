"""Bench regression gate (`tools/bench_gate.py`): rule evaluation,
exit codes, and — slow-marked — the committed BENCH_hotpath.json
holding every committed floor in tools/bench_floors.json."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.bench_gate import check_rule, main, resolve, run_gate

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCH = {
    "metric": "x",
    "detail": {
        "verify": {"host": {"verifies_per_s": 3000.0, "p99_ms": 2.4}},
        "flags": {"ok": True, "bad": False},
        "rows": [{"v": 1}, {"v": 2}],
        "absent_section": None,
    },
}


class TestResolve:
    def test_dict_walk(self):
        assert resolve(BENCH, "detail.verify.host.p99_ms") == (True, 2.4)

    def test_list_index(self):
        assert resolve(BENCH, "detail.rows.1.v") == (True, 2)

    def test_missing(self):
        assert resolve(BENCH, "detail.verify.device.p99_ms")[0] is False
        assert resolve(BENCH, "nope")[0] is False
        assert resolve(BENCH, "metric.deeper")[0] is False


class TestRules:
    def test_min_max(self):
        ok, _ = check_rule(
            BENCH, {"path": "detail.verify.host.verifies_per_s", "min": 1000}
        )
        assert ok == "ok"
        st, msg = check_rule(
            BENCH, {"path": "detail.verify.host.p99_ms", "max": 1.0}
        )
        assert st == "fail" and "ceiling" in msg
        st, _ = check_rule(
            BENCH, {"path": "detail.verify.host.verifies_per_s", "min": 5000}
        )
        assert st == "fail"

    def test_truthy(self):
        assert check_rule(BENCH, {"path": "detail.flags.ok", "truthy": True})[0] == "ok"
        assert (
            check_rule(BENCH, {"path": "detail.flags.bad", "truthy": True})[0]
            == "fail"
        )

    def test_missing_vs_optional(self):
        rule = {"path": "detail.absent_section.speedup", "min": 1}
        assert check_rule(BENCH, rule)[0] == "fail"
        assert check_rule(BENCH, {**rule, "optional": True})[0] == "skip"
        # null value behaves like missing
        assert (
            check_rule(
                BENCH, {"path": "detail.absent_section", "min": 1, "optional": True}
            )[0]
            == "skip"
        )

    def test_non_numeric_fails(self):
        assert check_rule(BENCH, {"path": "metric", "min": 1})[0] == "fail"

    def test_run_gate_aggregates(self):
        ok, lines = run_gate(
            BENCH,
            {
                "floors": [
                    {"path": "detail.verify.host.p99_ms", "max": 50},
                    {"path": "detail.verify.host.p99_ms", "max": 1},
                ]
            },
        )
        assert not ok
        assert "1 regressed" in lines[-1]


class TestCLI:
    def _write(self, tmp_path, bench, floors):
        bp = tmp_path / "bench.json"
        fp = tmp_path / "floors.json"
        bp.write_text(json.dumps(bench))
        fp.write_text(json.dumps(floors))
        return str(bp), str(fp)

    def test_exit_codes(self, tmp_path, capsys):
        bp, fp = self._write(
            tmp_path,
            BENCH,
            {"floors": [{"path": "detail.verify.host.p99_ms", "max": 50}]},
        )
        assert main(["--bench", bp, "--floors", fp]) == 0
        bp2, fp2 = self._write(
            tmp_path,
            BENCH,
            {"floors": [{"path": "detail.verify.host.p99_ms", "max": 1}]},
        )
        assert main(["--bench", bp2, "--floors", fp2]) == 1
        assert main(["--bench", str(tmp_path / "nope.json"), "--floors", fp]) == 2
        capsys.readouterr()


@pytest.mark.slow
class TestCommittedFloors:
    def test_committed_bench_holds_committed_floors(self, capsys):
        """The CI gate itself: the repo's BENCH_hotpath.json must hold
        every floor in tools/bench_floors.json — a perf PR reseeding the
        bench below a floor has to touch the floors file too, visibly."""
        rc = main(
            [
                "--bench",
                os.path.join(_REPO, "BENCH_hotpath.json"),
                "--floors",
                os.path.join(_REPO, "tools", "bench_floors.json"),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0, f"bench gate regressed:\n{out}"
