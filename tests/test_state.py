"""State, execution, ABCI apps, DB backends, tx indexing, fail points."""

import os
import subprocess
import sys

import pytest

from tendermint_tpu.abci.apps import CounterApp, KVStoreApp, PersistentKVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.abci.types import CodeType
from tendermint_tpu.db.kv import MemDB, SQLiteDB
from tendermint_tpu.state import load_state, make_genesis_state
from tendermint_tpu.state.state import ABCIResponses
from tendermint_tpu.state.txindex import KVTxIndexer
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.tx import tx_hash

from tests.helpers import ChainSim, make_genesis


class TestDB:
    def test_memdb_roundtrip_and_prefix_iterate(self):
        db = MemDB()
        db.set(b"a:1", b"x")
        db.set(b"a:2", b"y")
        db.set(b"b:1", b"z")
        assert db.get(b"a:1") == b"x"
        assert db.get(b"missing") is None
        assert list(db.iterate(b"a:")) == [(b"a:1", b"x"), (b"a:2", b"y")]
        db.delete(b"a:1")
        assert not db.has(b"a:1")

    def test_sqlite_roundtrip_persistence(self, tmp_path):
        path = str(tmp_path / "kv.db")
        db = SQLiteDB(path)
        db.set(b"k1", b"v1")
        db.set_sync(b"k2", b"v2")
        db.delete(b"k1")
        db.close()
        db2 = SQLiteDB(path)
        assert db2.get(b"k1") is None
        assert db2.get(b"k2") == b"v2"
        assert list(db2.iterate()) == [(b"k2", b"v2")]
        db2.close()


class TestApps:
    def test_kvstore(self):
        app = KVStoreApp()
        conns = local_client_creator(app)()
        assert conns.mempool.check_tx_async(b"name=satoshi").is_ok
        conns.consensus.deliver_tx_async(b"name=satoshi")
        h1 = conns.consensus.commit_sync().data
        assert h1 != b""
        q = conns.query.query_sync("/key", b"name")
        assert q.value == b"satoshi"
        conns.consensus.deliver_tx_async(b"other=thing")
        assert conns.consensus.commit_sync().data != h1

    def test_counter_serial_nonce(self):
        app = CounterApp(serial=True)
        conns = local_client_creator(app)()
        assert conns.consensus.deliver_tx_async(b"\x00").is_ok
        res = conns.consensus.deliver_tx_async(b"\x00")
        assert res.code == CodeType.BAD_NONCE
        assert conns.consensus.deliver_tx_async(b"\x01").is_ok
        assert conns.mempool.check_tx_async(b"\x00").code == CodeType.BAD_NONCE
        assert conns.mempool.check_tx_async(b"\x05").is_ok  # check allows >=

    def test_persistent_kvstore_reload(self):
        db = MemDB()
        app = PersistentKVStoreApp(db)
        app.deliver_tx(b"k=v")
        app.end_block(3)
        app.commit()
        app2 = PersistentKVStoreApp(db)
        assert app2.info().last_block_height == 3
        assert app2.query("/key", b"k").value == b"v"


class TestGenesisState:
    def test_make_save_load_roundtrip(self):
        db = MemDB()
        gen, _ = make_genesis(4)
        st = make_genesis_state(db, gen)
        assert st.last_block_height == 0
        assert st.validators.size() == 4
        assert st.last_validators.size() == 0
        st.save()
        st2 = load_state(db)
        assert st2 is not None and st2.equals(st)

    def test_load_missing_returns_none(self):
        assert load_state(MemDB()) is None


class TestApplyBlock:
    def test_three_heights_with_real_commits(self):
        sim = ChainSim(n_vals=4)
        sim.advance(txs=[b"a=1"])
        assert sim.state.last_block_height == 1
        app_hash_1 = sim.state.app_hash
        assert app_hash_1 != b""
        sim.advance(txs=[b"b=2"])
        app_hash_2 = sim.state.app_hash
        assert app_hash_2 != app_hash_1
        sim.advance()
        assert sim.state.last_block_height == 3
        assert sim.state.app_hash == app_hash_2  # height-3 block had no txs
        assert sim.state.last_validators.hash() == sim.state.validators.hash()
        # state persisted each height
        st = load_state(sim.db)
        assert st.last_block_height == 3

    def test_validate_block_rejections(self):
        sim = ChainSim(n_vals=4)
        sim.advance()
        block, ps = sim.make_next_block()
        block.header.height += 1  # wrong height
        from tendermint_tpu.state import validate_block

        with pytest.raises(ValidationError, match="wrong height"):
            validate_block(sim.state, block, None)

        block2, _ = sim.make_next_block()
        block2.header.app_hash = b"\x01" * 20
        block2.header.data_hash = b""  # force refill? header already filled
        with pytest.raises(ValidationError, match="app_hash"):
            validate_block(sim.state, block2, None)

    def test_bad_last_commit_signature_rejected(self):
        sim = ChainSim(n_vals=4)
        sim.advance()
        # tamper a commit signature, then try to apply height 2
        block, ps = sim.make_next_block()
        pc = block.last_commit.precommits[0]
        object.__setattr__(pc, "signature", bytes(64))
        block.header.last_commit_hash = b""
        block.fill_header()
        from tendermint_tpu.state import validate_block

        with pytest.raises(ValidationError):
            validate_block(sim.state, block, None)

    def test_tx_indexer_batch(self):
        db = MemDB()
        sim = ChainSim(n_vals=4)
        idx = KVTxIndexer(db)
        sim.advance(txs=[b"k1=v1", b"k2=v2"], tx_indexer=idx)
        tr = idx.get(tx_hash(b"k1=v1"))
        assert tr is not None and tr.height == 1 and tr.index == 0
        assert idx.get(b"\x00" * 20) is None


class TestValidatorChanges:
    def test_end_block_diffs_rotate_in(self):
        from tendermint_tpu.crypto.keys import gen_priv_key

        db = MemDB()
        sim = ChainSim(n_vals=4, app=PersistentKVStoreApp(db))
        new_key = gen_priv_key(b"\x99" * 32)
        hash_before = sim.state.validators.hash()
        sim.advance(txs=[b"val:" + new_key.pub_key.data.hex().encode() + b"/7"])
        # the diff applies to the validator set for the next height
        assert sim.state.validators.size() == 5
        assert sim.state.last_validators.hash() == hash_before
        assert sim.state.last_height_validators_changed == 2
        _, v = sim.state.validators.get_by_address(new_key.pub_key.address)
        assert v is not None and v.voting_power == 7

    def test_historical_validators_with_compression(self):
        sim = ChainSim(n_vals=3)
        for _ in range(4):
            sim.advance()
        vs1 = sim.state.load_validators(1)
        vs4 = sim.state.load_validators(4)
        assert vs1.hash() == vs4.hash() == sim.state.validators.hash()
        with pytest.raises(ValidationError):
            sim.state.load_validators(99)


class TestABCIResponses:
    def test_save_load(self):
        sim = ChainSim(n_vals=4)
        sim.advance(txs=[b"x=y"])
        res = sim.state.load_abci_responses(1)
        assert res is not None
        assert res.height == 1 and len(res.deliver_tx) == 1
        assert res.deliver_tx[0].is_ok
        assert sim.state.load_abci_responses(9) is None


class TestFailPoints:
    def test_fail_index_kills_process_at_each_point(self, tmp_path):
        script = tmp_path / "crash.py"
        script.write_text(
            "import sys; sys.path.insert(0, %r)\n"
            "from tests.helpers import ChainSim\n"
            "sim = ChainSim(n_vals=2)\n"
            "sim.advance(txs=[b'a=1'])\n"
            "print('SURVIVED')\n" % os.getcwd()
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # 4 fail points in apply_block: indices 0..3 must die, 4 survives
        for idx in range(4):
            env["FAIL_TEST_INDEX"] = str(idx)
            p = subprocess.run(
                [sys.executable, str(script)], env=env, capture_output=True, text=True
            )
            assert p.returncode == 1, (idx, p.stdout, p.stderr)
            assert "SURVIVED" not in p.stdout
        env["FAIL_TEST_INDEX"] = "4"
        p = subprocess.run(
            [sys.executable, str(script)], env=env, capture_output=True, text=True
        )
        assert p.returncode == 0 and "SURVIVED" in p.stdout, p.stderr
