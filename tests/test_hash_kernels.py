"""Device hash kernels cross-validated bit-exactly against hashlib."""

import hashlib

import numpy as np
import pytest

from tendermint_tpu.merkle import simple_hash_from_byte_slices
from tendermint_tpu.ops import (
    merkle_root_device,
    ripemd160_batch_jax,
    sha256_batch_jax,
    sha256_digest_bytes,
    sha512_batch_jax,
)
from tendermint_tpu.ops.padding import (
    digests_to_bytes_be,
    digests_to_bytes_le,
    pad_ripemd160,
    pad_sha256,
    pad_sha512,
)

# Device-kernel compiles dominate runtime (~minutes per bucket shape);
# excluded from the default selection (pytest.ini addopts) — run with
#   pytest -m kernel
# kernel suites are also 'slow': tier-1 CI selects -m 'not slow' (which
# overrides the ini's 'not kernel' default), and these compile device
# kernels on XLA:CPU for minutes. 'pytest -m kernel' still runs them.
pytestmark = [pytest.mark.kernel, pytest.mark.slow]

LENGTHS = [0, 1, 3, 31, 32, 55, 56, 63, 64, 65, 111, 112, 127, 128, 129, 200, 300]


def msgs_of_lengths():
    rng = np.random.RandomState(7)
    return [rng.bytes(n) for n in LENGTHS]


def test_sha256_matches_hashlib():
    msgs = msgs_of_lengths()
    blocks, counts = pad_sha256(msgs)
    got = digests_to_bytes_be(np.asarray(sha256_batch_jax(blocks, counts)))
    want = [hashlib.sha256(m).digest() for m in msgs]
    assert got == want


def test_sha256_convenience_api():
    msgs = [b"", b"abc", b"x" * 1000]
    assert sha256_digest_bytes(msgs) == [hashlib.sha256(m).digest() for m in msgs]


def test_sha512_matches_hashlib():
    msgs = msgs_of_lengths()
    blocks, counts = pad_sha512(msgs)
    out = np.asarray(sha512_batch_jax(blocks, counts))  # (B, 16) u32
    got = digests_to_bytes_be(out)
    want = [hashlib.sha512(m).digest() for m in msgs]
    assert got == want


def test_ripemd160_matches_hashlib():
    msgs = msgs_of_lengths()
    blocks, counts = pad_ripemd160(msgs)
    out = np.asarray(ripemd160_batch_jax(blocks, counts))
    got = digests_to_bytes_le(out)
    want = []
    for m in msgs:
        h = hashlib.new("ripemd160")
        h.update(m)
        want.append(h.digest())
    assert got == want


def test_mixed_length_bucketing_masks_correctly():
    # same batch, very different block counts: masking must freeze short msgs
    msgs = [b"a", b"b" * 500, b"c" * 10, b"d" * 250]
    blocks, counts = pad_sha256(msgs, max_blocks=16)
    got = digests_to_bytes_be(np.asarray(sha256_batch_jax(blocks, counts)))
    assert got == [hashlib.sha256(m).digest() for m in msgs]


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 6, 7, 8, 9, 11, 16, 17, 33, 100, 255, 256])
def test_merkle_root_device_matches_host(n):
    items = [f"leaf-{i}".encode() * (i % 5 + 1) for i in range(n)]
    assert merkle_root_device(items) == simple_hash_from_byte_slices(items)


def test_merkle_empty():
    assert merkle_root_device([]) == b""


def test_merkle_device_large_pow2():
    items = [i.to_bytes(8, "big") for i in range(1024)]
    assert merkle_root_device(items) == simple_hash_from_byte_slices(items)


def test_merkle_forest_mixed_tree_sizes():
    # one launch, trees of different leaf counts and leaf lengths
    from tendermint_tpu.ops.merkle_kernel import merkle_roots_forest

    trees = [
        [b"a", b"bb", b"ccc"],
        [f"x{i}".encode() * (i % 3 + 1) for i in range(17)],
        [b"solo"],
        [i.to_bytes(4, "big") for i in range(64)],
    ]
    got = merkle_roots_forest(trees)
    assert got == [simple_hash_from_byte_slices(t) for t in trees]


def test_65k_tx_block_data_hash_from_device_tree():
    """BASELINE config 4 as a production path: a 65k-tx block built through
    the device TreeHasher gets a data_hash bit-identical to the host tree
    (reference hot spot `types/tx.go:33-46` via `types/block.go:173-188`)."""
    from tendermint_tpu.services.hasher import TreeHasher
    from tendermint_tpu.types import BlockID, Txs
    from tendermint_tpu.types.block import Block, Commit

    txs = Txs(b"tx-%06d" % i for i in range(65536))
    dev = TreeHasher(backend="device")  # 65k clears the default threshold
    block = Block.make_block(
        height=1,
        chain_id="kernel-chain",
        txs=txs,
        last_commit=Commit.empty(),
        last_block_id=BlockID.zero(),
        time=1,
        validators_hash=b"\x01" * 20,
        app_hash=b"",
        hasher=dev,
    )
    assert block.header.data_hash == simple_hash_from_byte_slices(list(txs))
    # and the validation side accepts it through the same device path
    block.validate_basic(dev)


@pytest.mark.parametrize("n", [1, 2, 3, 7, 16, 33, 100])
def test_ripemd_merkle_tree_matches_host(n):
    """Device RIPEMD-160 tree (the reference's bit-compat variant,
    `docs/specification/merkle.rst:52-90`) vs the host tree."""
    items = [f"rleaf-{i}".encode() * (i % 4 + 1) for i in range(n)]
    assert merkle_root_device(items, "ripemd160") == simple_hash_from_byte_slices(
        items, "ripemd160"
    )


def test_ripemd_forest_mixed_tree_sizes():
    from tendermint_tpu.ops.merkle_kernel import merkle_roots_forest

    trees = [
        [b"a", b"bb", b"ccc"],
        [f"r{i}".encode() * (i % 3 + 1) for i in range(9)],
        [b"solo"],
    ]
    got = merkle_roots_forest(trees, "ripemd160")
    assert got == [simple_hash_from_byte_slices(t, "ripemd160") for t in trees]
