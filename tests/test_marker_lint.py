"""The collection-time marker lint (tests/conftest.py): a `kernel` mark
without a `slow` mark would let tier-1's `-m 'not slow'` selection pull
~20-minute XLA:CPU kernel compiles into the fast lane — the lint fails
collection before that can land (ROADMAP tier-1 note)."""

import pytest

from tests.conftest import lint_kernel_marks


class _FakeItem:
    def __init__(self, nodeid, marks):
        self.nodeid = nodeid
        self._marks = set(marks)

    def get_closest_marker(self, name):
        return object() if name in self._marks else None


def test_kernel_without_slow_is_flagged():
    items = [
        _FakeItem("tests/test_a.py::test_compiles", {"kernel"}),
        _FakeItem("tests/test_b.py::test_ok", {"kernel", "slow"}),
        _FakeItem("tests/test_c.py::test_plain", set()),
        _FakeItem("tests/test_d.py::test_slow_only", {"slow"}),
    ]
    assert lint_kernel_marks(items) == ["tests/test_a.py::test_compiles"]


def test_clean_suite_passes():
    assert lint_kernel_marks([_FakeItem("x::t", {"kernel", "slow"})]) == []


def test_collection_hook_raises_usage_error():
    import tests.conftest as conftest

    bad = [_FakeItem("tests/test_a.py::test_compiles", {"kernel"})]
    with pytest.raises(pytest.UsageError, match="missing the slow mark"):
        conftest.pytest_collection_modifyitems(None, bad)
