"""The collection-time lints (tests/conftest.py): a `kernel` mark
without a `slow` mark would let tier-1's `-m 'not slow'` selection pull
~20-minute XLA:CPU kernel compiles into the fast lane, and a
`tendermint_*` metric name used in code but absent from the
`telemetry/metrics.py` catalog means an invariant/dashboard queries a
series that will never exist — both fail collection before landing."""

import pytest

from tests.conftest import lint_kernel_marks, lint_metric_catalog


class _FakeItem:
    def __init__(self, nodeid, marks):
        self.nodeid = nodeid
        self._marks = set(marks)

    def get_closest_marker(self, name):
        return object() if name in self._marks else None


def test_kernel_without_slow_is_flagged():
    items = [
        _FakeItem("tests/test_a.py::test_compiles", {"kernel"}),
        _FakeItem("tests/test_b.py::test_ok", {"kernel", "slow"}),
        _FakeItem("tests/test_c.py::test_plain", set()),
        _FakeItem("tests/test_d.py::test_slow_only", {"slow"}),
    ]
    assert lint_kernel_marks(items) == ["tests/test_a.py::test_compiles"]


def test_clean_suite_passes():
    assert lint_kernel_marks([_FakeItem("x::t", {"kernel", "slow"})]) == []


def test_collection_hook_raises_usage_error():
    import tests.conftest as conftest

    bad = [_FakeItem("tests/test_a.py::test_compiles", {"kernel"})]
    with pytest.raises(pytest.UsageError, match="missing the slow mark"):
        conftest.pytest_collection_modifyitems(None, bad)


class TestMetricCatalogLint:
    def test_current_tree_is_clean(self):
        assert lint_metric_catalog() == []

    def test_unregistered_name_is_flagged(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            'NAME = "tendermint_not_in_the_catalog_total"\n'
        )
        off = lint_metric_catalog(roots=[tmp_path])
        assert len(off) == 1
        assert off[0].endswith(":tendermint_not_in_the_catalog_total")

    def test_registered_names_and_suffixes_pass(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            'A = "tendermint_verify_seconds"\n'
            'B = "tendermint_verify_seconds_count"\n'  # exposition suffix
            'C = "tendermint_batcher_coalesce_factor"\n'
            'PKG = "tendermint_tpu.services"\n'  # package path, not a metric
        )
        assert lint_metric_catalog(roots=[tmp_path]) == []


class TestSpanCatalogLint:
    def test_current_tree_is_clean(self):
        from tests.conftest import lint_span_catalog

        assert lint_span_catalog() == []

    def test_uncataloged_span_name_is_flagged(self, tmp_path):
        from tests.conftest import lint_span_catalog

        (tmp_path / "mod.py").write_text(
            'TRACER.span("not.in.catalog")\n'
            'TRACER.add("mempool.admission", 0.0, 1.0)\n'  # cataloged
            'tracer.add("local.variable.skipped", 0.0, 1.0)\n'  # not TRACER
        )
        off = lint_span_catalog(roots=[tmp_path])
        assert len(off) == 1
        assert off[0].endswith(":not.in.catalog")
