"""Gossip observatory unit coverage (telemetry/gossiplog.py).

Four properties the observatory must hold:

* the static classification tables mirror the reactors' own wire
  constants (drift fails here, not as silent "other" classification);
* the rollup tables are bounded no matter what a byzantine peer sends
  (peer-row overflow folds, first-seen heights evict oldest-first);
* accounting never changes the wire — frames are byte-identical with
  the hook installed vs sampled out (the golden-bytes test), and an
  instrumented switch interoperates with a TENDERMINT_TPU_GOSSIPLOG=0
  one;
* the redundancy-factor arithmetic (delivered/useful) is exact.
"""

import queue
import threading
import time

from tendermint_tpu.p2p import (
    ChannelDescriptor,
    MConnection,
    NodeInfo,
    Reactor,
    Switch,
    connect_switches,
    pipe_pair,
)
from tendermint_tpu.telemetry.gossiplog import (
    CHANNEL_NAMES,
    KIND_TAGS,
    GossipRollup,
    channel_name,
    classify,
    enabled_from_env,
)


def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class TestKindTablesMatchReactors:
    """The one static table in gossiplog.py vs the constants every
    reactor actually writes on the wire. A new message type or a
    renumbered channel must show up here, or it gets classified
    "other" in every dump."""

    def test_channel_ids_match_reactors(self):
        from tendermint_tpu.blockchain.reactor import BLOCKCHAIN_CHANNEL
        from tendermint_tpu.consensus.reactor import (
            DATA_CHANNEL,
            STATE_CHANNEL,
            VOTE_CHANNEL,
            VOTE_SET_BITS_CHANNEL,
        )
        from tendermint_tpu.evidence.reactor import EVIDENCE_CHANNEL
        from tendermint_tpu.lightclient.reactor import LIGHTCLIENT_CHANNEL
        from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL
        from tendermint_tpu.p2p.connection import CTRL_CHANNEL
        from tendermint_tpu.p2p.pex import PEX_CHANNEL
        from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL

        expected = {
            PEX_CHANNEL: "pex",
            STATE_CHANNEL: "cns_state",
            DATA_CHANNEL: "cns_data",
            VOTE_CHANNEL: "cns_vote",
            VOTE_SET_BITS_CHANNEL: "cns_votebits",
            MEMPOOL_CHANNEL: "mempool",
            EVIDENCE_CHANNEL: "evidence",
            BLOCKCHAIN_CHANNEL: "blockchain",
            STATESYNC_CHANNEL: "statesync",
            LIGHTCLIENT_CHANNEL: "lightclient",
            CTRL_CHANNEL: "ctrl",
        }
        assert CHANNEL_NAMES == expected

    def test_message_tags_match_reactors(self):
        import tendermint_tpu.blockchain.reactor as bc
        import tendermint_tpu.consensus.reactor as cns
        import tendermint_tpu.evidence.reactor as evr
        import tendermint_tpu.lightclient.reactor as lc
        import tendermint_tpu.mempool.reactor as mp
        import tendermint_tpu.p2p.connection as conn
        import tendermint_tpu.p2p.pex as pex
        import tendermint_tpu.statesync.reactor as ss

        expected = {
            pex.PEX_CHANNEL: {
                pex._MSG_REQUEST: "pex_request",
                pex._MSG_ADDRS: "pex_addrs",
            },
            cns.STATE_CHANNEL: {
                cns._MSG_NEW_ROUND_STEP: "new_round_step",
                cns._MSG_COMMIT_STEP: "commit_step",
                cns._MSG_HAS_VOTE: "has_vote",
                cns._MSG_VOTE_SET_MAJ23: "vote_set_maj23",
                cns._MSG_PROPOSAL_HEARTBEAT: "proposal_heartbeat",
            },
            cns.DATA_CHANNEL: {
                cns._MSG_PROPOSAL: "proposal",
                cns._MSG_PROPOSAL_POL: "proposal_pol",
                cns._MSG_BLOCK_PART: "block_part",
            },
            cns.VOTE_CHANNEL: {cns._MSG_VOTE: "vote"},
            cns.VOTE_SET_BITS_CHANNEL: {
                cns._MSG_VOTE_SET_BITS: "vote_set_bits"
            },
            mp.MEMPOOL_CHANNEL: {mp._MSG_TX: "tx"},
            evr.EVIDENCE_CHANNEL: {evr._MSG_EVIDENCE_LIST: "evidence_list"},
            bc.BLOCKCHAIN_CHANNEL: {
                bc._MSG_BLOCK_REQUEST: "block_request",
                bc._MSG_BLOCK_RESPONSE: "block_response",
                bc._MSG_NO_BLOCK: "no_block",
                bc._MSG_STATUS_REQUEST: "status_request",
                bc._MSG_STATUS_RESPONSE: "status_response",
            },
            ss.STATESYNC_CHANNEL: {
                ss._MSG_SNAPSHOTS_REQUEST: "snapshots_request",
                ss._MSG_SNAPSHOTS_RESPONSE: "snapshots_response",
                ss._MSG_CHUNK_REQUEST: "chunk_request",
                ss._MSG_CHUNK_RESPONSE: "chunk_response",
                ss._MSG_NO_CHUNK: "no_chunk",
                ss._MSG_COMMIT_REQUEST: "commit_request",
                ss._MSG_COMMIT_RESPONSE: "commit_response",
            },
            lc.LIGHTCLIENT_CHANNEL: {
                lc._MSG_FC_REQUEST: "fc_request",
                lc._MSG_FC_RESPONSE: "fc_response",
                lc._MSG_FC_SUBSCRIBE: "fc_subscribe",
                lc._MSG_FC_ANNOUNCE: "fc_announce",
            },
            conn.CTRL_CHANNEL: {
                conn._PING[0]: "ping",
                conn._PONG[0]: "pong",
            },
        }
        assert KIND_TAGS == expected

    def test_kind_vocabulary_is_cataloged(self):
        """Every kind the classifier can emit must be a pre-seeded label
        value of tendermint_gossip_msgs_total (bounded cardinality by
        construction)."""
        from tendermint_tpu.telemetry.metrics import (
            GOSSIP_CHANNELS,
            GOSSIP_KINDS,
        )

        kinds = {k for tags in KIND_TAGS.values() for k in tags.values()}
        assert kinds | {"other"} == set(GOSSIP_KINDS)
        names = set(CHANNEL_NAMES.values())
        assert names | {"other"} == set(GOSSIP_CHANNELS)

    def test_classify_unknowns_stay_bounded(self):
        assert classify(0x22, b"\x06rest") == "vote"
        assert classify(0x22, b"\x07rest") == "other"  # unknown tag
        assert classify(0x99, b"\x01") == "other"  # unknown channel
        assert classify(0x22, b"") == "other"  # empty payload
        assert channel_name(0x30) == "mempool"
        assert channel_name(0x99) == "other"


class TestRollupBounds:
    def test_peer_rows_fold_into_overflow(self):
        g = GossipRollup(enabled=True)
        for i in range(GossipRollup.MAX_PEERS + 10):
            g.record(f"peer{i}", "recv", 0x22, b"\x06v", 64)
        snap = g.snapshot()
        assert len(snap["peers"]) == GossipRollup.MAX_PEERS + 1
        over = snap["peers"][GossipRollup._OVERFLOW]
        assert over["cns_vote/vote/recv"] == [10, 640]
        # aggregates still see every frame
        assert snap["channels"]["cns_vote"]["recv_msgs"] == (
            GossipRollup.MAX_PEERS + 10
        )

    def test_first_seen_evicts_oldest_height(self):
        g = GossipRollup(enabled=True)
        for h in range(1, GossipRollup.MAX_FIRST_HEIGHTS + 3):
            g.first_seen("vote", h, 0, 0)
        snap = g.snapshot()
        heights = {int(k.split("/")[1]) for k in snap["first_seen"]}
        assert len(heights) == GossipRollup.MAX_FIRST_HEIGHTS
        assert min(heights) == 3  # 1 and 2 evicted
        # older than the whole retained window: dropped, no eviction
        g.first_seen("vote", 1, 0, 0)
        assert len(g.snapshot()["first_seen"]) == len(snap["first_seen"])

    def test_first_seen_per_height_cap(self):
        g = GossipRollup(enabled=True)
        g.MAX_FIRST_PER_HEIGHT = 4
        for i in range(10):
            g.first_seen("vote", 5, 0, i)
        assert len(g.snapshot()["first_seen"]) == 4

    def test_first_seen_earliest_stamp_wins(self):
        g = GossipRollup(enabled=True)
        g.first_seen("vote", 5, 0, 1)
        t0 = g.snapshot()["first_seen"]["vote/5/0/1"]
        time.sleep(0.02)
        g.first_seen("vote", 5, 0, 1)  # re-delivery: no-op
        assert g.snapshot()["first_seen"]["vote/5/0/1"] == t0

    def test_record_is_thread_safe(self):
        g = GossipRollup(enabled=True)

        def pump(pid):
            for _ in range(500):
                g.record(pid, "recv", 0x30, b"\x01tx", 32)

        threads = [
            threading.Thread(target=pump, args=(f"p{i}",)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert g.snapshot()["channels"]["mempool"]["recv_msgs"] == 2000


class TestDisabledRollup:
    def test_disabled_is_a_noop(self):
        g = GossipRollup(enabled=False)
        g.record("p", "recv", 0x22, b"\x06v", 64)
        g.redundant("vote", 64)
        g.first_seen("vote", 1, 0, 0)
        snap = g.snapshot()
        assert snap["enabled"] is False
        assert snap["peers"] == {}
        assert snap["redundant"] == {}
        assert snap["first_seen"] == {}
        assert g.headline() == {"enabled": False}
        assert g.redundancy_factors() == {}

    def test_env_knob(self, monkeypatch):
        monkeypatch.delenv("TENDERMINT_TPU_GOSSIPLOG", raising=False)
        assert enabled_from_env() is True
        monkeypatch.setenv("TENDERMINT_TPU_GOSSIPLOG", "0")
        assert enabled_from_env() is False
        assert GossipRollup().enabled is False
        monkeypatch.setenv("TENDERMINT_TPU_GOSSIPLOG", "1")
        assert GossipRollup().enabled is True


class TestRedundancyFactors:
    def test_delivered_over_useful(self):
        g = GossipRollup(enabled=True)
        for _ in range(10):
            g.record("p", "recv", 0x22, b"\x06v", 64)
        for _ in range(4):
            g.redundant("vote", 64)
        # 10 delivered, 4 were dups -> 6 useful -> 10/6
        assert g.redundancy_factors()["vote"] == round(10 / 6, 3)

    def test_wire_kind_join_for_evidence(self):
        """Redundancy is counted as "evidence" at the pool, but the wire
        kind is "evidence_list" — the factor must join the two."""
        g = GossipRollup(enabled=True)
        for _ in range(4):
            g.record("p", "recv", 0x38, b"\x01ev", 100)
        g.redundant("evidence", 100)
        assert g.redundancy_factors()["evidence"] == round(4 / 3, 3)

    def test_fallback_without_wire_traffic(self):
        """Dedup'd adds with no recv accounting (e.g. rollup attached
        mid-run) still report: factor = dups on top of one useful."""
        g = GossipRollup(enabled=True)
        g.redundant("tx", 32)
        g.redundant("tx", 32)
        assert g.redundancy_factors()["tx"] == 3.0

    def test_headline_names_top_waste(self):
        g = GossipRollup(enabled=True)
        g.record("p", "recv", 0x21, b"\x05part", 4096)
        g.redundant("vote", 64)
        g.redundant("block_part", 4096)
        g.redundant("block_part", 4096)
        h = g.headline()
        assert h["top_redundant_kind"] == "block_part"
        assert h["top_redundant_msgs"] == 2
        assert h["hottest_channel"] == "cns_data"
        assert h["hottest_channel_bytes"] == 4096


class _Tap:
    """Endpoint wrapper that records every raw wire write, unmodified."""

    def __init__(self, inner):
        self._inner = inner
        self.frames = []

    def send(self, data):
        self.frames.append(bytes(data))
        self._inner.send(data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestGoldenBytes:
    """Accounting observes frames; it must NEVER change them. The same
    send schedule produces byte-identical wire traffic with the
    on_traffic hook installed vs sampled out (None)."""

    PAYLOADS = [
        (0x22, b"\x06" + b"v" * 80),
        (0x21, b"\x05" + b"p" * 200),
        (0x30, b"\x01" + b"t" * 33),
        (0x22, b"\x06" + b"w" * 80),
    ]

    def _pump(self, on_traffic):
        ea, eb = pipe_pair()
        tap = _Tap(ea)
        got = queue.Queue()
        chans = [
            ChannelDescriptor(0x21),
            ChannelDescriptor(0x22),
            ChannelDescriptor(0x30),
        ]
        ca = MConnection(tap, chans, lambda c, p: None,
                         ping_interval=0, on_traffic=on_traffic)
        cb = MConnection(eb, chans, lambda c, p: got.put((c, p)),
                         ping_interval=0)
        ca.start()
        cb.start()
        try:
            for chan, payload in self.PAYLOADS:
                ca.send(chan, payload)
                # serialize sends so the frame order is deterministic
                assert got.get(timeout=2) == (chan, payload)
        finally:
            ca.stop()
            cb.stop()
        return tap.frames

    def test_frames_byte_identical_with_accounting(self):
        g = GossipRollup(enabled=True)
        hook = lambda d, c, p, n: g.record("peer", d, c, p, n)  # noqa: E731
        instrumented = self._pump(hook)
        plain = self._pump(None)
        assert instrumented == plain
        # and the hook really saw every frame, sized as-on-the-wire
        snap = g.snapshot()
        assert snap["kinds"]["vote"]["send_msgs"] == 2
        assert snap["kinds"]["block_part"]["send_msgs"] == 1
        assert snap["kinds"]["tx"]["send_msgs"] == 1
        wire_bytes = sum(len(f) for f in instrumented)
        counted = sum(
            st["send_bytes"] for st in snap["channels"].values()
        )
        assert counted == wire_bytes

    def test_build_frame_ignores_gossip_env(self, monkeypatch):
        from tendermint_tpu.p2p.connection import build_frame

        monkeypatch.setenv("TENDERMINT_TPU_GOSSIPLOG", "1")
        on = build_frame(0x22, b"\x06payload")
        monkeypatch.setenv("TENDERMINT_TPU_GOSSIPLOG", "0")
        off = build_frame(0x22, b"\x06payload")
        assert on == off


class _Echo(Reactor):
    def __init__(self, chan_id):
        super().__init__()
        self.chan_id = chan_id
        self.got = queue.Queue()

    def get_channels(self):
        return [ChannelDescriptor(self.chan_id)]

    def receive(self, chan_id, peer, data):
        self.got.put(bytes(data))


class TestInterop:
    def test_instrumented_and_sampled_out_switches_interop(self, monkeypatch):
        """A TENDERMINT_TPU_GOSSIPLOG=0 node and an instrumented node
        speak the same protocol: traffic flows both ways, the
        instrumented side counts it, the sampled-out side counts
        nothing (and pays nothing: its peers get no hook)."""
        monkeypatch.setenv("TENDERMINT_TPU_GOSSIPLOG", "0")
        plain = Switch(NodeInfo("p" * 40, "plain", "interop"))
        monkeypatch.setenv("TENDERMINT_TPU_GOSSIPLOG", "1")
        inst = Switch(NodeInfo("i" * 40, "inst", "interop"))
        assert plain.gossip.enabled is False
        assert inst.gossip.enabled is True
        plain.ping_interval = inst.ping_interval = 0
        er_p = plain.add_reactor("echo", _Echo(0x22))
        er_i = inst.add_reactor("echo", _Echo(0x22))
        plain.start()
        inst.start()
        try:
            pp, pi = connect_switches(plain, inst)
            assert pp.send(0x22, b"\x06from-plain")
            assert pi.send(0x22, b"\x06from-inst")
            assert er_i.got.get(timeout=2) == b"\x06from-plain"
            assert er_p.got.get(timeout=2) == b"\x06from-inst"
            wait_until(
                lambda: inst.gossip.snapshot()["kinds"]
                .get("vote", {})
                .get("recv_msgs", 0)
                >= 1,
                msg="instrumented recv accounting",
            )
            snap = inst.gossip.snapshot()
            assert snap["kinds"]["vote"]["send_msgs"] >= 1
            assert "p" * 40 in snap["peers"]
            assert plain.gossip.snapshot()["peers"] == {}
        finally:
            plain.stop()
            inst.stop()
