"""Structured logging + flow-rate monitoring (reference tmlibs/log,
tmlibs/flowrate)."""

import io
import logging
import time

from tendermint_tpu.utils.flowrate import Monitor
from tendermint_tpu.utils.log import kv, logger, setup_logging


class TestLogging:
    def test_per_module_levels(self):
        buf = io.StringIO()
        setup_logging("state:info,consensus:debug,*:error", stream=buf)
        kv(logger("state"), logging.INFO, "state info")  # emitted
        kv(logger("state"), logging.DEBUG, "state debug")  # filtered
        kv(logger("consensus"), logging.DEBUG, "cs debug")  # emitted
        kv(logger("p2p"), logging.INFO, "p2p info")  # filtered (default error)
        kv(logger("p2p"), logging.ERROR, "p2p err")  # emitted
        out = buf.getvalue()
        assert "state info" in out and "state debug" not in out
        assert "cs debug" in out
        assert "p2p info" not in out and "p2p err" in out
        # reconfigure tightens previously-loosened modules
        buf2 = io.StringIO()
        setup_logging("*:error", stream=buf2)
        kv(logger("consensus"), logging.DEBUG, "now filtered")
        assert "now filtered" not in buf2.getvalue()

    def test_kv_format(self):
        buf = io.StringIO()
        setup_logging("blockchain:info,*:error", stream=buf)
        kv(
            logger("blockchain"),
            logging.INFO,
            "fast-sync progress",
            height=42,
            blocks_per_s=7.5,
        )
        line = buf.getvalue().strip()
        assert 'module=blockchain msg="fast-sync progress"' in line
        assert "height=42" in line and "blocks_per_s=7.5" in line
        assert line.startswith("ts=")


class TestFlowrate:
    def test_totals_and_rate(self):
        m = Monitor(window_s=0.05)
        for _ in range(10):
            m.update(1000)
        assert m.total == 10_000
        time.sleep(0.08)
        assert m.rate > 0

    def test_throttle_caps_rate(self):
        m = Monitor(limit_bytes_per_s=50_000, window_s=0.2)
        start = time.monotonic()
        sent = 0
        while sent < 25_000:
            m.throttle()
            m.update(5_000)
            sent += 5_000
        elapsed = time.monotonic() - start
        # 25kB at 50kB/s needs ~0.5s; unthrottled this loop is ~instant
        assert elapsed >= 0.3, f"throttle too weak: {elapsed:.3f}s"

    def test_unlimited_never_sleeps(self):
        m = Monitor()
        start = time.monotonic()
        for _ in range(1000):
            m.throttle()
            m.update(10_000)
        assert time.monotonic() - start < 0.5
