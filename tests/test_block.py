import time

import pytest

from tendermint_tpu.types import Block, BlockID, Commit, Data, Txs, ValidationError
from tests.helpers import CHAIN_ID, make_block_id, make_commit, make_validators


def make_test_block(height=2, n_txs=5):
    vs, privs = make_validators(4)
    last_bid = make_block_id(b"prev")
    last_commit = make_commit(vs, privs, height=height - 1, round_=0, block_id=last_bid)
    txs = Txs(f"tx-{i}".encode() for i in range(n_txs))
    return Block.make_block(
        height=height,
        chain_id=CHAIN_ID,
        txs=txs,
        last_commit=last_commit,
        last_block_id=last_bid,
        time=time.time_ns(),
        validators_hash=vs.hash(),
        app_hash=b"\x01" * 32,
    )


def test_block_hash_stable_and_nonempty():
    b = make_test_block()
    h1, h2 = b.hash(), b.hash()
    assert h1 == h2 and len(h1) == 32


def test_header_hash_changes_with_fields():
    b1, b2 = make_test_block(), make_test_block()
    b2.header.app_hash = b"\x02" * 32
    assert b1.hash() != b2.hash()


def test_validate_basic_ok():
    make_test_block().validate_basic()


def test_validate_basic_catches_num_txs():
    b = make_test_block()
    b.header.num_txs = 99
    with pytest.raises(ValidationError):
        b.validate_basic()


def test_validate_basic_catches_data_tamper():
    b = make_test_block()
    b.data.txs[0] = b"evil"
    with pytest.raises(ValidationError):
        b.validate_basic()


def test_encode_decode_roundtrip():
    b = make_test_block()
    b2 = Block.decode(b.encode())
    assert b2.hash() == b.hash()
    assert b2.data.txs == b.data.txs
    assert b2.last_commit.block_id == b.last_commit.block_id
    b2.validate_basic()


def test_part_set_roundtrip():
    b = make_test_block(n_txs=200)
    ps = b.make_part_set(part_size=512)
    assert ps.total > 1
    assert Block.decode(ps.assemble()).hash() == b.hash()


def test_commit_validate_basic():
    vs, privs = make_validators(4)
    bid = make_block_id()
    c = make_commit(vs, privs, height=3, round_=1, block_id=bid)
    c.validate_basic()
    assert c.height() == 3 and c.round() == 1
    assert c.bit_array().num_set() == 4


def test_empty_commit_for_height_1():
    b = make_test_block(height=2)
    assert Commit.empty().size() == 0
