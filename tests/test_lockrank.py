"""utils/lockrank.py: the runtime half of tmlint.

Covers rank-inversion detection (with both threads' stacks in the
report), order-graph cycle detection across 3 threads, a deliberate
ABBA deadlock caught WITHOUT hanging, same-rank lane seq ordering,
Condition integration, zero-overhead pass-through when disabled, and
the acceptance scenario: a deliberate inversion injected against live
mempool admission traffic is detected and reported while the suite
keeps running."""

import threading
import time

import pytest

from tendermint_tpu.utils import lockrank
from tendermint_tpu.utils.lockrank import (
    LockRankViolation,
    RankedLock,
    RankedRLock,
    ranked_lock,
    ranked_rlock,
)


@pytest.fixture(autouse=True)
def _clean_lockrank():
    """Isolate the process-global graph/violations per test, and drain
    deliberate violations so the conftest guard doesn't re-fail us."""
    lockrank.reset()
    lockrank.set_raise(None)
    yield
    lockrank.set_raise(None)
    lockrank.reset()


class TestRankInversion:
    def test_ascending_order_is_clean(self):
        lo = RankedLock("mempool.wal")
        hi = RankedLock("mempool.counter")
        with lo:
            with hi:
                pass
        assert lockrank.violations() == []

    def test_inversion_recorded_with_stack(self):
        lo = RankedLock("mempool.wal")
        hi = RankedLock("mempool.counter")
        with hi:
            with lo:
                pass
        vs = lockrank.violations()
        assert len(vs) == 1
        assert vs[0]["kind"] == "rank_inversion"
        assert "mempool.wal" in vs[0]["message"]
        assert "mempool.counter" in vs[0]["message"]
        report = lockrank.render_report()
        assert "test_inversion_recorded_with_stack" in report

    def test_inversion_report_carries_both_threads_stacks(self):
        lo = RankedLock("mempool.wal")
        hi = RankedLock("mempool.counter")

        def legal():
            with lo:
                with hi:
                    # tmlint: disable=L002 -- test scaffolding: widens the window in which the legal edge is observed first
                    time.sleep(0.01)

        t = threading.Thread(target=legal, name="legal-order")
        t.start()
        t.join()
        with hi:
            with lo:
                pass
        (v,) = lockrank.violations()
        labels = [s["label"] for s in v["stacks"]]
        assert any("this acquire" in lb or "acquire of" in lb for lb in labels)
        # the legal direction was observed first on the other thread
        assert any("first observed" in lb for lb in labels)
        threads = {s["thread"] for s in v["stacks"]}
        assert "legal-order" in threads

    def test_same_rank_ascending_seq_allowed(self):
        lanes = [RankedRLock("mempool.lane", seq=i) for i in range(4)]
        for ln in lanes:  # index order, like Mempool.lock()
            ln.acquire()
        for ln in reversed(lanes):
            ln.release()
        assert lockrank.violations() == []

    def test_same_rank_descending_seq_flagged(self):
        lanes = [RankedRLock("mempool.lane", seq=i) for i in range(2)]
        lanes[1].acquire()
        lanes[0].acquire()
        lanes[0].release()
        lanes[1].release()
        vs = lockrank.violations()
        assert len(vs) == 1 and vs[0]["kind"] == "rank_inversion"

    def test_rlock_reentry_is_not_a_violation(self):
        mtx = RankedRLock("consensus.state")
        with mtx:
            with mtx:
                pass
        assert lockrank.violations() == []

    def test_unranked_locks_skip_rank_check(self):
        a = RankedLock("custom.a", rank=None)
        b = RankedLock("custom.b", rank=None)
        with b:
            with a:
                pass
        assert lockrank.violations() == []


class TestCycleDetection:
    def test_two_lock_aba_cycle(self):
        a = RankedLock("custom.a", rank=None)
        b = RankedLock("custom.b", rank=None)
        done = threading.Event()

        def t1():
            with a:
                with b:
                    done.set()

        th = threading.Thread(target=t1, name="ab-thread")
        th.start()
        th.join()
        with b:
            with a:  # closes the cycle in the order graph — no contention
                pass
        vs = lockrank.violations()
        assert len(vs) == 1
        assert vs[0]["kind"] == "cycle"
        assert "custom.a" in vs[0]["message"]
        threads = {s["thread"] for s in vs[0]["stacks"]}
        assert "ab-thread" in threads  # both sides' stacks present
        assert len(threads) >= 2

    def test_three_thread_three_lock_cycle(self):
        a = RankedLock("custom.a", rank=None)
        b = RankedLock("custom.b", rank=None)
        c = RankedLock("custom.c", rank=None)

        def nest(outer, inner, name):
            def run():
                with outer:
                    with inner:
                        pass

            t = threading.Thread(target=run, name=name)
            t.start()
            t.join()

        nest(a, b, "t-ab")
        nest(b, c, "t-bc")
        nest(c, a, "t-ca")  # a->b->c->a
        vs = [v for v in lockrank.violations() if v["kind"] == "cycle"]
        assert len(vs) == 1
        msg = vs[0]["message"]
        for name in ("custom.a", "custom.b", "custom.c"):
            assert name in msg
        threads = {s["thread"] for s in vs[0]["stacks"]}
        assert {"t-ab", "t-bc"} <= threads  # prior edges' stacks included

    def test_no_false_cycle_on_diamond(self):
        a = RankedLock("custom.a", rank=None)
        b = RankedLock("custom.b", rank=None)
        c = RankedLock("custom.c", rank=None)
        for outer, inner in ((a, b), (a, c), (b, c)):
            with outer:
                with inner:
                    pass
        assert lockrank.violations() == []


class TestAbbaRegression:
    def test_abba_deadlock_caught_without_hanging(self):
        """Two threads take A/B in opposite orders with real contention.
        In raise mode the second order raises BEFORE blocking, so the
        would-be deadlock terminates with a report instead of hanging."""
        lockrank.set_raise(True)
        a = RankedLock("mempool.wal")  # rank 48
        b = RankedLock("mempool.counter")  # rank 52
        a_held = threading.Event()
        release_a = threading.Event()
        outcomes = {}

        def legal():
            with a:
                a_held.set()
                release_a.wait(5)  # hold A while the bad thread runs
                with b:
                    outcomes["legal"] = "ok"

        def inverted():
            a_held.wait(5)
            b.acquire()  # rank 52 first...
            try:
                try:
                    a.acquire()  # ...then 48: raises pre-block
                    a.release()
                    outcomes["inverted"] = "acquired"
                except LockRankViolation:
                    outcomes["inverted"] = "caught"
            finally:
                b.release()
                release_a.set()

        t1 = threading.Thread(target=legal, name="abba-legal")
        t2 = threading.Thread(target=inverted, name="abba-inverted")
        t1.start()
        t2.start()
        t1.join(10)
        t2.join(10)
        assert not t1.is_alive() and not t2.is_alive(), "ABBA test wedged"
        assert outcomes == {"legal": "ok", "inverted": "caught"}
        assert lockrank.drain()  # the violation was also recorded


class TestConditionIntegration:
    def test_condition_wait_notify_roundtrip(self):
        cond = threading.Condition(ranked_lock("mempool.avail"))
        hits = []

        def waiter():
            with cond:
                while not hits:
                    if not cond.wait(5):
                        return
            hits.append("woke")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            hits.append("set")
            cond.notify_all()
        t.join(5)
        assert not t.is_alive()
        assert hits == ["set", "woke"]
        assert lockrank.violations() == []

    def test_condition_lock_participates_in_ranking(self):
        cond_lock = ranked_lock("mempool.avail")  # rank 30
        lane = ranked_rlock("mempool.lane")  # rank 40
        cond = threading.Condition(cond_lock)
        with cond:
            with lane:  # avail -> lane: the documented get_after order
                pass
        assert lockrank.violations() == []
        with lane:
            # tmlint: disable=L001 -- deliberate inversion: this test asserts the runtime sanitizer flags it
            with cond:  # lane -> avail: the forbidden direction
                pass
        assert any(
            v["kind"] == "rank_inversion" for v in lockrank.drain()
        )


class TestDisabledPassThrough:
    def test_factories_return_plain_locks_when_disabled(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_LOCKRANK", "0")
        lock = ranked_lock("mempool.wal")
        rlock = ranked_rlock("mempool.lane")
        assert type(lock) is type(threading.Lock())
        assert "RLock" in type(rlock).__name__
        assert not isinstance(lock, RankedLock)
        # misuse with plain locks records nothing
        hi = ranked_lock("mempool.counter")
        with hi:
            # tmlint: disable=L001 -- deliberate inversion: proves the disabled factories record nothing
            with lock:
                pass
        assert lockrank.violations() == []

    def test_factories_instrument_when_enabled(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_LOCKRANK", "1")
        assert isinstance(ranked_lock("mempool.wal"), RankedLock)
        assert isinstance(ranked_rlock("mempool.lane"), RankedRLock)


class TestMempoolAcceptance:
    """Acceptance: a deliberate inversion injected against a REAL
    mempool under concurrent admission traffic is detected and reported
    with both threads' stacks — and nothing deadlocks (nemesis-style:
    contention is real, timing is controlled)."""

    def test_injected_inversion_under_live_admissions(self):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.mempool.mempool import Mempool

        mp = Mempool(
            local_client_creator(KVStoreApp())().mempool,
            lanes=2,
            ingress_batch=False,
            signed_txs=False,
        )
        if not isinstance(mp._wal_lock, RankedLock):
            pytest.skip("lockrank disabled in this environment")
        stop = threading.Event()

        def traffic():
            i = 0
            while not stop.is_set():
                mp.check_tx(b"k%d=v" % i)  # lane -> wal -> counter (legal)
                i += 1

        t = threading.Thread(target=traffic, name="admission-traffic")
        t.start()
        time.sleep(0.05)
        # the inversion: counter (52) held while taking a lane (40)
        with mp._counter_lock:
            with mp._lanes[0].lock:
                pass
        stop.set()
        t.join(10)
        assert not t.is_alive(), "admission thread wedged"
        vs = [
            v
            for v in lockrank.drain()
            if v["kind"] == "rank_inversion"
            and "mempool.lane" in v["message"]
        ]
        assert vs, "injected inversion not detected"
        report = lockrank.render_violation(vs[0])
        assert "mempool.counter" in report
        # both sides: this test's stack plus the legal-order edge stack
        # recorded from the admission thread
        assert "test_injected_inversion_under_live_admissions" in report
        assert "admission-traffic" in report
        # the pool still works after the report
        res = mp.check_tx(b"post=ok")
        assert res.is_ok
