"""Remote ABCI over sockets AND gRPC: the process boundary (reference
`proxy/client.go:14-80` remote creators + `test/app/*_test.sh`). Both
transports run the same suite — the reference ships socket and grpc
arms of NewRemoteClientCreator."""

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.grpc_transport import ABCIGrpcServer, grpc_client_creator
from tendermint_tpu.abci.socket import ABCISocketServer, socket_client_creator
from tendermint_tpu.abci.types import Validator as ABCIValidator
from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import Config
from tendermint_tpu.node import Node
from tendermint_tpu.rpc.client import LocalClient

pytestmark = pytest.mark.slow


@pytest.fixture(params=["socket", "grpc"])
def served_app(request):
    app = KVStoreApp()
    if request.param == "socket":
        srv = ABCISocketServer(app, "tcp://127.0.0.1:0")
        creator = socket_client_creator
    else:
        srv = ABCIGrpcServer(app, "tcp://127.0.0.1:0")
        creator = grpc_client_creator
    yield app, creator(f"127.0.0.1:{srv.port}")
    srv.stop()


class TestSocketProxy:
    def test_three_connections_round_trip(self, served_app):
        app, creator = served_app
        conns = creator()
        assert conns.query.echo_sync("ping") == "ping"
        info = conns.query.info_sync()
        assert info.last_block_height == 0

        assert conns.mempool.check_tx_async(b"k=v").is_ok
        conns.mempool.flush_sync()

        conns.consensus.init_chain_sync(
            [ABCIValidator(pub_key=b"\x01" * 32, power=10)]
        )
        from tendermint_tpu.types.block import Header
        from tendermint_tpu.types.block_id import BlockID

        header = Header(
            chain_id="sock", height=1, time=1, num_txs=1,
            last_block_id=BlockID.zero(), validators_hash=b"\x02" * 32,
        )
        conns.consensus.begin_block_sync(b"\xaa" * 32, header)
        assert conns.consensus.deliver_tx_async(b"k=v").is_ok
        assert conns.consensus.end_block_sync(1) == []
        commit = conns.consensus.commit_sync()
        assert commit.is_ok and commit.data  # app hash advanced

        q = conns.query.query_sync("", b"k")
        assert q.value == b"v"

    def test_node_runs_against_remote_app(self, served_app, tmp_path):
        _, creator = served_app
        home = str(tmp_path / "remote-app-node")
        cli_main(["init", "--home", home, "--chain-id", "remote-abci"])
        cfg = Config.test_config(home)
        cfg.base.fast_sync = False
        node = Node(cfg, client_creator=creator)
        node.start()
        try:
            c = LocalClient(node)
            res = c.broadcast_tx_commit(b"remote=yes")
            assert res["deliver_tx"]["code"] == 0
            q = c.abci_query(data=b"remote")
            assert bytes.fromhex(q["value"]) == b"yes"
            assert c.status()["sync_info"]["latest_block_height"] >= 1
        finally:
            node.stop()
