"""Gossip observatory integration: the duplicate-delivery live net,
the dump/health surfaces, and the cross-node report tool.

The headline test is the ISSUE's acceptance shape in miniature: a
4-node net where chaos re-delivers every frame twice. The dedup sites
(VoteSet duplicate adds, PartSet already-have parts) swallow the
copies exactly as before — consensus output is identical across nodes,
no fork — but `tendermint_gossip_redundant_total` now *counts* them,
and the per-kind redundancy factor reads > 1.0.
"""

import json
import time
from types import SimpleNamespace

from tendermint_tpu.p2p import NodeInfo
from tendermint_tpu.telemetry import views
from tendermint_tpu.telemetry.gossiplog import GossipRollup
from tendermint_tpu.telemetry.health import _gossip_section, build_health
from tendermint_tpu.telemetry.heightlog import HeightLedger
from tendermint_tpu.testing.nemesis import Nemesis

from tools.gossip_report import build_report, load_dumps, render_text


class TestDuplicateDeliveryNet:
    def test_duplicated_links_count_redundancy_without_forking(self, tmp_path):
        """Every link delivers every frame twice (dup_prob=1.0): the
        exact duplicates hit the silent dedup sites, the redundant
        counters advance, the redundancy factor clears 1.0 — and the
        committed chain is byte-identical on every node."""
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            for i in range(4):
                for j in range(i + 1, 4):
                    net.duplicate(i, j, 1.0)
            target = max(net.heights()) + 3
            net.wait_height(target, timeout=90)

            red = {}
            for node in net.nodes:
                snap = node.switch.gossip.snapshot()
                for kind, st in snap["redundant"].items():
                    red[kind] = red.get(kind, 0) + st["msgs"]
            assert red.get("vote", 0) > 0, f"no redundant votes: {red}"
            # dup'd frames carry real bytes, and at least one node's
            # vote factor shows the 2x-delivery wastage
            factors = [
                node.switch.gossip.redundancy_factors().get("vote", 0.0)
                for node in net.nodes
            ]
            assert max(factors) > 1.0, f"factors: {factors}"

            # consensus output unaffected: no fork, and the block bytes
            # agree across all four stores at every shared height
            net.check_invariants()
            common = min(net.heights())
            for h in range(1, common + 1):
                blocks = {
                    bytes(node.store.load_block(h).encode())
                    for node in net.nodes
                }
                assert len(blocks) == 1, f"stores disagree at h{h}"


def _gossip_with_traffic() -> GossipRollup:
    g = GossipRollup(enabled=True)
    for i in range(6):
        g.record("ab" * 20, "recv", 0x22, b"\x06" + b"v" * 80, 90)
    g.record("ab" * 20, "send", 0x21, b"\x05" + b"p" * 300, 310)
    g.redundant("vote", 90)
    g.redundant("vote", 90)
    g.first_seen("vote", 7, 0, 1)
    return g


def _stub_node(gossip=None):
    ledger = HeightLedger()
    now = time.time()
    for h in (1, 2, 3):
        ledger.record(
            {"height": h, "finality_s": 0.2 if h > 1 else None, "t_commit": now}
        )
    switch = SimpleNamespace(
        n_peers=lambda: 3,
        node_info=NodeInfo("s" * 40, "stub-moniker", "stub-chain"),
    )
    if gossip is not None:
        switch.gossip = gossip
    return SimpleNamespace(
        node_id="stub",
        consensus=SimpleNamespace(
            verifier=SimpleNamespace(snapshot=lambda: {"state": "closed"}),
            fatal_error=None,
        ),
        blockchain_reactor=SimpleNamespace(fast_sync=False),
        statesync_reactor=None,
        switch=switch,
        block_store=SimpleNamespace(height=3),
        hasher=None,
        height_ledger=ledger,
    )


class TestDumpView:
    def test_gossip_view_joins_node_identity(self):
        node = _stub_node(gossip=_gossip_with_traffic())
        out = views.collect(node, ["gossip"])
        view = out["gossip"]
        assert view["node_id"] == "s" * 40
        assert view["moniker"] == "stub-moniker"
        assert view["kinds"]["vote"]["recv_msgs"] == 6
        assert view["redundant"]["vote"]["msgs"] == 2
        assert view["redundancy_factor"]["vote"] == 1.5  # 6 / (6-2)
        assert "vote/7/0/1" in view["first_seen"]

    def test_view_omitted_without_rollup(self):
        node = _stub_node(gossip=None)
        assert "gossip" not in views.collect(node, ["gossip"])

    def test_view_is_json_serializable(self):
        node = _stub_node(gossip=_gossip_with_traffic())
        json.dumps(views.collect(node, ["gossip"]))


class TestHealthSection:
    def test_headline_reported_not_folded(self):
        node = _stub_node(gossip=_gossip_with_traffic())
        h = build_health(node)
        assert h["status"] == "ok" and h["ready"]  # never folds status
        assert h["gossip"]["top_redundant_kind"] == "vote"
        assert h["gossip"]["hottest_channel"] == "cns_vote"

    def test_section_absent_when_sampled_out(self):
        assert _gossip_section(_stub_node(gossip=None)) is None
        node = _stub_node(gossip=GossipRollup(enabled=False))
        assert _gossip_section(node) is None
        h = build_health(node)
        assert "gossip" not in h


def _synthetic_view(node_id, moniker, recv_votes, red_votes, stamps):
    g = GossipRollup(enabled=True)
    for _ in range(recv_votes):
        g.record("peer" + node_id, "recv", 0x22, b"\x06v", 90)
    for _ in range(red_votes):
        g.redundant("vote", 90)
    view = g.snapshot()
    # deterministic cross-node stamps (the live path uses time.time())
    view["first_seen"] = stamps
    view["node_id"] = node_id
    view["moniker"] = moniker
    return view


class TestReportTool:
    def _views(self):
        # vote v at h5 originates on node a (t=100.0) and propagates:
        # b +30ms, c +80ms; part p reaches only b (+10ms)
        return [
            _synthetic_view("a" * 40, "node-a", 10, 4,
                            {"vote/5/0/1": 100.0, "block_part/5/0/0": 100.0}),
            _synthetic_view("b" * 40, "node-b", 10, 2,
                            {"vote/5/0/1": 100.03, "block_part/5/0/0": 100.01}),
            _synthetic_view("c" * 40, "node-c", 12, 0,
                            {"vote/5/0/1": 100.08}),
        ]

    def test_waterfall_redundancy_and_propagation(self):
        report = build_report(
            self._views(), placement=["us-east", "us-west", "eu-west"]
        )
        assert report["nodes"] == 3
        assert report["regions"] == ["us-east", "us-west", "eu-west"]
        # waterfall: 32 recv vote frames x 90B
        assert report["channels"]["cns_vote"]["recv_bytes"] == 32 * 90
        # redundancy ranking: 32 delivered, 6 dup'd -> 32/26
        vote = report["redundancy"]["vote"]
        assert vote["redundant_msgs"] == 6
        assert vote["factor"] == round(32 / 26, 3)
        # propagation: origin us-east, deltas in ms
        prop = report["propagation"]
        assert prop["us-east->us-west"]["n"] == 2  # vote + part
        assert abs(prop["us-east->us-west"]["mean_ms"] - 20.0) < 0.5
        assert abs(prop["us-east->eu-west"]["mean_ms"] - 80.0) < 0.5
        assert report["propagation_keys_merged"] == 2

    def test_verdict_names_top_waste_with_roadmap_fix(self):
        report = build_report(self._views())
        v = report["verdict"]
        assert v["top_waste_source"] == "vote_redundancy"
        assert v["cost_bytes"] == 6 * 90
        assert "item 3" in v["fix_first"]

    def test_verdict_falls_back_to_hottest_channel(self):
        g = GossipRollup(enabled=True)
        g.record("p" * 40, "recv", 0x21, b"\x05part", 5000)
        view = g.snapshot()
        report = build_report([view])
        assert report["verdict"]["top_waste_source"] == "data_bandwidth"

    def test_render_text_is_complete(self):
        report = build_report(
            self._views(), placement=["us-east", "us-west", "eu-west"]
        )
        text = render_text(report)
        assert "cns_vote" in text
        assert "vote" in text
        assert "us-east->us-west" in text or "us-east -> us-west" in text
        assert "vote_redundancy" in text

    def test_load_dumps_accepts_all_shapes(self, tmp_path):
        bare = self._views()[0]
        wrapped = {"gossip": self._views()[1]}
        rpc = {"result": {"gossip": self._views()[2]}}
        for name, payload in [
            ("bare.json", bare), ("wrapped.json", wrapped), ("rpc.json", rpc)
        ]:
            (tmp_path / name).write_text(json.dumps(payload))
        (tmp_path / "junk.json").write_text("not json {")
        loaded = load_dumps([str(tmp_path / "*.json")])
        assert len(loaded) == 3
        assert all("channels" in v and "redundant" in v for v in loaded)


class TestScenarioGrading:
    """The expect.gossip schema (docs/SCENARIOS.md) graded against a
    synthetic report — the seams scenario specs use to bound gossip
    amplification alongside finality."""

    def _graded(self, gossip_summary, gexp):
        from tendermint_tpu.testing.scenario import ScenarioRunner

        runner = ScenarioRunner.__new__(ScenarioRunner)
        report = {
            "heights": [5, 5, 5, 5],
            "failures": [],
            "finality": {},
            "gossip": gossip_summary,
        }
        spec = {
            "expect": {"min_height": 1, "gossip": gexp},
            "run": {"target_height": 1},
        }
        net = SimpleNamespace(
            check_invariants=lambda: None,
            nodes=[SimpleNamespace(running=True)] * 4,
        )
        runner._grade(net, spec, report)
        return report

    def _summary(self, **over):
        base = {
            "channel_bytes": {"cns_vote": 2_000_000, "mempool": 500_000},
            "redundant": {"vote": {"msgs": 10, "bytes": 900}},
            "redundancy_factor": {"vote": 2.0},
            "top_redundant_kind": "vote",
            "total_bytes": 2_500_000,
        }
        base.update(over)
        return base

    def test_within_bounds_passes(self):
        report = self._graded(
            self._summary(),
            {"require_counted": True, "max_redundancy": {"vote": 4.0},
             "max_channel_mbytes": {"cns_vote": 10.0}},
        )
        assert report["ok"], report["failures"]

    def test_redundancy_cap_fails(self):
        report = self._graded(
            self._summary(), {"max_redundancy": {"vote": 1.5}}
        )
        assert not report["ok"]
        assert any("redundancy vote" in f for f in report["failures"])

    def test_channel_budget_fails(self):
        report = self._graded(
            self._summary(), {"max_channel_mbytes": {"cns_vote": 1.0}}
        )
        assert not report["ok"]
        assert any("channel cns_vote" in f for f in report["failures"])

    def test_missing_rollup_fails_when_expected(self):
        report = self._graded(None, {"require_counted": True})
        assert not report["ok"]
        assert any("no rollup" in f for f in report["failures"])
