"""Telemetry subsystem: registry semantics (concurrency, histogram
bucket math, Prometheus golden exposition), the span tracer, the
`GET /metrics` route, and the registry-driven hot-path bench tool.

End-to-end coverage against a full running node (consensus phase
histograms moving, breaker series, `dump_telemetry`) lives in
`tests/test_telemetry_node.py` with the other node-composition suites.
"""

from __future__ import annotations

import json
import math
import threading
import urllib.request

import pytest

from tendermint_tpu.telemetry import REGISTRY, TRACER
from tendermint_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from tendermint_tpu.telemetry.tracer import Tracer


class TestCountersAndGauges:
    def test_counter_basics(self):
        reg = Registry()
        c = Counter("t_total", "help", registry=reg)
        assert c.value == 0.0
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_labeled_counter_children(self):
        reg = Registry()
        c = Counter("t_total", "", labelnames=("kind",), registry=reg)
        c.labels(kind="a").inc()
        c.labels("a").inc()  # positional == keyword
        c.labels(kind="b").inc(5)
        assert reg.counter_value("t_total", kind="a") == 2.0
        assert reg.counter_value("t_total", kind="b") == 5.0
        assert reg.counter_value("t_total", kind="never") == 0.0
        with pytest.raises(ValueError):
            c.inc()  # labeled family has no default child
        with pytest.raises(ValueError):
            c.labels("a", "b")  # wrong arity

    def test_duplicate_registration_rejected(self):
        reg = Registry()
        Counter("dup", "", registry=reg)
        with pytest.raises(ValueError):
            Counter("dup", "", registry=reg)

    def test_gauge_set_inc_dec(self):
        reg = Registry()
        g = Gauge("g", "", registry=reg)
        g.set(7)
        g.inc(3)
        g.dec()
        assert g.value == 9.0

    def test_gauge_callback_wins_and_survives_errors(self):
        reg = Registry()
        g = Gauge("g", "", registry=reg)
        g.set(1)
        g.set_function(lambda: 42)
        assert g.value == 42.0
        boom = {"on": False}

        def fn():
            if boom["on"]:
                raise RuntimeError("source gone")
            return 13

        g.set_function(fn)
        assert g.value == 13.0
        boom["on"] = True
        # a dead source keeps the last good value, never breaks a scrape
        assert g.value == 13.0
        assert "g 13" in reg.prometheus_text()


class TestHistogram:
    def test_bucket_math(self):
        reg = Registry()
        h = Histogram("h", "", buckets=(1, 5, 10), registry=reg)
        for v in (0.5, 1.0, 3.0, 7.0, 100.0):
            h.observe(v)
        snap = h.value
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(111.5)
        # cumulative: <=1 gets 0.5 and 1.0; <=5 adds 3.0; <=10 adds 7.0
        assert snap["buckets"] == [
            (1.0, 2),
            (5.0, 3),
            (10.0, 4),
            (math.inf, 5),
        ]

    def test_buckets_are_sorted_on_registration(self):
        reg = Registry()
        h = Histogram("h", "", buckets=(10, 1, 5), registry=reg)
        assert [b for b, _ in h.value["buckets"]] == [1.0, 5.0, 10.0, math.inf]

    def test_quantile_interpolation(self):
        reg = Registry()
        h = Histogram("h", "", buckets=(1, 2, 4), registry=reg)
        for _ in range(50):
            h.observe(0.5)
        for _ in range(50):
            h.observe(3.0)
        # p50 falls at the boundary of the first bucket
        assert h.quantile(0.5) == pytest.approx(1.0)
        # p99 interpolates inside (2, 4]
        assert 2.0 < h.quantile(0.99) <= 4.0
        empty = Histogram("h2", "", buckets=(1,), registry=reg)
        assert math.isnan(empty.quantile(0.5))

    def test_labeled_histogram(self):
        reg = Registry()
        h = Histogram("h", "", labelnames=("backend",), buckets=(1,), registry=reg)
        h.labels(backend="host").observe(0.5)
        h.labels(backend="host").observe(2.0)
        assert h.labels(backend="host").value["count"] == 2
        assert h.labels(backend="device").value["count"] == 0


class TestConcurrency:
    def test_counter_under_threads_is_exact(self):
        reg = Registry()
        c = Counter("c_total", "", labelnames=("k",), registry=reg)
        h = Histogram("lat", "", buckets=(0.5, 1.0), registry=reg)
        n_threads, per_thread = 8, 5_000

        def hammer(i):
            child = c.labels(k=str(i % 2))
            for _ in range(per_thread):
                child.inc()
                h.observe(0.25)

        threads = [
            threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = reg.counter_value("c_total", k="0") + reg.counter_value(
            "c_total", k="1"
        )
        assert total == n_threads * per_thread
        assert h.value["count"] == n_threads * per_thread


class TestPrometheusExposition:
    def test_golden_output(self):
        reg = Registry()
        c = Counter("a_total", "counts things", labelnames=("kind",), registry=reg)
        g = Gauge("b", "a gauge", registry=reg)
        h = Histogram("lat_seconds", "latency", buckets=(0.5, 1.0), registry=reg)
        c.labels(kind="x").inc(3)
        g.set(1.5)
        h.observe(0.25)
        h.observe(0.75)
        assert reg.prometheus_text() == (
            "# HELP a_total counts things\n"
            "# TYPE a_total counter\n"
            'a_total{kind="x"} 3\n'
            "# HELP b a gauge\n"
            "# TYPE b gauge\n"
            "b 1.5\n"
            "# HELP lat_seconds latency\n"
            "# TYPE lat_seconds histogram\n"
            'lat_seconds_bucket{le="0.5"} 1\n'
            'lat_seconds_bucket{le="1"} 2\n'
            'lat_seconds_bucket{le="+Inf"} 2\n'
            "lat_seconds_sum 1\n"
            "lat_seconds_count 2\n"
        )

    def test_label_and_help_escaping(self):
        reg = Registry()
        c = Counter("e_total", 'has "quotes"\nand newline', labelnames=("v",), registry=reg)
        c.labels(v='a"b\\c\nd').inc()
        text = reg.prometheus_text()
        assert '# HELP e_total has "quotes"\\nand newline\n' in text
        assert 'e_total{v="a\\"b\\\\c\\nd"} 1\n' in text

    def test_unlabeled_families_expose_zero_samples(self):
        reg = Registry()
        Counter("idle_total", "", registry=reg)
        Histogram("idle_seconds", "", buckets=(1,), registry=reg)
        text = reg.prometheus_text()
        assert "idle_total 0\n" in text
        assert "idle_seconds_count 0\n" in text

    def test_to_dict_round_trips_through_json(self):
        reg = Registry()
        h = Histogram("h", "", buckets=(1,), registry=reg)
        h.observe(0.5)
        d = json.loads(json.dumps(reg.to_dict()))
        assert d["h"]["type"] == "histogram"
        assert d["h"]["series"][0]["count"] == 1
        assert d["h"]["series"][0]["buckets"][-1][0] == "+Inf"


class TestTracer:
    def test_span_context_manager_records(self):
        tr = Tracer(capacity=8)
        with tr.span("unit.work", n=3):
            pass
        spans = tr.recent()
        assert len(spans) == 1
        assert spans[0]["name"] == "unit.work"
        assert spans[0]["attrs"]["n"] == 3
        assert spans[0]["duration_s"] >= 0

    def test_span_records_errors(self):
        tr = Tracer(capacity=8)
        with pytest.raises(RuntimeError):
            with tr.span("unit.fail"):
                raise RuntimeError("boom")
        assert tr.recent()[0]["attrs"]["error"] == "RuntimeError"

    def test_ring_capacity_and_prefix_filter(self):
        tr = Tracer(capacity=4)
        for i in range(10):
            tr.add(f"a.{i % 2}", 0.0, 1.0, i=i)
        assert len(tr) == 4
        assert all(s["name"].startswith("a.") for s in tr.recent(prefix="a."))
        assert tr.recent(n=2)[-1]["attrs"]["i"] == 9


class TestCatalog:
    def test_global_catalog_registered(self):
        # the catalog module must have registered every advertised family
        from tendermint_tpu.telemetry import metrics  # noqa: F401

        for name in (
            "tendermint_consensus_height",
            "tendermint_consensus_phase_seconds",
            "tendermint_consensus_round_skips_total",
            "tendermint_consensus_vote_drain_batch_size",
            "tendermint_verify_batch_size",
            "tendermint_hash_seconds",
            "tendermint_breaker_state",
            "tendermint_breaker_transitions_total",
            "tendermint_p2p_sent_bytes_total",
            "tendermint_mempool_size",
            "tendermint_wal_fsync_seconds",
        ):
            assert REGISTRY.get(name) is not None, name

    def test_breaker_binds_telemetry(self):
        from tendermint_tpu.utils.circuit import CircuitBreaker

        before = REGISTRY.counter_value(
            "tendermint_breaker_transitions_total", kind="t-unit", to="open"
        )
        b = CircuitBreaker(failure_threshold=2, name="t-unit")
        b.record_failure()
        b.record_failure()
        assert b.state == "open"
        assert REGISTRY.counter_value(
            "tendermint_breaker_state", kind="t-unit"
        ) == 2.0
        assert (
            REGISTRY.counter_value(
                "tendermint_breaker_transitions_total", kind="t-unit", to="open"
            )
            == before + 1
        )


class TestMetricsRoute:
    def test_get_metrics_serves_prometheus_text(self):
        from tendermint_tpu.rpc.server import RPCServer

        srv = RPCServer({"echo": lambda: {"ok": True}}, "tcp://127.0.0.1:0")
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10
            ) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = resp.read().decode()
            # global registry families render, HELP/TYPE lines included
            assert "# TYPE tendermint_consensus_height gauge" in body
            assert "# TYPE tendermint_verify_seconds histogram" in body
            assert "tendermint_p2p_sent_bytes_total" in body
            # the scrape itself is counted
            assert REGISTRY.counter_value(
                "tendermint_rpc_requests_total", method="metrics", result="ok"
            ) >= 1
            # JSON-RPC routes still work beside the exposition route
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/echo", timeout=10
            ) as resp:
                assert json.load(resp)["result"] == {"ok": True}
        finally:
            srv.stop()


class TestBenchHotpath:
    def test_emits_bench_json_from_registry(self, tmp_path):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "bench_hotpath",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
                "bench_hotpath.py",
            ),
        )
        bh = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bh)

        out = tmp_path / "BENCH_hotpath.json"
        rc = bh.main(
            [
                "--out",
                str(out),
                "--reps",
                "1",
                "--sizes",
                "8,16",
                "--wal-records",
                "16",
                "--no-device",
            ]
        )
        assert rc == 0
        data = json.loads(out.read_text())
        assert data["unit"] == "verifies/s"
        assert data["value"] > 0
        host = data["detail"]["verify"]["host"]
        assert host["signatures"] >= 8 + 16  # this run's (registry may hold more)
        assert data["detail"]["wal_fsync"]["count"] >= 16
        assert data["detail"]["hash"]["host"]["leaves_per_s"] > 0


class TestSpanPersistence:
    """Span timelines survive restarts: bounded JSONL ring under the
    data dir, replayed into the tracer on boot (ROADMAP observability
    follow-up)."""

    def test_sink_appends_and_load_roundtrips(self, tmp_path):
        from tendermint_tpu.telemetry.spanlog import SpanLog

        tr = Tracer(capacity=16)
        log = SpanLog(str(tmp_path / "spans.jsonl"), capacity=16)
        tr.set_sink(log.append)
        tr.add("consensus.propose", 1.0, 2.0, height=7)
        tr.add("verify.batch", 2.0, 2.5, n=64)
        tr.clear_sink(log.append)
        log.close()
        loaded = SpanLog(str(tmp_path / "spans.jsonl"), capacity=16).load()
        assert [d["name"] for d in loaded] == [
            "consensus.propose",
            "verify.batch",
        ]
        assert loaded[0]["attrs"]["height"] == 7

    def test_ring_compacts_to_capacity(self, tmp_path):
        from tendermint_tpu.telemetry.spanlog import SpanLog

        path = str(tmp_path / "spans.jsonl")
        log = SpanLog(path, capacity=8)
        tr = Tracer(capacity=64)
        tr.set_sink(log.append)
        for i in range(40):
            tr.add("s", float(i), float(i) + 0.5, i=i)
        log.close()
        loaded = SpanLog(path, capacity=8).load()
        assert len(loaded) <= 8
        # the NEWEST spans survive compaction
        assert loaded[-1]["attrs"]["i"] == 39

    def test_persist_spans_replays_then_sinks(self, tmp_path):
        from tendermint_tpu.telemetry.spanlog import SpanLog, persist_spans

        path = str(tmp_path / "spans.jsonl")
        first = SpanLog(path, capacity=32)
        tr0 = Tracer(capacity=32)
        tr0.set_sink(first.append)
        tr0.add("consensus.commit", 10.0, 11.0, height=42)
        first.close()

        # "restart": a fresh tracer replays the persisted window and
        # keeps persisting new spans
        tr1 = Tracer(capacity=32)
        log = persist_spans(tr1, path, capacity=32)
        restored = tr1.recent()
        assert restored[0]["name"] == "consensus.commit"
        assert restored[0]["attrs"]["restored"] is True
        assert restored[0]["attrs"]["height"] == 42
        tr1.add("consensus.propose", 11.0, 12.0, height=43)
        tr1.clear_sink(log.append)
        log.close()
        names = [d["name"] for d in SpanLog(path, capacity=32).load()]
        # the replayed span is NOT re-appended; the new one is
        assert names == ["consensus.commit", "consensus.propose"]

    def test_torn_final_line_is_skipped(self, tmp_path):
        from tendermint_tpu.telemetry.spanlog import SpanLog

        path = tmp_path / "spans.jsonl"
        path.write_text(
            '{"name":"ok","start":1.0,"end":2.0}\n{"name":"torn","sta'
        )
        loaded = SpanLog(str(path), capacity=8).load()
        assert [d["name"] for d in loaded] == ["ok"]

    def test_clear_sink_only_removes_own_sink(self):
        tr = Tracer(capacity=4)
        mine, theirs = [], []
        tr.set_sink(mine.append)
        tr.set_sink(theirs.append)  # a successor took over
        tr.clear_sink(mine.append)  # stopping node must not strip it
        tr.add("s", 0.0, 1.0)
        assert len(theirs) == 1 and not mine


class TestHistogramExemplars:
    """Exemplar trace ids on histogram observations: the breadcrumb
    from an aggregate back to one concrete traced request (JSON dump
    only — text exposition 0.0.4 has no exemplar syntax)."""

    def test_observe_with_exemplar_surfaces_in_snapshots(self):
        reg = Registry()
        h = Histogram("h", "", buckets=(1.0,), registry=reg)
        h.observe(0.5)
        assert "exemplar" not in h.value
        h.observe(0.7, exemplar="feedface01")
        assert h.value["exemplar"] == "feedface01"
        series = reg.to_dict()["h"]["series"][0]
        assert series["exemplar"] == "feedface01"
        # text exposition is unchanged by exemplars
        assert "exemplar" not in reg.prometheus_text()

    def test_labeled_children_keep_independent_exemplars(self):
        reg = Registry()
        h = Histogram("h", "", labelnames=("stage",), buckets=(1.0,), registry=reg)
        h.labels(stage="drain").observe(0.1, exemplar="aaaa")
        h.labels(stage="verify").observe(0.2)
        assert h.labels(stage="drain").value["exemplar"] == "aaaa"
        assert "exemplar" not in h.labels(stage="verify").value
