"""Interpret-mode smoke test for the pallas ladder's call plumbing.

`tests/test_ladder_pallas.py` gates the ladder MATH on CPU (plane ops
as plain jnp), but the pallas_call mechanics — BlockSpec index_maps,
grid order, the t==0 scratch reset, the final-step out write — had no
CPU coverage: an index_map regression would surface only on TPU runs.

Full-geometry interpret mode is unusable as a test budget (>10 min per
call; even a toy-geometry graph takes XLA ~5 min to compile because of
the 20-limb field math). So this file shrinks BOTH dimensions:

* toy geometry via monkeypatched SCALAR_BITS / MIN_LANES /
  MAX_TILE_LANES (8 lanes, 6 ladder steps, one (8, 1)-plane tile);
* the field math (`_double_planes` / `_madd_planes`) replaced with
  cheap shape-preserving arithmetic — the kernel resolves them from
  module globals, so the REAL kernel body still runs, block indexing
  and all; only the limb math inside is substituted. The math itself
  is separately CPU-gated by test_ladder_pallas.py.

Any change that misindexes a BlockSpec, reorders the grid, skips the
scratch reset, or drops the final-step write now fails on CPU CI in
about a second.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import tendermint_tpu.ops.ed25519_ladder_pallas as lad  # noqa: E402

TOY_BITS = 6
TOY_LANES = 8  # one (8, 1)-plane tile


def _cheap_double(acc):
    """Stands in for _double_planes: per-plane, invertible, cheap."""
    return tuple([2 * p for p in coord] for coord in acc)


def _cheap_madd(acc, ypx, ymx, t2d):
    """Stands in for _madd_planes: mixes acc with all three entry
    groups so a wrong/missing entry select shows up in the output."""
    x, y, z, t = acc
    return (
        [a + e for a, e in zip(x, ypx)],
        [a + e for a, e in zip(y, ymx)],
        [a + e for a, e in zip(z, t2d)],
        [a + e1 - e2 for a, e1, e2 in zip(t, ypx, ymx)],
    )


@pytest.fixture
def toy_kernel(monkeypatch):
    monkeypatch.setattr(lad, "SCALAR_BITS", TOY_BITS)
    monkeypatch.setattr(lad, "MIN_LANES", TOY_LANES)
    monkeypatch.setattr(lad, "MAX_TILE_LANES", TOY_LANES)
    monkeypatch.setattr(lad, "_double_planes", _cheap_double)
    monkeypatch.setattr(lad, "_madd_planes", _cheap_madd)


def _toy_inputs(rng, tiles=1, w=TOY_LANES // 8):
    gtab = rng.integers(0, 1 << 8, size=(tiles, 4, 60, 8, w), dtype=np.int32)
    dig = rng.integers(0, 4, size=(tiles, TOY_BITS, 8, w), dtype=np.int32)
    return jnp.asarray(gtab), jnp.asarray(dig)


def _host_reference(gtab, dig, w):
    """The kernel body's semantics step by step in plain numpy/jnp:
    t==0 identity init, double, 4-way masked entry select, madd —
    mirrors _make_ladder_kernel including msb-first step order."""
    tiles = gtab.shape[0]
    outs = []
    for i in range(tiles):
        rows = jax.lax.broadcasted_iota(jnp.int32, (80, 8, w), 0)
        acc_arr = jnp.where((rows == 20) | (rows == 40), 1, 0)
        for t in range(TOY_BITS):
            acc = tuple(
                [acc_arr[20 * ci + k] for k in range(20)] for ci in range(4)
            )
            acc = _cheap_double(acc)
            d = dig[i, t]
            gt = gtab[i]
            masks = [d == k for k in range(4)]
            ent = []
            for limb in range(60):
                v = jnp.where(masks[0], gt[0, limb], 0)
                for k in range(1, 4):
                    v = v + jnp.where(masks[k], gt[k, limb], 0)
                ent.append(v)
            nxt = _cheap_madd(acc, ent[:20], ent[20:40], ent[40:])
            acc_arr = jnp.stack([p for coord in nxt for p in coord])
        outs.append(acc_arr)
    return jnp.stack(outs)


def _coords_from_out(out, tiles, w):
    coords = out.reshape(tiles, 4, 20, 8, w)
    return jnp.transpose(coords, (1, 0, 3, 4, 2)).reshape(4, -1, lad.NLIMBS)


class TestInterpretPlumbing:
    def test_single_tile_matches_host_reference(self, toy_kernel):
        rng = np.random.default_rng(7)
        gtab, dig = _toy_inputs(rng)
        got = lad._ladder_pallas(gtab, dig, w=1, interpret=True)
        expect = _coords_from_out(_host_reference(gtab, dig, 1), 1, 1)
        for c, (g, e) in enumerate(zip(got, expect)):
            np.testing.assert_array_equal(
                np.asarray(g), np.asarray(e), err_msg=f"coord {c}"
            )

    def test_multi_tile_grid_indexing(self, toy_kernel):
        """Two tiles with DIFFERENT tables/digits: a wrong index_map
        (swapped grid axes, off-by-one block origin) collapses the
        tiles onto each other and fails this comparison."""
        rng = np.random.default_rng(11)
        gtab, dig = _toy_inputs(rng, tiles=2)
        got = lad._ladder_pallas(gtab, dig, w=1, interpret=True)
        expect = _coords_from_out(_host_reference(gtab, dig, 1), 2, 1)
        for g, e in zip(got, expect):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(e))
        x = np.asarray(got[0])
        assert not np.array_equal(x[:TOY_LANES], x[TOY_LANES:])

    def test_digit_schedule_is_msb_first(self, monkeypatch):
        """_ladder_digits column t must be bit (SCALAR_BITS-1-t): the
        kernel consumes digits msb-first via the (i, t) BlockSpec."""
        monkeypatch.setattr(lad, "SCALAR_BITS", TOY_BITS)
        s = np.zeros((2, 32), dtype=np.uint8)
        h = np.zeros((2, 32), dtype=np.uint8)
        s[0, 0] = 0b100001  # bits 0 and 5 of lane 0
        h[1, 0] = 0b000010  # bit 1 of lane 1
        dig = np.asarray(lad._ladder_digits(jnp.asarray(s), jnp.asarray(h)))
        assert dig.shape == (2, TOY_BITS)
        assert dig[0].tolist() == [1, 0, 0, 0, 0, 1]  # s bits, msb first
        assert dig[1].tolist() == [0, 0, 0, 0, 2, 0]  # h bit -> selector 2

    def test_tile_lanes_rejects_sub_minimum_batches(self, monkeypatch):
        monkeypatch.setattr(lad, "MAX_TILE_LANES", TOY_LANES)
        monkeypatch.setattr(lad, "MIN_LANES", TOY_LANES)
        assert lad._tile_lanes(TOY_LANES) == TOY_LANES
        assert lad._tile_lanes(4 * TOY_LANES) == TOY_LANES
        with pytest.raises(ValueError):
            lad._tile_lanes(TOY_LANES - 2)
