"""Threading stress: concurrent access to the shared structures.

The reference runs its whole suite under Go's race detector
(`Makefile:31-33`); Python has no equivalent sanitizer, so this suite
hammers each lock-guarded structure from many threads and asserts the
invariants that racing mutations would break (lost updates, double
counts, torn state).
"""

import threading

from tests.helpers import CHAIN_ID, make_block_id, make_validators, signed_vote

from tendermint_tpu.types import VOTE_TYPE_PRECOMMIT, VoteSet
from tendermint_tpu.utils.bit_array import BitArray

N_THREADS = 8
N_OPS = 200


def _run_threads(fn):
    threads = [threading.Thread(target=fn, args=(i,)) for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress thread wedged"


class TestRaceStress:
    def test_vote_set_concurrent_adds(self):
        vs, privs = make_validators(N_THREADS)
        bid = make_block_id()
        votes = [
            signed_vote(privs[i], i, 1, 0, VOTE_TYPE_PRECOMMIT, bid, CHAIN_ID)
            for i in range(N_THREADS)
        ]
        vote_set = VoteSet(CHAIN_ID, 1, 0, VOTE_TYPE_PRECOMMIT, vs)
        errors = []

        def worker(i):
            try:
                for _ in range(50):  # re-adds must dedup, not double-count
                    vote_set.add_vote(votes[i])
            except Exception as e:  # pragma: no cover
                errors.append(e)

        _run_threads(worker)
        assert not errors
        assert vote_set.bit_array().num_set() == N_THREADS
        assert vote_set.sum == vs.total_voting_power  # no double-counted power
        assert vote_set.has_two_thirds_majority()

    def test_bit_array_concurrent_sets(self):
        ba = BitArray(N_THREADS * N_OPS)

        def worker(i):
            for j in range(N_OPS):
                ba.set(i * N_OPS + j, True)

        _run_threads(worker)
        assert ba.num_set() == N_THREADS * N_OPS  # no lost updates

    def test_mempool_concurrent_checktx_reap_update(self):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.mempool.mempool import Mempool
        from tendermint_tpu.types.tx import Txs

        mp = Mempool(local_client_creator(KVStoreApp())().mempool)
        stop = threading.Event()

        def producer(i):
            for j in range(N_OPS):
                mp.check_tx(b"k%d-%d=v" % (i, j))

        def churner(_i):
            while not stop.is_set():
                txs = mp.reap(10)
                if txs:
                    mp.lock()
                    try:
                        mp.update(1, Txs(list(txs)))
                    finally:
                        mp.unlock()

        churn = threading.Thread(target=churner, args=(0,))
        churn.start()
        _run_threads(producer)
        stop.set()
        churn.join(timeout=10)
        assert not churn.is_alive()
        # drain: everything that remains is unique and reapable
        leftover = mp.reap(-1)
        assert len(set(bytes(t) for t in leftover)) == len(leftover)

    def test_event_switch_concurrent_fire_and_mutate(self):
        from tendermint_tpu.types.events import EventSwitch

        es = EventSwitch()
        hits = []

        def subscriber(i):
            for j in range(N_OPS):
                es.add_listener(f"l{i}-{j}", "ev", lambda d: hits.append(d))
                es.fire("ev", j)
                es.remove_listener(f"l{i}-{j}")

        _run_threads(subscriber)
        assert hits  # fired without deadlock or exception

    def test_part_set_concurrent_add(self):
        from tendermint_tpu.types.part_set import PartSet

        ps_full = PartSet.from_data(b"\xab" * 40_000, part_size=512)
        target = PartSet.from_header(ps_full.header)
        added = []

        def worker(i):
            ok = 0
            for idx in range(ps_full.total):
                if target.add_part(ps_full.get_part(idx)):
                    ok += 1
            added.append(ok)

        _run_threads(worker)
        assert target.is_complete()
        # each part accepted EXACTLY once across all threads
        assert sum(added) == ps_full.total
