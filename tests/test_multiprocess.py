"""Process-isolated exercises (VERDICT r4 missing #6 / weak #6):

1. A real 2-process `jax.distributed` run through `parallel/distributed.py`
   proving `host_local_to_global`'s multi-process branch and the global-mesh
   sharded verify actually execute multi-host (reference scale-out:
   SURVEY §5.8, the NCCL/MPI slot).
2. A 4-node subprocess testnet driven through the real CLI (`testnet` +
   `node`) to one committed tx via RPC — the portable equivalent of the
   reference's `test/p2p/local_testnet_start.sh` + atomic_broadcast suite.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_DIST_WORKER = r"""
import os, sys
rank = int(sys.argv[1]); coord = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, @REPO@)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from jax.sharding import PartitionSpec as P

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.ops.ed25519_kernel import prepare_batch
from tendermint_tpu.parallel import distributed as dist
from tendermint_tpu.parallel.mesh import BATCH_AXIS, sharded_verify_and_tally

dist.initialize(coordinator=coord, num_processes=2, process_id=rank)
assert dist.process_info() == (rank, 2), dist.process_info()
mesh = dist.global_batch_mesh()
assert mesh.devices.size == 8, mesh  # 2 procs x 4 virtual cpu devices

# deterministic triples; lane 5 corrupted (global index -> rank 0's shard)
privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(16)]
msgs = [b"dist-msg-%d" % i for i in range(16)]
sigs = [p.sign(m) for p, m in zip(privs, msgs)]
sigs[5] = sigs[5][:8] + bytes([sigs[5][8] ^ 1]) + sigs[5][9:]
pubs = [p.pub_key.data for p in privs]
pub, r, s, h, _pre = prepare_batch(pubs, msgs, sigs)
powers = np.full(16, 3, dtype=np.int32)

# each process contributes ONLY its own half, in global order
lo, hi = rank * 8, rank * 8 + 8
spec = P(BATCH_AXIS)
placed = [dist.host_local_to_global(mesh, spec, np.asarray(a)[lo:hi])
          for a in (pub, r, s, h)]
pw = dist.host_local_to_global(mesh, spec, powers[lo:hi])
ok, total = sharded_verify_and_tally(mesh)(*placed, pw)
# the psum tally is replicated: every process can read it
assert int(total) == 15 * 3, int(total)
# each process checks its own addressable shard of the verdict mask
local_ok = np.concatenate(
    [np.asarray(sh.data).ravel() for sh in sorted(
        ok.addressable_shards, key=lambda sh: sh.index)]
)
want = np.ones(8, dtype=bool)
if rank == 0:
    want[5] = False
assert (local_ok == want).all(), (rank, local_ok)
print("RANK%d OK" % rank, flush=True)
"""


class TestJaxDistributedTwoProcess:
    def test_global_mesh_verify_across_two_processes(self, tmp_path):
        """2 real OS processes, 8-device global mesh: the multi-process
        branch of host_local_to_global (each host supplies only its own
        lanes) runs, the planted bad signature localizes on the owning
        rank, and the psum tally replicates to both."""
        coord = f"127.0.0.1:{_free_port()}"
        script = tmp_path / "dist_worker.py"
        script.write_text(_DIST_WORKER.replace("@REPO@", repr(REPO)))
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
        }
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(rank), coord],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                env=env,
                text=True,
            )
            for rank in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=420)
                outs.append(out)
            for rank, (p, out) in enumerate(zip(procs, outs)):
                assert p.returncode == 0, f"rank{rank} failed:\n{out[-3000:]}"
                assert f"RANK{rank} OK" in out
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()


def _rpc(port, method, timeout=60, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


class TestSubprocessTestnet:
    def test_four_node_processes_commit_a_tx(self, tmp_path):
        """4 REAL `tendermint_tpu node` processes from `testnet` fixtures
        reach consensus over localhost TCP and commit a tx submitted via
        broadcast_tx_commit (reference
        `test/p2p/atomic_broadcast/test.sh`)."""
        out_dir = str(tmp_path / "net")
        base = _free_port() | 1  # odd base; testnet uses base..base+7
        subprocess.run(
            [
                sys.executable,
                "-m",
                "tendermint_tpu.cmd",
                "testnet",
                "--n",
                "4",
                "--output",
                out_dir,
                "--starting-port",
                str(base),
            ],
            cwd=REPO,
            check=True,
            capture_output=True,
        )
        rpc_ports = [base + 2 * i + 1 for i in range(4)]
        procs = []
        try:
            for i in range(4):
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-m",
                            "tendermint_tpu.cmd",
                            "node",
                            "--home",
                            os.path.join(out_dir, f"node{i}"),
                        ],
                        cwd=REPO,
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                )
            deadline = time.time() + 120
            heights = {}
            while time.time() < deadline:
                try:
                    heights = {
                        p: _rpc(p, "status", timeout=5)["sync_info"][
                            "latest_block_height"
                        ]
                        for p in rpc_ports
                    }
                    if all(h >= 2 for h in heights.values()):
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            assert heights and all(h >= 2 for h in heights.values()), heights

            res = _rpc(rpc_ports[0], "broadcast_tx_commit", tx=b"mp=ok".hex(), timeout=90)
            assert res["deliver_tx"]["code"] == 0
            # the tx is queryable chain-wide once peers catch up
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    got = _rpc(rpc_ports[3], "tx", hash=res["hash"], timeout=5)
                    assert bytes.fromhex(got["tx"]) == b"mp=ok"
                    break
                except RuntimeError:
                    time.sleep(0.5)
            else:
                raise AssertionError("tx never indexed on node3")
            info = _rpc(rpc_ports[3], "net_info")
            assert info["n_peers"] == 3
            # all four agree on the genesis block hash
            h1 = {
                _rpc(p, "block", height=1)["block"]["header"]["height"]
                for p in rpc_ports
            }
            assert h1 == {1}
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
