"""Device TreeHasher wired into block production (reference hash hot spots
`types/tx.go:33-46`, `types/part_set.go:95-122`, `types/block.go:173-188`).

Always-on tier: proves the production plumbing actually routes through the
TreeHasher API (the round-4 verdict found a dead seam calling a nonexistent
method) and that device/host roots are bit-identical at small sizes. The
65k-leaf device build lives in the kernel tier (`test_hash_kernels.py`).
"""

import pytest

from tendermint_tpu.merkle.simple import simple_hash_from_byte_slices
from tendermint_tpu.services.hasher import TreeHasher
from tendermint_tpu.types import BlockID, Txs
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.part_set import PartSet

from tests.helpers import ChainSim


class SpyHasher(TreeHasher):
    """Host-backed TreeHasher that records which API methods fire."""

    def __init__(self):
        super().__init__(backend="host")
        self.root_calls = 0
        self.proof_calls = 0

    def root_from_items(self, items):
        self.root_calls += 1
        return super().root_from_items(items)

    def proofs(self, items):
        self.proof_calls += 1
        return super().proofs(items)


class TestProductionSeam:
    def test_txs_hash_uses_tree_hasher_api(self):
        """The seam the r4 verdict found broken: Txs.hash(hasher) must call
        the real TreeHasher API and produce the host-identical root."""
        txs = Txs(b"tx-%d" % i for i in range(37))
        spy = SpyHasher()
        assert txs.hash(spy) == simple_hash_from_byte_slices(list(txs))
        assert spy.root_calls == 1

    def test_make_block_threads_hasher(self):
        txs = Txs(b"payload-%d" % i for i in range(20))
        spy = SpyHasher()
        block = Block.make_block(
            height=1,
            chain_id="seam-chain",
            txs=txs,
            last_commit=Commit.empty(),
            last_block_id=BlockID.zero(),
            time=1,
            validators_hash=b"\x01" * 20,
            app_hash=b"",
            hasher=spy,
        )
        assert spy.root_calls == 1
        assert block.header.data_hash == simple_hash_from_byte_slices(list(txs))
        # validate_basic(hasher) recomputes through the same seam
        spy2 = SpyHasher()
        block.validate_basic(spy2)
        assert spy2.root_calls == 1

    def test_part_set_from_data_threads_hasher(self):
        spy = SpyHasher()
        data = bytes(range(256)) * 40
        ps = PartSet.from_data(data, part_size=256, hasher=spy)
        assert spy.proof_calls == 1
        # roots agree with the unhashed path
        assert ps.header == PartSet.from_data(data, part_size=256).header

    def test_chain_advances_with_hasher(self):
        """Fast-sync-style end-to-end: blocks built AND validated through
        the hasher apply cleanly and match a hasherless chain bit-for-bit."""
        spy = SpyHasher()
        sim = ChainSim(n_vals=4, hasher=spy)
        plain = ChainSim(n_vals=4)
        for h in range(1, 4):
            b1 = sim.advance(txs=[b"tx-%d-%d" % (h, i) for i in range(32)])
            b2 = plain.advance(txs=[b"tx-%d-%d" % (h, i) for i in range(32)])
            # genesis_time differs between sims, so compare the hasher-derived
            # field, not the whole header
            assert b1.header.data_hash == b2.header.data_hash
        assert sim.state.last_block_height == 3
        assert spy.root_calls > 0
        assert spy.proof_calls > 0

    def test_device_backend_bit_identical_on_small_block(self):
        """Device tree (forced via min_device_leaves=2) produces the same
        data_hash as host for a produced block."""
        dev = TreeHasher(backend="device", min_device_leaves=2)
        txs = Txs(b"devtx-%d" % i for i in range(16))
        assert txs.hash(dev) == txs.hash(None)

    def test_default_threshold_routes_small_to_host(self, monkeypatch):
        """Below min_device_leaves the device kernel must NOT launch: small
        blocks would eat the ~60ms dispatch floor for nothing."""
        import tendermint_tpu.ops.merkle_kernel as mk

        def boom(*a, **k):  # pragma: no cover - failure path
            raise AssertionError("device kernel launched below threshold")

        monkeypatch.setattr(mk, "merkle_root_device", boom)
        th = TreeHasher(backend="device")  # default threshold (8192)
        items = [b"small-%d" % i for i in range(64)]
        assert th.root_from_items(items) == simple_hash_from_byte_slices(items)

    def test_auto_hasher_backend_matches_platform(self):
        import jax

        from tendermint_tpu.services.hasher import auto_hasher

        th = auto_hasher()
        expected = "device" if jax.default_backend() == "tpu" else "host"
        assert th.backend == expected
