"""Address book + PEX discovery (reference `p2p/addrbook_test.go`,
`p2p/pex_reactor_test.go`)."""

import time

import pytest

from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import Config
from tendermint_tpu.node import Node
from tendermint_tpu.p2p.addrbook import MAX_ATTEMPTS, AddrBook, NetAddress


class TestAddrBook:
    def test_add_promote_and_persist(self, tmp_path):
        path = str(tmp_path / "addrbook.json")
        book = AddrBook(path)
        a = NetAddress("aa" * 20, "127.0.0.1:1000")
        b = NetAddress("bb" * 20, "127.0.0.1:2000")
        assert book.add_address(a, src_id="seed")
        assert book.add_address(b, src_id="seed")
        assert not book.add_address(NetAddress("", ""), "x")
        assert book.size() == 2
        book.mark_good(a.node_id)
        # old entries are not overwritten by later gossip
        assert not book.add_address(
            NetAddress(a.node_id, "9.9.9.9:1"), src_id="liar"
        )
        # restart survival (saves are debounced; shutdown flushes)
        book.flush()
        book2 = AddrBook(path)
        assert book2.size() == 2
        assert book2.has(a.node_id)
        picked = {book2.pick_address().node_id for _ in range(50)}
        assert picked <= {a.node_id, b.node_id}

    def test_failed_attempts_evict_new_addresses(self, tmp_path):
        book = AddrBook(str(tmp_path / "ab.json"))
        a = NetAddress("cc" * 20, "127.0.0.1:3000")
        book.add_address(a, "seed")
        for _ in range(MAX_ATTEMPTS):
            book.mark_attempt(a.node_id)
        assert not book.has(a.node_id)  # flaky new address dropped
        # but OLD (proven) addresses survive failed attempts
        b = NetAddress("dd" * 20, "127.0.0.1:4000")
        book.add_address(b, "seed")
        book.mark_good(b.node_id)
        for _ in range(MAX_ATTEMPTS + 2):
            book.mark_attempt(b.node_id)
        assert book.has(b.node_id)

    def test_sample_bounded(self, tmp_path):
        book = AddrBook(str(tmp_path / "ab.json"))
        for i in range(40):
            book.add_address(
                NetAddress(f"{i:040x}", f"127.0.0.1:{5000+i}"), "seed"
            )
        assert len(book.sample(16)) == 16


class TestReRequest:
    def test_ensure_pass_reasks_when_book_exhausted(self, tmp_path):
        """The request/registration race (two judges hit it): if our one
        addr request reached a peer before ITS book had the third node,
        discovery deadlocked. ensure_peers must re-ask a connected peer
        (rate-limited) whenever the book can't cover the deficit."""
        from tendermint_tpu.p2p.pex import PEX_CHANNEL, PEXReactor, decode_message

        book = AddrBook(str(tmp_path / "ab.json"))
        r = PEXReactor(book, dial_fn=lambda addr: None, max_peers=4)
        r.REREQUEST_MIN_S = 0.0  # no wall-clock in the unit test

        sent = []

        class FakePeer:
            id = "ee" * 20
            outbound = True

            class node_info:
                listen_addr = ""

            def try_send(self, chan, payload):
                sent.append((chan, payload))
                return True

        peer = FakePeer()

        class FakeSwitch:
            def peers(self):
                return [peer]

        r.switch = FakeSwitch()
        r._running = True
        r.ensure_peers()  # empty book, below target -> must re-request
        assert sent, "no addr request issued on an exhausted book"
        chan, payload = sent[-1]
        assert chan == PEX_CHANNEL
        assert decode_message(payload)[0] == "request"
        # rate limit: an immediate second pass must NOT spam requests
        r.REREQUEST_MIN_S = 60.0
        r._requested[peer.id] = __import__("time").monotonic()
        n = len(sent)
        r.ensure_peers()
        assert len(sent) == n


@pytest.mark.slow
class TestPEXDiscovery:
    def test_transitive_peer_discovery(self, tmp_path):
        """A knows only B; C knows only B; PEX must connect A<->C."""
        nodes = []
        try:
            for name in ("a", "b", "c"):
                home = str(tmp_path / name)
                cli_main(["init", "--home", home, "--chain-id", "pex-chain"])
                cfg = Config.test_config(home)
                cfg.base.fast_sync = False
                cfg.base.moniker = name
                nodes.append(Node(cfg))
            # distinct validators not required: discovery is consensus-free,
            # but all three share the chain id so handshakes pass
            for n in nodes:
                n.start()
            a, b, c = nodes
            from tendermint_tpu.p2p.tcp import dial

            dial(a.switch, f"127.0.0.1:{b.p2p_port}", priv_key=a._node_key)
            dial(c.switch, f"127.0.0.1:{b.p2p_port}", priv_key=c._node_key)

            deadline = time.time() + 30
            while time.time() < deadline:
                if all(n.switch.n_peers() >= 2 for n in nodes):
                    break
                time.sleep(0.2)
            assert all(
                n.switch.n_peers() >= 2 for n in nodes
            ), [n.switch.n_peers() for n in nodes]
            # the books learned the transitive addresses
            assert a.addr_book.has(c.node_id) or c.addr_book.has(a.node_id)
        finally:
            for n in nodes:
                n.stop()
