import pytest

from tendermint_tpu.types import GenesisDoc, GenesisValidator, ValidationError
from tests.helpers import det_priv_keys


def make_genesis(n=4):
    keys = det_priv_keys(n)
    return GenesisDoc(
        chain_id="test-chain",
        validators=[GenesisValidator(pub_key=k.pub_key, power=10) for k in keys],
    )


def test_roundtrip_json():
    doc = make_genesis()
    doc.validate_and_complete()
    doc2 = GenesisDoc.from_json(doc.to_json())
    assert doc2.chain_id == doc.chain_id
    assert doc2.validator_hash() == doc.validator_hash()
    assert doc2.genesis_time == doc.genesis_time


def test_save_load_file(tmp_path):
    doc = make_genesis()
    doc.validate_and_complete()
    p = str(tmp_path / "genesis.json")
    doc.save_as(p)
    assert GenesisDoc.from_file(p).validator_hash() == doc.validator_hash()


def test_empty_chain_id_rejected():
    doc = make_genesis()
    doc.chain_id = ""
    with pytest.raises(ValidationError):
        doc.validate_and_complete()


def test_no_validators_rejected():
    doc = make_genesis()
    doc.validators = []
    with pytest.raises(ValidationError):
        doc.validate_and_complete()


def test_validator_set_size():
    doc = make_genesis(7)
    doc.validate_and_complete()
    assert doc.validator_set().size() == 7
