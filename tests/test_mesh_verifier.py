"""Multi-chip sharded verify mesh: the production mesh path on the
8-virtual-device CPU mesh conftest forces (ISSUE 6 tentpole).

Choreography — pad/slice geometry, shard faults, survivor re-mesh,
breaker interplay, coalescer drain order — runs tier-1 through the
`executor="host"` mesh stand-in (verdict-identical host evaluation of
the device equation, zero XLA compiles; the TestFusedPathShaping idiom).
One tier-1 test compiles the REAL sharded ladder once to pin verdict
parity through the default stack; heavier real-kernel variants are
double-marked kernel+slow per the conftest lint.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.parallel.mesh import (
    MeshExhaustedError,
    MeshManager,
    mesh_device_count,
    set_default_mesh_manager,
)
from tendermint_tpu.services.batcher import CoalescingVerifier
from tendermint_tpu.services.resilient import ResilientVerifier
from tendermint_tpu.services.verifier import (
    HostBatchVerifier,
    ShardedBatchVerifier,
    ShardedTableBatchVerifier,
    set_default_verifier,
)
from tendermint_tpu.telemetry import REGISTRY
from tendermint_tpu.utils import fail


@pytest.fixture(autouse=True)
def _clean_mesh_state():
    fail.clear_device_faults()
    set_default_mesh_manager(None)
    yield
    fail.clear_device_faults()
    set_default_mesh_manager(None)
    set_default_verifier(None)


def _triples(n, corrupt=(), salt=b""):
    privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
    out = []
    for i, p in enumerate(privs):
        m = b"mesh-msg-%s-%d" % (salt, i)
        sig = p.sign(m)
        if i in corrupt:
            sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]
        out.append((p.pub_key.data, m, sig))
    return out


def _counter(name, **labels) -> float:
    return REGISTRY.counter_value(name, **labels)


def _host_mesh_verifier(min_batch=1, reprobe_s=60.0, devices=None):
    mgr = MeshManager(executor="host", reprobe_s=reprobe_s, devices=devices)
    return ShardedBatchVerifier(mesh=mgr, min_device_batch=min_batch), mgr


class TestMeshManager:
    def test_discovers_all_eight_virtual_devices(self):
        assert mesh_device_count() == 8
        mgr = MeshManager(executor="host")
        assert mgr.n_total == 8
        assert mgr.active_indices() == tuple(range(8))
        assert not mgr.degraded

    def test_mesh_devices_knob(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "3")
        assert mesh_device_count() == 3
        assert MeshManager(executor="host").n_total == 3
        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "1")
        assert mesh_device_count() == 1  # force single-device legacy
        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "0")
        assert mesh_device_count() == 8  # 0/unset = all
        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "64")
        assert mesh_device_count() == 8  # capped at visible devices

    def test_shard_fault_excludes_then_reprobe_restores(self):
        mgr = MeshManager(executor="host", reprobe_s=0.05)
        shrink0 = _counter("tendermint_mesh_remesh_total", direction="shrink")
        restore0 = _counter("tendermint_mesh_remesh_total", direction="restore")
        assert mgr.record_shard_fault(5)  # survivors remain
        assert mgr.n_active == 7
        assert 5 not in mgr.active_indices()
        assert mgr.degraded
        assert (
            _counter("tendermint_mesh_remesh_total", direction="shrink")
            == shrink0 + 1
        )
        # inside the window: still degraded
        mgr.maybe_reprobe()
        assert mgr.n_active == 7
        time.sleep(0.06)
        mgr.maybe_reprobe()
        assert mgr.n_active == 8 and not mgr.degraded
        assert (
            _counter("tendermint_mesh_remesh_total", direction="restore")
            == restore0 + 1
        )

    def test_reprobe_keeps_excluding_while_fault_armed(self):
        mgr = MeshManager(executor="host", reprobe_s=0.05)
        fail.set_device_fault("shard2")
        assert mgr.record_shard_fault(2)
        time.sleep(0.06)
        mgr.maybe_reprobe()  # peeks the armed fault, stays degraded
        assert mgr.n_active == 7
        fail.clear_device_faults()
        time.sleep(0.06)
        mgr.maybe_reprobe()
        assert mgr.n_active == 8

    def test_exhaustion_reports_no_survivors(self):
        mgr = MeshManager(executor="host")
        for i in range(7):
            assert mgr.record_shard_fault(i)
        assert not mgr.record_shard_fault(7)
        assert mgr.n_active == 0
        snap = mgr.snapshot()
        assert snap["devices_active"] == 0
        assert snap["excluded"] == list(range(8))

    def test_devices_gauge_tracks_active(self):
        mgr = MeshManager(executor="host")
        fam = REGISTRY.get("tendermint_mesh_devices")
        assert fam.value == 8
        mgr.record_shard_fault(1)
        assert fam.value == 7
        mgr.reset()
        assert fam.value == 8


class TestShardedVerifierHostExecutor:
    @pytest.mark.parametrize("n", [1, 7, 8, 9, 16, 17])
    def test_pad_slice_round_trip_non_divisible(self, n):
        """Every batch size — divisible by the mesh or not — must come
        back bit-identical to the host library, at the true length."""
        v, _mgr = _host_mesh_verifier()
        triples = _triples(n, corrupt={n - 1} if n > 2 else ())
        want = HostBatchVerifier().verify_batch(triples)
        got = v.verify_batch(triples)
        assert got.shape == (n,)
        assert (got == want).all()

    def test_per_shard_bucket_geometry(self, monkeypatch):
        """Launch rows = per-chip power-of-two bucket x active chips —
        the chunk/stack fix: geometry derives from the per-chip shard
        size, and re-derives after a survivor re-mesh."""
        v, mgr = _host_mesh_verifier()
        shapes = []
        real_factory = mgr.verify_step

        def spying_step():
            real = real_factory()

            def _step(pub, r, s, h, pw):
                shapes.append(pub.shape[0])
                return real(pub, r, s, h, pw)

            return _step

        monkeypatch.setattr(mgr, "verify_step", spying_step)
        v.verify_batch(_triples(10))
        assert shapes[-1] == 8 * 8  # ceil(10/8)=2 -> bucket 8 -> x8 chips
        for i in (0, 1, 2):
            mgr.record_shard_fault(i)
        v.verify_batch(_triples(10))
        assert shapes[-1] == 8 * 5  # 5 survivors, per-chip bucket 8
        v.verify_batch(_triples(200))
        assert shapes[-1] == 64 * 5  # ceil(200/5)=40 -> bucket 64

    def test_zero_padding_rows_never_verify(self):
        """The pad-row safety property on the host-emulated step: an
        all-zero row reports False and zero power, so padding can never
        inflate a tally (mirrors `pad_to_multiple`'s kernel analysis)."""
        from tendermint_tpu.parallel.mesh import _host_verify_prepared_rows

        zeros = np.zeros((16, 32), dtype=np.uint8)
        ok = _host_verify_prepared_rows(zeros, zeros, zeros, zeros)
        assert not ok.any()

    def test_commit_tally_with_powers(self):
        v, _mgr = _host_mesh_verifier()
        triples = _triples(10, corrupt={3, 7})
        powers = np.arange(1, 11, dtype=np.int32)
        mask, tally = v.verify_batch_with_powers(triples, powers)
        want = HostBatchVerifier().verify_batch(triples)
        assert (mask == want).all()
        assert tally == int(powers[want].sum())

    def test_commit_grid_flat_lanes(self):
        v, _mgr = _host_mesh_verifier()
        triples = _triples(10, corrupt={2})
        pubs = [t[0] for t in triples]
        msgs = [t[1] for t in triples]
        sigs = [t[2] for t in triples]
        absent_msgs = list(msgs)
        absent_sigs = list(sigs)
        absent_msgs[5] = None
        absent_sigs[5] = None
        grid = v.verify_commits(pubs, [(msgs, sigs), (absent_msgs, absent_sigs)])
        assert grid.shape == (2, 10)
        want = HostBatchVerifier().verify_batch(triples)
        assert (grid[0] == want).all()
        want_absent = want.copy()
        want_absent[5] = False
        assert (grid[1] == want_absent).all()

    def test_small_batch_short_circuits_to_host(self, monkeypatch):
        v, mgr = _host_mesh_verifier(min_batch=512)

        def boom():  # the mesh must not be consulted below the threshold
            raise AssertionError("sub-threshold batch reached the mesh")

        monkeypatch.setattr(mgr, "verify_step", boom)
        triples = _triples(4)
        assert v.verify_batch(triples).all()

    def test_shard_fault_survivor_remesh_keeps_serving(self):
        """A single shard fault degrades through re-mesh, NOT through
        the breaker: verdicts stay correct, the resilient wrapper never
        sees a failure, telemetry shows the shrink."""
        v, mgr = _host_mesh_verifier()
        rv = ResilientVerifier(v)
        faults0 = _counter("tendermint_mesh_shard_faults_total")
        fallback0 = _counter(
            "tendermint_device_fallback_calls_total", kind="verify"
        )
        fail.set_device_fault("shard4")
        triples = _triples(12, corrupt={0})
        want = HostBatchVerifier().verify_batch(triples)
        got = rv.verify_batch(triples)
        assert (got == want).all()
        assert mgr.n_active == 7 and 4 not in mgr.active_indices()
        assert _counter("tendermint_mesh_shard_faults_total") == faults0 + 1
        # the breaker path was NEVER taken — re-mesh absorbed the fault
        assert (
            _counter("tendermint_device_fallback_calls_total", kind="verify")
            == fallback0
        )
        assert rv.breaker.state == "closed"

    def test_exhaustion_degrades_through_breaker_then_recovers(self):
        """All shards faulted -> MeshExhaustedError -> CircuitBreaker
        host fallback (the PR 1 ladder); clearing the faults and passing
        the re-probe window restores the FULL mesh."""
        v, mgr = _host_mesh_verifier(reprobe_s=0.05)
        rv = ResilientVerifier(v, max_retries=0)
        for i in range(8):
            fail.set_device_fault(f"shard{i}")
        fallback0 = _counter(
            "tendermint_device_fallback_calls_total", kind="verify"
        )
        triples = _triples(10, corrupt={1})
        want = HostBatchVerifier().verify_batch(triples)
        got = rv.verify_batch(triples)  # breaker fallback answers
        assert (got == want).all()
        assert mgr.n_active == 0
        assert (
            _counter("tendermint_device_fallback_calls_total", kind="verify")
            == fallback0 + 1
        )
        fail.clear_device_faults()
        time.sleep(0.06)
        restore0 = _counter("tendermint_mesh_remesh_total", direction="restore")
        got2 = rv.verify_batch(triples)
        assert (got2 == want).all()
        assert mgr.n_active == 8
        assert (
            _counter("tendermint_mesh_remesh_total", direction="restore")
            == restore0 + 1
        )

    def test_mesh_exhausted_raises_without_breaker(self):
        v, _mgr = _host_mesh_verifier()
        for i in range(8):
            fail.set_device_fault(f"shard{i}")
        with pytest.raises(MeshExhaustedError):
            v.verify_batch(_triples(9))


class TestTablesMeshGeometry:
    """Mesh-aware TableBatchVerifier SHAPING on the CPU mesh: the
    validator-axis table path's lane reordering, per-shard K padding,
    and fallbacks — kernel calls faked (the TestFusedPathShaping idiom),
    kernel correctness pinned by the kernel-marked suites and
    test_services' sharded tables test."""

    def _verifier(self, n, monkeypatch, executor="device"):
        import jax.numpy as jnp

        from tendermint_tpu.ops.ed25519_tables import host_build_key_tables

        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        pubs = tuple(p.pub_key.data for p in privs)
        mgr = MeshManager(executor=executor, reprobe_s=60.0)
        v = ShardedTableBatchVerifier(mesh=mgr, min_device_batch=1)
        tables, ok = host_build_key_tables(list(pubs))
        v._tables[v._cache_key(pubs)] = (pubs, jnp.asarray(tables), ok)
        calls = []

        def fake_tables_step():
            def _step(tables, s, h, r, lane_ok, power):
                calls.append(
                    {"lanes": s.shape[0], "lane_ok": np.asarray(lane_ok).copy()}
                )
                return np.asarray(lane_ok).copy(), int(
                    np.where(np.asarray(lane_ok), power, 0).sum()
                )

            return _step

        monkeypatch.setattr(mgr, "tables_step", fake_tables_step)
        # the sharded-tables placement needs a real Mesh even with the
        # fake step skipped on CPU — avoid it entirely
        monkeypatch.setattr(
            v, "_tables_for_mesh", lambda pk, m: v._tables_for(pk)
        )
        return privs, pubs, v, mgr, calls

    def _commits(self, privs, k, absent=()):
        commits = []
        for c in range(k):
            msgs = [b"c%d-%d" % (c, i) for i in range(len(privs))]
            sigs = [p.sign(m) for p, m in zip(privs, msgs)]
            for (ci, i) in absent:
                if ci == c:
                    msgs[i] = None
                    sigs[i] = None
            commits.append((msgs, sigs))
        return commits

    def test_shard_major_order_and_absent_lanes(self, monkeypatch):
        """The grid a fake echo-lane_ok step produces must equal the
        presence mask — proving the shard-major reorder and its inverse
        round-trip lane identity exactly."""
        privs, pubs, v, mgr, calls = self._verifier(16, monkeypatch)
        commits = self._commits(privs, 3, absent=[(1, 5), (2, 0)])
        grid = v.verify_commits(list(pubs), commits)
        assert grid.shape == (3, 16)
        want = np.ones((3, 16), dtype=bool)
        want[1, 5] = False
        want[2, 0] = False
        assert (grid == want).all()
        assert calls[-1]["lanes"] == 3 * 16

    def test_k_padding_from_per_shard_geometry(self, monkeypatch):
        """force_fused pads the K stack to multiples of 8 with absent
        commits (sliced off at finalize) — per-chip lane counts, the
        single-device assumption removed."""
        privs, pubs, v, mgr, calls = self._verifier(16, monkeypatch)
        commits = self._commits(privs, 3)
        grid = v.verify_commits(list(pubs), commits, force_fused=True)
        assert grid.shape == (3, 16)
        assert grid.all()
        assert calls[-1]["lanes"] == 8 * 16  # K 3 -> padded stack of 8

    def test_uneven_valset_falls_back_to_single_device(self, monkeypatch):
        """N=10 does not split over 8 chips: the call degrades to the
        legacy single-device table path, not an error."""
        privs, pubs, v, mgr, calls = self._verifier(10, monkeypatch)
        sentinel = []

        import tendermint_tpu.services.verifier as svc

        orig = svc.TableBatchVerifier.launch_verify_commits

        def spy(self, pubkeys, commits, force_fused=None):
            sentinel.append(len(pubkeys))
            return ("host", self._host_commit_loop(pubkeys, commits))

        monkeypatch.setattr(svc.TableBatchVerifier, "launch_verify_commits", spy)
        grid = v.verify_commits(list(pubs), self._commits(privs, 2))
        assert sentinel == [10]
        assert grid.shape == (2, 10) and grid.all()
        assert not calls  # mesh tables step never consulted
        assert orig is not None

    def test_shard_fault_mid_commit_grid_remeshes(self, monkeypatch):
        """A shard fault during a commit-grid launch re-meshes; with 16
        validators over 7 survivors the split is uneven, so the SAME
        call lands on the single-device path — degraded but serving."""
        privs, pubs, v, mgr, calls = self._verifier(16, monkeypatch)
        fail.set_device_fault("shard3")
        grid = v.verify_commits(list(pubs), self._commits(privs, 2))
        assert grid.shape == (2, 16) and grid.all()
        assert mgr.n_active == 7

    def test_host_executor_routes_flat_lanes(self, monkeypatch):
        privs, pubs, v, mgr, calls = self._verifier(
            16, monkeypatch, executor="host"
        )
        commits = self._commits(privs, 2, absent=[(0, 1)])
        grid = v.verify_commits(list(pubs), commits)
        want = np.ones((2, 16), dtype=bool)
        want[0, 1] = False
        assert (grid == want).all()
        assert not calls  # host executor has no tables program


class TestCoalescerMeshIntegration:
    def test_max_batch_scales_with_mesh_width(self):
        from tendermint_tpu.services.batcher import MAX_COALESCED_BATCH

        v, _mgr = _host_mesh_verifier()
        cv = CoalescingVerifier(ResilientVerifier(v))
        try:
            assert cv.coalescer._max_batch == MAX_COALESCED_BATCH * 8
        finally:
            cv.close()
        single = CoalescingVerifier(HostBatchVerifier())
        try:
            assert single.coalescer._max_batch == MAX_COALESCED_BATCH
        finally:
            single.close()

    def test_explicit_max_batch_stays_per_call(self):
        v, _mgr = _host_mesh_verifier()
        cv = CoalescingVerifier(ResilientVerifier(v), max_batch=64)
        try:
            assert cv.coalescer._max_batch == 64
        finally:
            cv.close()

    def test_drain_order_through_mid_coalesce_shard_fault(self):
        """Two consumers stream FIFO batches through one coalescer; a
        shard fault lands mid-stream. The re-mesh happens INSIDE the
        merged launch — every sub-handle still resolves, in per-consumer
        submission order, with correct verdicts (PR 4/5 discipline)."""
        v, mgr = _host_mesh_verifier()
        cv = CoalescingVerifier(
            ResilientVerifier(v), cache_size=0, window_s=0.002
        )
        try:
            batches = {
                tag: [
                    _triples(6, corrupt={r}, salt=b"%s%d" % (tag.encode(), r))
                    for r in range(3)
                ]
                for tag in ("consensus", "fastsync")
            }
            handles = {tag: [] for tag in batches}
            for r in range(3):
                for tag in batches:
                    handles[tag].append(
                        cv.verify_batch_async(batches[tag][r], consumer=tag)
                    )
                if r == 0:
                    fail.set_device_fault("shard6")
            for tag in batches:
                for r, h in enumerate(handles[tag]):
                    got = h.result(timeout=30)
                    want = np.ones(6, dtype=bool)
                    want[r] = False
                    assert (got == want).all(), (tag, r)
            assert mgr.n_active == 7
        finally:
            cv.close()


class TestDefaultStackComposition:
    def test_cpu_opt_in_builds_mesh_stack(self, monkeypatch):
        import tendermint_tpu.services.verifier as svc

        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "8")
        set_default_verifier(None)
        v = svc.default_verifier()
        try:
            assert isinstance(v, CoalescingVerifier)
            assert isinstance(v.inner, ResilientVerifier)
            assert isinstance(v.inner.primary, ShardedBatchVerifier)
            assert v.inner.primary.mesh.n_total == 8
            assert v.inner.mesh is v.inner.primary.mesh  # passthrough
        finally:
            v.close()
            set_default_verifier(None)

    def test_cpu_without_knob_stays_host(self, monkeypatch):
        import tendermint_tpu.services.verifier as svc

        monkeypatch.delenv("TENDERMINT_TPU_MESH_DEVICES", raising=False)
        set_default_verifier(None)
        v = svc.default_verifier()
        try:
            inner = getattr(v, "inner", v)
            assert not isinstance(inner, ResilientVerifier) or not isinstance(
                getattr(inner, "primary", None), ShardedBatchVerifier
            )
        finally:
            if hasattr(v, "close"):
                v.close()
            set_default_verifier(None)

    def test_force_single_device_knob(self, monkeypatch):
        import tendermint_tpu.services.verifier as svc

        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "1")
        set_default_verifier(None)
        v = svc.default_verifier()
        try:
            inner = getattr(v, "inner", v)
            assert not isinstance(
                getattr(inner, "primary", None), ShardedBatchVerifier
            )
        finally:
            if hasattr(v, "close"):
                v.close()
            set_default_verifier(None)

    def test_auto_hasher_cpu_opt_in_gets_mesh(self, monkeypatch):
        from tendermint_tpu.services.hasher import auto_hasher
        from tendermint_tpu.services.resilient import ResilientTreeHasher

        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "8")
        h = auto_hasher()
        assert isinstance(h, ResilientTreeHasher)
        assert h.mesh is not None and h.mesh.n_total == 8
        assert h.primary.mesh is h.mesh

    def test_auto_hasher_without_knob_stays_host(self, monkeypatch):
        from tendermint_tpu.services.hasher import TreeHasher, auto_hasher

        monkeypatch.delenv("TENDERMINT_TPU_MESH_DEVICES", raising=False)
        h = auto_hasher()
        assert type(h) is TreeHasher and h.backend == "host"


class TestMeshHasherLane:
    def test_host_executor_leaf_hashes_match_and_remesh(self):
        from tendermint_tpu.merkle.simple import leaf_hash
        from tendermint_tpu.services.hasher import TreeHasher

        mgr = MeshManager(executor="host", reprobe_s=60.0)
        th = TreeHasher(backend="device", min_device_leaves=2, mesh=mgr)
        items = [b"leaf-%d" % i for i in range(37)]
        fail.set_device_fault("shard1")
        out = th.leaf_hashes(items)
        assert out == [leaf_hash(x) for x in items]
        assert mgr.n_active == 7  # the hash lane re-meshed too

    def test_hash_lane_exhaustion_hits_hash_breaker(self):
        from tendermint_tpu.merkle.simple import leaf_hash
        from tendermint_tpu.services.hasher import TreeHasher
        from tendermint_tpu.services.resilient import ResilientTreeHasher

        mgr = MeshManager(executor="host")
        th = ResilientTreeHasher(
            TreeHasher(backend="device", min_device_leaves=2, mesh=mgr),
            TreeHasher(backend="host"),
            max_retries=0,
        )
        for i in range(8):
            fail.set_device_fault(f"shard{i}")
        fallback0 = _counter(
            "tendermint_device_fallback_calls_total", kind="hash"
        )
        items = [b"x%d" % i for i in range(9)]
        assert th.leaf_hashes(items) == [leaf_hash(x) for x in items]
        assert (
            _counter("tendermint_device_fallback_calls_total", kind="hash")
            == fallback0 + 1
        )


class TestMeshNemesis:
    def test_live_net_loses_shard_mid_height_keeps_committing(self, tmp_path):
        """The chaos acceptance: a running 4-validator net whose verify
        spine is the full production mesh stack (coalescer -> resilient
        -> sharded mesh, host-emulated executor) loses one shard
        mid-height. The mesh re-meshes onto 7 survivors and the chain
        keeps committing — no fork, NO breaker trip (re-mesh absorbs the
        fault below the breaker); clearing the fault restores the full
        mesh. The whole cycle is asserted through exported telemetry."""
        from tendermint_tpu.testing import Nemesis

        stacks = []

        def factory(_i):
            mgr = MeshManager(executor="host", reprobe_s=0.5)
            cv = CoalescingVerifier(
                ResilientVerifier(
                    ShardedBatchVerifier(mesh=mgr, min_device_batch=1),
                    max_retries=0,
                ),
                cache_size=4096,
            )
            stacks.append((cv, mgr))
            return cv

        try:
            with Nemesis(
                4, home=str(tmp_path), verifier_factory=factory
            ) as net:
                net.wait_height(2, timeout=60)
                base = net.mesh_baseline()
                trips0 = _counter(
                    "tendermint_breaker_transitions_total",
                    kind="verify",
                    to="open",
                )

                fail.set_device_fault("shard2")  # one chip dies mid-height
                net.wait_progress(delta=2, timeout=60)  # commits continue
                net.assert_mesh_degraded(base)
                # every node's mesh degraded to 7 survivors ...
                degraded = [m.n_active for _cv, m in stacks]
                assert all(a == 7 for a in degraded), degraded
                # ... WITHOUT tripping any verify breaker (re-mesh is a
                # layer below the PR 1 degradation ladder)
                assert (
                    _counter(
                        "tendermint_breaker_transitions_total",
                        kind="verify",
                        to="open",
                    )
                    == trips0
                )
                net.check_invariants()  # no fork while degraded

                fail.clear_device_faults()  # the chip comes back
                net.assert_mesh_restored(base)
                net.wait_progress(delta=2, timeout=60)
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    if all(m.n_active == 8 for _cv, m in stacks):
                        break
                    time.sleep(0.1)
                actives = [m.n_active for _cv, m in stacks]
                assert all(a == 8 for a in actives), actives
                net.check_invariants()
        finally:
            for cv, _m in stacks:
                cv.close()


class TestDefaultStackRealKernelParity:
    """The acceptance criterion, tier-1: with the 8-virtual-device CPU
    mesh, the DEFAULT verifier stack (coalescer -> resilient -> sharded
    device) verifies batches and tallies commit power sharded over all
    8 devices, bit-identical to the single-device kernel and the host
    library. ONE ladder compile (~70 s XLA:CPU): every call here reuses
    the same 64-row global shape, so the jit cache serves all of them.
    """

    def test_default_stack_sharded_verify_parity_and_tally(self, monkeypatch):
        import tendermint_tpu.services.verifier as svc

        monkeypatch.setenv("TENDERMINT_TPU_MESH_DEVICES", "8")
        monkeypatch.setattr(svc, "DEVICE_MIN_BATCH", 1)
        set_default_verifier(None)
        v = svc.default_verifier()
        try:
            assert isinstance(v, CoalescingVerifier)
            sharded = v.inner.primary
            assert isinstance(sharded, ShardedBatchVerifier)
            mgr = sharded.mesh
            assert mgr.n_total == 8 and mgr.executor == "device"

            triples = _triples(10, corrupt={3, 7})
            want_host = HostBatchVerifier().verify_batch(triples)
            want_dev = svc.DeviceBatchVerifier(min_device_batch=1).verify_batch(
                triples
            )
            assert (want_host == want_dev).all()  # single-device oracle

            got = v.verify_batch(triples)  # compiles the sharded step
            assert (got == want_host).all()

            # the coalesced async lane rides the SAME mesh executable
            fresh = _triples(10, corrupt={1}, salt=b"async")
            want2 = HostBatchVerifier().verify_batch(fresh)
            h = v.verify_batch_async(fresh, consumer="consensus")
            assert (h.result(timeout=120) == want2).all()

            # commit tally: psum-reduced on device across all 8 shards,
            # equal to the host-side power sum over valid lanes
            powers = np.arange(1, 11, dtype=np.int32)
            mask, tally = sharded.verify_batch_with_powers(triples, powers)
            assert (mask == want_host).all()
            assert tally == int(powers[want_host].sum())

            # zero pad rows verify False on the REAL kernel (the
            # property the padding rule depends on) — same 64-row shape
            zeros = np.zeros((64, 32), dtype=np.uint8)
            zero_pw = np.zeros(64, dtype=np.int32)
            ok, total = mgr.verify_step()(zeros, zeros, zeros, zeros, zero_pw)
            assert not np.asarray(ok).any()
            assert int(total) == 0

            # commit grids flatten onto the same sharded lane
            pubs = [t[0] for t in triples]
            msgs = [t[1] for t in triples]
            sigs = [t[2] for t in triples]
            grid = sharded.verify_commits(pubs, [(msgs, sigs), (msgs, sigs)])
            assert (grid == np.stack([want_host, want_host])).all()
        finally:
            v.close()
            set_default_verifier(None)


@pytest.mark.kernel
@pytest.mark.slow
class TestMeshRealKernelMatrix:
    """Real shard_map ladder compiles beyond the single tier-1 parity
    test: survivor re-mesh on the live kernel and the sharded tables
    program through the production class."""

    def test_real_kernel_survivor_remesh(self):
        mgr = MeshManager(reprobe_s=60.0)
        v = ShardedBatchVerifier(mesh=mgr, min_device_batch=1)
        triples = _triples(10, corrupt={4})
        want = HostBatchVerifier().verify_batch(triples)
        assert (v.verify_batch(triples) == want).all()
        fail.set_device_fault("shard0")
        got = v.verify_batch(triples)  # recompiles over 7 survivors
        assert (got == want).all()
        assert mgr.n_active == 7

    def test_real_sharded_tables_through_production_class(self):
        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(16)]
        pubs = [p.pub_key.data for p in privs]
        import jax.numpy as jnp

        from tendermint_tpu.ops.ed25519_tables import host_build_key_tables

        mgr = MeshManager(reprobe_s=60.0)
        v = ShardedTableBatchVerifier(mesh=mgr, min_device_batch=1)
        tables, ok = host_build_key_tables(pubs)
        v._tables[v._cache_key(tuple(pubs))] = (
            tuple(pubs),
            jnp.asarray(tables),
            ok,
        )
        msgs = [b"t-%d" % i for i in range(16)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        sigs[5] = sigs[5][:10] + bytes([sigs[5][10] ^ 1]) + sigs[5][11:]
        grid = v.verify_commits(pubs, [(msgs, sigs), (msgs, sigs)])
        want = np.ones((2, 16), dtype=bool)
        want[:, 5] = False
        assert (grid == want).all()
