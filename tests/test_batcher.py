"""Cross-subsystem verify coalescer + verified-signature dedup cache
(`services/batcher.py`).

Covers the PR's acceptance surface: negatives are never cached (a
forged sig for a cached-positive triple's pubkey is still rejected),
cache keys cannot alias across field boundaries (byte-boundary fuzz),
round-robin fairness under a starving consumer, all three flush reasons
(window/size/barrier), per-consumer drain-order preservation with
device faults mid-coalesce, dedup-cache concurrency, and the nemesis
assertion that cache hits never mask a breaker-faulted launch. All
CPU-safe, no kernel marks.
"""

from __future__ import annotations

import random
import threading
import time

import numpy as np
import pytest

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.services.batcher import (
    CoalescingVerifier,
    VerifiedSigCache,
    VerifyCoalescer,
    consumer_kwargs,
)
from tendermint_tpu.services.verifier import BatchVerifier, HostBatchVerifier
from tendermint_tpu.telemetry import REGISTRY
from tendermint_tpu.utils import fail


@pytest.fixture(autouse=True)
def _clean_faults():
    fail.clear_device_faults()
    yield
    fail.clear_device_faults()


def _triples(n, salt=b"", start=0):
    out = []
    for i in range(start, start + n):
        priv = gen_priv_key(bytes([i % 251 + 1]) * 32)
        msg = b"batcher-msg-%d-" % i + salt
        out.append((priv.pub_key.data, msg, priv.sign(msg)))
    return out


def _counter(name, **labels):
    return REGISTRY.counter_value(name, **labels)


class _CountingVerifier(BatchVerifier):
    """Host verifier that records every underlying verify call."""

    def __init__(self):
        super().__init__()
        self._host = HostBatchVerifier()
        self.calls: list[int] = []
        self.lock = threading.Lock()

    def verify_batch(self, triples):
        with self.lock:
            self.calls.append(len(triples))
        return self._host.verify_batch(triples)


class TestVerifiedSigCache:
    def test_positive_only_contract_and_hit_metrics(self):
        cache = VerifiedSigCache(capacity=64)
        (pk, msg, sig) = _triples(1)[0]
        key = VerifiedSigCache.key(pk, msg, sig)
        h0 = _counter("tendermint_verify_cache_hits_total")
        m0 = _counter("tendermint_verify_cache_misses_total")
        assert not cache.hit(key)
        cache.add(key)
        assert cache.hit(key)
        assert _counter("tendermint_verify_cache_hits_total") == h0 + 1
        assert _counter("tendermint_verify_cache_misses_total") == m0 + 1

    def test_lru_eviction_bounded_and_counted(self):
        cache = VerifiedSigCache(capacity=VerifiedSigCache.SHARDS * 4)
        e0 = _counter("tendermint_verify_cache_evictions_total")
        for i in range(VerifiedSigCache.SHARDS * 16):
            cache.add(VerifiedSigCache.key(b"\x01" * 32, b"m%d" % i, b"\x02" * 64))
        assert len(cache) <= cache.capacity
        assert _counter("tendermint_verify_cache_evictions_total") > e0

    def test_key_never_aliases_across_field_boundaries(self):
        """Property fuzz: re-split the same concatenated bytes at every
        boundary — distinct (pubkey, msg, sig) splits must key apart
        (the raw-concat key would collide on ALL of these)."""
        rng = random.Random(0xBEEF)
        for _trial in range(50):
            blob = bytes(rng.getrandbits(8) for _ in range(rng.randint(3, 48)))
            keys = set()
            splits = 0
            for a in range(len(blob) + 1):
                for b in range(a, len(blob) + 1):
                    keys.add(VerifiedSigCache.key(blob[:a], blob[a:b], blob[b:]))
                    splits += 1
            assert len(keys) == splits

    def test_shifted_msg_vs_pubkey_boundary(self):
        pk, msg = b"\xaa" * 32, b"hello-world"
        sig = b"\x05" * 64
        k1 = VerifiedSigCache.key(pk, msg, sig)
        k2 = VerifiedSigCache.key(pk + msg[:1], msg[1:], sig)
        k3 = VerifiedSigCache.key(pk, msg + sig[:1], sig[1:])
        assert len({k1, k2, k3}) == 3

    def test_concurrent_add_and_hit(self):
        cache = VerifiedSigCache(capacity=1024)
        keys = [
            VerifiedSigCache.key(b"\x07" * 32, b"c%d" % i, b"\x01" * 64)
            for i in range(256)
        ]
        errors = []

        def worker(seed):
            rng = random.Random(seed)
            try:
                for _ in range(500):
                    k = keys[rng.randrange(len(keys))]
                    if rng.random() < 0.5:
                        cache.add(k)
                    else:
                        cache.hit(k)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(cache) <= cache.capacity


class TestNegativeVerdictsNeverCached:
    def test_forged_sig_for_cached_positive_pubkey_rejected(self):
        v = CoalescingVerifier(HostBatchVerifier(), window_s=0.001)
        try:
            (pk, msg, sig) = _triples(1, salt=b"neg")[0]
            assert v.verify_batch([(pk, msg, sig)]).all()
            # the genuine triple is now cached-positive; forging a sig
            # for the SAME pubkey (same and different msg) must still
            # reject — the cache keys on the full triple and negatives
            # never enter
            forged = b"\xff" * 64
            assert not v.verify_batch([(pk, msg, forged)]).any()
            assert not v.verify_batch([(pk, b"other-msg", forged)]).any()
            assert not v.verify_batch_async(
                [(pk, msg, forged)], consumer="rpc"
            ).result(timeout=10).any()
            # and the failures did not poison the cache
            assert VerifiedSigCache.key(pk, msg, forged) not in v.cache
            assert v.verify_batch([(pk, msg, sig)]).all()
        finally:
            v.close()

    def test_failed_lane_reverifies_every_time(self):
        counting = _CountingVerifier()
        v = CoalescingVerifier(counting, window_s=0.001)
        try:
            (pk, msg, _sig) = _triples(1, salt=b"re")[0]
            bad = (pk, msg, b"\x01" * 64)
            for _ in range(3):
                assert not v.verify_batch([bad]).any()
            # all three attempts reached the backend — nothing served
            # the forged triple from cache
            assert len(counting.calls) == 3
        finally:
            v.close()


class TestFlushReasons:
    @staticmethod
    def _wait_done(*handles, timeout=10.0):
        """Wait for flush WITHOUT joining — result() on an unflushed
        request would trigger a barrier and mask the reason under test."""
        deadline = time.monotonic() + timeout
        while not all(h.done() for h in handles):
            if time.monotonic() > deadline:
                raise TimeoutError("coalesced handles never resolved")
            time.sleep(0.002)

    def test_window_flush_merges_concurrent_consumers(self):
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=0.05, max_batch=4096)
        try:
            f0 = _counter("tendermint_batcher_flush_total", reason="window")
            h1 = v.submit(_triples(3, salt=b"w1"), consumer="consensus")
            h2 = v.submit(_triples(3, salt=b"w2", start=100), consumer="fastsync")
            # neither consumer joins: the window timer must flush both
            # as ONE merged launch
            self._wait_done(h1, h2)
            assert h1.result(timeout=10).all()
            assert h2.result(timeout=10).all()
            assert counting.calls == [6]
            assert (
                _counter("tendermint_batcher_flush_total", reason="window")
                == f0 + 1
            )
        finally:
            v.close()

    def test_size_flush_fires_before_window(self):
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=10.0, max_batch=4)
        try:
            s0 = _counter("tendermint_batcher_flush_total", reason="size")
            h = v.submit(_triples(4, salt=b"sz"), consumer="consensus")
            self._wait_done(h)  # resolved long before the 10 s window
            assert h.result(timeout=10).all()
            assert (
                _counter("tendermint_batcher_flush_total", reason="size")
                == s0 + 1
            )
        finally:
            v.close()

    def test_barrier_flush_on_early_join(self):
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=30.0, max_batch=4096)
        try:
            b0 = _counter("tendermint_batcher_flush_total", reason="barrier")
            t0 = time.perf_counter()
            h = v.submit(_triples(2, salt=b"bar"), consumer="statesync")
            assert h.result(timeout=10).all()
            assert time.perf_counter() - t0 < 5.0  # did not wait the window
            assert (
                _counter("tendermint_batcher_flush_total", reason="barrier")
                == b0 + 1
            )
        finally:
            v.close()

    def test_coalesce_factor_and_wait_telemetry_move(self):
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=0.05)
        try:
            fam = REGISTRY.get("tendermint_batcher_coalesce_factor")
            c0 = fam.value["count"]
            h1 = v.submit(_triples(1, salt=b"cf1"), consumer="consensus")
            h2 = v.submit(_triples(1, salt=b"cf2", start=50), consumer="rpc")
            h1.result(timeout=10)
            h2.result(timeout=10)
            snap = fam.value
            assert snap["count"] > c0
            wait = REGISTRY.get("tendermint_batcher_wait_seconds")
            assert wait.labels(consumer="consensus").value["count"] > 0
        finally:
            v.close()


class TestFairness:
    def test_starving_consumer_rides_the_first_take(self, monkeypatch):
        """A hot consumer with a deep backlog must not starve a
        one-request consumer: the round-robin take puts the starving
        request into the very next merged launch, not behind the whole
        backlog. Exercised at the `_take_locked` level with the flusher
        parked so the take composition is deterministic."""
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=30.0, max_batch=8)
        monkeypatch.setattr(v, "_ensure_threads", lambda: None)
        hot = [
            v.submit(_triples(4, salt=b"hot%d" % i, start=10 * i), "fastsync")
            for i in range(6)
        ]
        starving = v.submit(_triples(1, salt=b"starve", start=200), "rpc")
        with v._cond:
            first = v._take_locked()
        consumers = [r.consumer for r in first]
        assert "rpc" in consumers, f"starving consumer not in first take: {consumers}"
        # one-per-consumer cycles: hot[0], starving, hot[1] fill the cap
        assert consumers == ["fastsync", "rpc", "fastsync"]
        # per-consumer FIFO: the hot requests taken are the OLDEST two
        assert first[0] is hot[0]._req and first[2] is hot[1]._req
        v.close()

    def test_rotation_does_not_pin_the_first_consumer(self, monkeypatch):
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=30.0, max_batch=1)
        monkeypatch.setattr(v, "_ensure_threads", lambda: None)
        v.submit(_triples(1, salt=b"a"), "consensus")
        v.submit(_triples(1, salt=b"b", start=50), "rpc")
        v.submit(_triples(1, salt=b"c", start=60), "consensus")
        v.submit(_triples(1, salt=b"d", start=70), "rpc")
        takes = []
        for _ in range(4):
            with v._cond:
                takes.extend(r.consumer for r in v._take_locked())
        # both consumers got served in the first two takes (rotation),
        # not consensus twice then rpc twice
        assert set(takes[:2]) == {"consensus", "rpc"}
        v.close()

    def test_per_consumer_fifo_order_is_preserved(self):
        counting = _CountingVerifier()
        v = VerifyCoalescer(counting, cache=None, window_s=30.0, max_batch=3)
        try:
            batches = [_triples(2, salt=b"fifo%d" % i, start=20 * i) for i in range(4)]
            handles = [v.submit(b, consumer="consensus") for b in batches]
            v.request_barrier()
            # joining in submission order always succeeds (no handle
            # depends on a later flush than a successor's)
            for h in handles:
                assert h.result(timeout=10).all()
        finally:
            v.close()


class TestFaultsMidCoalesce:
    def test_drain_order_with_breaker_faults(self):
        """Faults injected mid-coalesce degrade through the resilient
        handle INSIDE the merged launch: every sub-handle still resolves
        to host-truth verdicts, in per-consumer submission order."""
        from tendermint_tpu.services.resilient import ResilientVerifier
        from tendermint_tpu.services.verifier import DeviceBatchVerifier

        # default min_device_batch keeps post-fault launches on the host
        # short-circuit (an actual XLA:CPU curve compile has no place in
        # tier-1); the injected faults fire BEFORE the backend runs
        inner = ResilientVerifier(DeviceBatchVerifier())
        v = CoalescingVerifier(inner, cache_size=0, window_s=0.005)
        try:
            fail.set_device_fault("verify", 2)  # first two launches fault
            good = _triples(3, salt=b"fault")
            bad = [(good[0][0], good[0][1], b"\x09" * 64)]
            handles = []
            for i in range(4):
                handles.append(
                    v.verify_batch_async(good, consumer="consensus")
                )
                handles.append(v.verify_batch_async(bad, consumer="rpc"))
            for i, h in enumerate(handles):
                out = h.result(timeout=20)
                if i % 2 == 0:
                    assert out.all(), f"batch {i} lost verdicts to the fault"
                else:
                    assert not out.any(), f"forged batch {i} passed"
        finally:
            v.close()

    def test_cache_hits_never_mask_a_breaker_faulted_launch(self):
        """Nemesis assertion: a proven-positive cache entry must come
        from a REAL verification (device or host fallback), and cache
        hits must never turn a faulted launch into a false positive for
        novel triples sharing the batch."""
        from tendermint_tpu.services.resilient import ResilientVerifier
        from tendermint_tpu.services.verifier import DeviceBatchVerifier

        inner = ResilientVerifier(DeviceBatchVerifier())
        v = CoalescingVerifier(inner, window_s=0.005)
        try:
            fb0 = _counter(
                "tendermint_device_fallback_calls_total", kind="verify"
            )
            fail.set_device_fault("verify")  # every device launch faults
            good = _triples(2, salt=b"mask")
            forged = (good[0][0], good[0][1], b"\x0c" * 64)
            # first pass: faulted launch -> host fallback proves the
            # positives; those (and only those) enter the cache
            assert v.verify_batch_async(good, consumer="consensus").result(
                timeout=20
            ).all()
            assert (
                _counter(
                    "tendermint_device_fallback_calls_total", kind="verify"
                )
                > fb0
            )
            # second pass mixes cached positives with a forged triple:
            # the cached lanes answer True, the forged lane re-verifies
            # (still under fault -> host fallback) and must reject
            out = v.verify_batch_async(
                good + [forged], consumer="consensus"
            ).result(timeout=20)
            assert out[0] and out[1] and not out[2]
            assert VerifiedSigCache.key(*forged) not in v.cache
        finally:
            v.close()


class TestCommitGridDedup:
    def _commit_fixture(self, n=4):
        triples = _triples(n, salt=b"grid")
        pubs = [t[0] for t in triples]
        commits = [([t[1] for t in triples], [t[2] for t in triples])]
        return pubs, commits, triples

    def test_cached_lanes_skip_the_backend(self):
        counting = _CountingVerifier()
        v = CoalescingVerifier(counting, window_s=0.001)
        try:
            pubs, commits, triples = self._commit_fixture()
            assert v.verify_batch(triples).all()  # gossip pass: populate
            calls_before = len(counting.calls)
            grid = v.verify_commits(pubs, commits)  # commit pass
            assert grid.all()
            # every lane was cached -> no backend call for the grid
            assert len(counting.calls) == calls_before
        finally:
            v.close()

    def test_partial_cache_sends_only_novel_lanes(self):
        counting = _CountingVerifier()
        v = CoalescingVerifier(counting, window_s=0.001)
        try:
            pubs, commits, triples = self._commit_fixture()
            assert v.verify_batch(triples[:2]).all()  # half cached
            grid = v.verify_commits_async(pubs, commits, consumer="fastsync")
            assert grid.result(timeout=10).all()
            # the grid launch carried exactly the two novel lanes
            assert counting.calls[-1] == 2
        finally:
            v.close()

    def test_forged_lane_rejected_despite_cached_neighbors(self):
        v = CoalescingVerifier(HostBatchVerifier(), window_s=0.001)
        try:
            pubs, commits, triples = self._commit_fixture()
            assert v.verify_batch(triples).all()
            msgs, sigs = [list(x) for x in commits[0]]
            sigs[1] = b"\x0d" * 64  # forge one lane
            grid = v.verify_commits(pubs, [(msgs, sigs)])
            assert grid[0, 0] and grid[0, 2] and grid[0, 3]
            assert not grid[0, 1]
        finally:
            v.close()


class TestValidatorSetRouting:
    def _chain_fixture(self, n_vals=4):
        from tendermint_tpu.testing.nemesis import make_genesis
        from tendermint_tpu.types import BlockID
        from tendermint_tpu.types.part_set import PartSetHeader
        from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote
        from tendermint_tpu.types.vote_set import VoteSet

        genesis, privs = make_genesis(n_vals, chain_id="batcher-vs")
        valset = genesis.validator_set()
        block_id = BlockID(b"\x11" * 20, PartSetHeader(total=1, hash=b"\x22" * 20))
        vote_set = VoteSet("batcher-vs", 5, 0, VOTE_TYPE_PRECOMMIT, valset)
        for i, priv in enumerate(privs):
            vote = Vote(
                validator_address=priv.address,
                validator_index=i,
                height=5,
                round=0,
                timestamp=1,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=block_id,
            )
            vote_set.add_vote(priv.sign_vote("batcher-vs", vote))
        return valset, block_id, vote_set.make_commit()

    def test_verify_commit_batched_through_coalescer(self):
        valset, block_id, commit = self._chain_fixture()
        v = CoalescingVerifier(HostBatchVerifier(), window_s=0.001)
        try:
            valset.verify_commit_batched(
                "batcher-vs",
                [(block_id, 5, commit)],
                verifier=v,
                consumer="statesync",
            )
            # second walk over the same commit is answered by the cache
            h0 = _counter("tendermint_verify_cache_hits_total")
            valset.verify_commit_batched(
                "batcher-vs", [(block_id, 5, commit)], verifier=v,
                consumer="rpc",
            )
            assert _counter("tendermint_verify_cache_hits_total") >= h0 + 4
        finally:
            v.close()

    def test_certifier_walk_hits_the_cache(self):
        """The light-client/statesync certifier re-walk: certifying the
        same FullCommit twice verifies its signatures once."""
        from tendermint_tpu.certifiers.certifier import StaticCertifier

        valset, block_id, commit = self._chain_fixture()
        v = CoalescingVerifier(HostBatchVerifier(), window_s=0.001)
        try:
            entries = [(block_id, 5, commit)]
            cert = StaticCertifier("batcher-vs", valset, verifier=v)
            m0 = _counter("tendermint_verify_cache_misses_total")
            valset.verify_commit_batched(
                "batcher-vs", entries, verifier=v, consumer=cert.consumer
            )
            misses_first = (
                _counter("tendermint_verify_cache_misses_total") - m0
            )
            assert misses_first >= 4
            m1 = _counter("tendermint_verify_cache_misses_total")
            valset.verify_commit_batched(
                "batcher-vs", entries, verifier=v, consumer=cert.consumer
            )
            assert _counter("tendermint_verify_cache_misses_total") == m1
        finally:
            v.close()

    def test_consumer_kwargs_gate(self):
        v = CoalescingVerifier(HostBatchVerifier(), window_s=0.001)
        try:
            assert consumer_kwargs(v, "rpc") == {"consumer": "rpc"}

            class _Minimal:
                def verify_batch(self, triples):
                    return np.ones(len(triples), dtype=bool)

            assert consumer_kwargs(_Minimal(), "rpc") == {}
        finally:
            v.close()


class TestDedupConcurrency:
    def test_overlapping_submissions_from_many_threads(self):
        counting = _CountingVerifier()
        v = CoalescingVerifier(counting, window_s=0.002)
        try:
            shared = _triples(8, salt=b"conc")
            errors = []

            def worker(seed):
                rng = random.Random(seed)
                try:
                    for _ in range(20):
                        batch = rng.sample(shared, rng.randint(1, len(shared)))
                        out = v.verify_batch_async(
                            batch, consumer=f"c{seed % 4}"
                        ).result(timeout=20)
                        if not np.asarray(out).all():
                            errors.append(("verdict", batch))
                except Exception as e:
                    errors.append(("exc", e))

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            # dedup engaged: far fewer triples reached the backend than
            # the ~720 requested (8 threads x 20 rounds x avg 4.5);
            # generous bound absorbs pre-cache concurrent first rounds
            assert sum(counting.calls) < 300
        finally:
            v.close()


class TestAdaptiveKnobs:
    def test_pipeline_depth_env_wins(self, monkeypatch):
        from tendermint_tpu.blockchain.reactor import adaptive_pipeline_depth

        monkeypatch.setenv("TENDERMINT_TPU_PIPELINE_DEPTH", "3")
        assert adaptive_pipeline_depth() == 3

    def test_pipeline_depth_from_ratio_clamped(self, monkeypatch):
        from tendermint_tpu.blockchain.reactor import adaptive_pipeline_depth
        from tendermint_tpu.services import dispatch as dispatch_mod

        monkeypatch.delenv("TENDERMINT_TPU_PIPELINE_DEPTH", raising=False)
        # depth = clamp(1 + round(launch:apply), 1, 4); None (no samples
        # yet) keeps the classic double-buffer default
        for ratio, want in ((None, 2), (0.2, 1), (1.0, 2), (2.6, 4), (50.0, 4)):
            monkeypatch.setattr(
                dispatch_mod,
                "measured_launch_apply_ratio",
                lambda queue=None, r=ratio: r,
            )
            assert adaptive_pipeline_depth() == want

    def test_launch_apply_ratio_from_overlap_histogram(self):
        from tendermint_tpu.services.dispatch import (
            measured_launch_apply_ratio,
        )
        from tendermint_tpu.telemetry import metrics as _metrics

        _metrics.DISPATCH_OVERLAP.labels(queue="ratio-test").observe(0.5)
        r = measured_launch_apply_ratio("ratio-test")
        assert r == pytest.approx(1.0)
        assert measured_launch_apply_ratio("no-such-queue") is None
