"""Pallas generic-ladder kernel correctness
(`ops/ed25519_ladder_pallas` — the ad-hoc verify fast path; reference
semantics `types/validator_set.go:284-349` VerifyCommitAny).

The kernel body is plain plane-list math (`_double_planes`,
`_madd_planes`, 4-way masked select) — these tests run EXACTLY that
code as jnp ops against the XLA scan kernel, so the algorithm is gated
on CPU without pallas interpret mode (measured >10 min per 1024-lane
interpreted call — unusable as a test budget). The pallas-call
mechanics (BlockSpecs, grid, VMEM scratch) are exercised on real TPU
runs (bench + the tpu-gated test below)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.ops.ed25519_kernel import (
    NLIMBS,
    pt_double,
    prepare_batch,
    verify_kernel,
)

# kernel suites are also 'slow': tier-1 CI selects -m 'not slow' (which
# overrides the ini's 'not kernel' default), and these compile device
# kernels on XLA:CPU for minutes. 'pytest -m kernel' still runs them.
pytestmark = [pytest.mark.kernel, pytest.mark.slow]


def _batch(n, corrupt=(), bad_pub=(), bad_r=()):
    privs = [gen_priv_key(bytes([i % 250 + 1, i // 250 + 1]) + b"\0" * 30) for i in range(n)]
    msgs = [b"ladder-%d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    pubs = [p.pub_key.data for p in privs]
    for i in corrupt:
        sigs[i] = sigs[i][:8] + bytes([sigs[i][8] ^ 1]) + sigs[i][9:]
    for i in bad_pub:
        pubs[i] = b"\xff" * 32  # non-canonical y
    for i in bad_r:
        sigs[i] = bytes([sigs[i][0] ^ 1]) + sigs[i][1:]  # corrupt R
    return pubs, msgs, sigs


def _planes_from_limbs(a):
    """(B, 20) limb array -> list of 20 (8, B//8) planes (kernel layout)."""
    b = a.shape[0]
    return [a[:, i].reshape(8, b // 8) for i in range(NLIMBS)]


def _limbs_from_planes(planes):
    return jnp.stack([p.reshape(-1) for p in planes], axis=-1)


class TestKernelMath:
    def test_double_planes_matches_pt_double(self):
        """The kernel's extended doubling (new in round 5) must match
        pt_double bit-for-bit on random on-curve points."""
        from tendermint_tpu.ops.ed25519_ladder_pallas import _double_planes

        n = 64
        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        pubs = np.stack(
            [np.frombuffer(p.pub_key.data, dtype=np.uint8) for p in privs]
        )
        from tendermint_tpu.ops.ed25519_kernel import fe_canon, pt_decompress

        pt, ok = pt_decompress(jnp.asarray(pubs))
        assert np.asarray(ok).all()
        want = pt_double(pt)
        got_planes = _double_planes(tuple(_planes_from_limbs(c) for c in pt))
        got = tuple(_limbs_from_planes(p) for p in got_planes)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(
                np.asarray(fe_canon(w)), np.asarray(fe_canon(g))
            )

    def test_ladder_semantics_on_host_bigints(self):
        """Prove the kernel's ALGORITHM — msb-first digit schedule +
        {O, B, -A, B-A} entry mapping + double-then-add recurrence —
        computes [S]B + [h](-A), by emulating the exact per-step
        recurrence with host big-int point arithmetic on the module's
        own `_ladder_digits` output. Pure host: no XLA compile (the
        plane-op step body is gated by test_double_planes and the fused
        kernel suites; pallas plumbing by TPU runs/bench)."""
        import jax.numpy as jnp

        from tendermint_tpu.ops import ed25519_ladder_pallas as lp
        from tendermint_tpu.ops.ed25519_kernel import BX, BY, L, P
        from tendermint_tpu.ops.ed25519_tables import (
            _hadd,
            _host_decompress,
            host_affine,
            host_scalar_mul,
        )

        n = 4
        privs = [gen_priv_key(bytes([7 * i + 1]) * 32) for i in range(n)]
        msgs = [b"sem-%d" % i for i in range(n)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        pubs = [p.pub_key.data for p in privs]
        pub, r, s, h, pre = prepare_batch(pubs, msgs, sigs)
        assert pre.all()

        dig = np.asarray(lp._ladder_digits(jnp.asarray(s), jnp.asarray(h)))
        b_ext = (BX, BY, 1, BX * BY % P)
        ident = (0, 1, 1, 0)
        for lane in range(n):
            ax, ay = _host_decompress(pubs[lane])
            neg_a = (P - ax, ay, 1, (P - ax) * ay % P)
            table = [ident, b_ext, neg_a, _hadd(b_ext, neg_a)]
            acc = ident
            for t in range(dig.shape[1]):
                acc = _hadd(acc, acc)  # double
                acc = _hadd(acc, table[dig[lane, t]])
            s_int = int.from_bytes(bytes(sigs[lane][32:]), "little")
            h_int = int.from_bytes(bytes(h[lane]), "little")
            assert s_int < L and h_int < L
            want = _hadd(
                host_scalar_mul(s_int, b_ext), host_scalar_mul(h_int, neg_a)
            )
            assert host_affine(acc) == host_affine(want), f"lane {lane}"

    def test_build_inputs_entries_match_host_precomp(self):
        """The prologue's per-lane gtab rows must hold the affine
        ypx/ymx/t2d precomp of {O, B, -A, B-A} exactly (host-int cross
        check, eager — a handful of lanes, no kernel compile)."""
        import jax.numpy as jnp

        from tendermint_tpu.ops import ed25519_ladder_pallas as lp
        from tendermint_tpu.ops.ed25519_kernel import BX, BY, D2, P, _limbs_to_int
        from tendermint_tpu.ops.ed25519_tables import (
            _hadd,
            _host_decompress,
            host_affine,
        )

        n = 1024  # _tile_lanes minimum; eager decompress is the cost
        privs = [gen_priv_key(bytes([i % 250 + 1, i // 250 + 2]) + b"\0" * 30) for i in range(n)]
        pubs = [p.pub_key.data for p in privs]
        msgs = [b"pre-%d" % i for i in range(n)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        pub, r, s, h, _pre = prepare_batch(pubs, msgs, sigs)
        gtab, dig, a_ok = lp._build_inputs(
            jnp.asarray(pub), jnp.asarray(s), jnp.asarray(h), 1024
        )
        assert np.asarray(a_ok).all()
        gt = np.asarray(gtab)  # (1, 4, 60, 8, 128)

        def precomp(x, y):
            return ((y + x) % P, (y - x) % P, D2 * x % P * y % P)

        b_ext = (BX, BY, 1, BX * BY % P)
        for lane in (0, 1, 511, 1023):
            row, col = lane // 128, lane % 128
            ax, ay = _host_decompress(pubs[lane])
            neg_a = (P - ax, ay, 1, (P - ax) * ay % P)
            expected = [
                (1, 1, 0),
                precomp(BX, BY),
                precomp(P - ax, ay),
                precomp(*host_affine(_hadd(b_ext, neg_a))),
            ]
            for e in range(4):
                got = [
                    _limbs_to_int(gt[0, e, 20 * c : 20 * (c + 1), row, col])
                    for c in range(3)
                ]
                assert got == list(expected[e]), (lane, e)
    @pytest.mark.skipif(
        jax.default_backend() != "tpu", reason="pallas mechanics need a real TPU"
    )
    def test_full_kernel_on_tpu(self):
        from tendermint_tpu.ops.ed25519_ladder_pallas import verify_kernel_pallas

        n = 1024
        pubs, msgs, sigs = _batch(n, corrupt={5}, bad_pub={7})
        pub, r, s, h, pre = prepare_batch(pubs, msgs, sigs)
        got = np.asarray(verify_kernel_pallas(pub, r, s, h))
        expect = np.ones(n, dtype=bool)
        expect[5] = expect[7] = False
        assert (got == expect).all()


class TestRouting:
    def test_batch_verify_routes_by_backend_and_size(self, monkeypatch):
        """batch_verify must take the pallas ladder only on TPU and only
        when the padded batch clears the 1024-lane plane geometry."""
        import tendermint_tpu.ops.ed25519_kernel as ek
        import tendermint_tpu.ops.ed25519_ladder_pallas as lpk

        calls = []
        monkeypatch.setattr(
            lpk,
            "verify_kernel_pallas",
            lambda pub, r, s, h, **k: (
                calls.append(pub.shape[0]),
                ek.verify_kernel(pub, r, s, h),
            )[1],
        )
        import jax as jax_mod

        monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
        pubs, msgs, sigs = _batch(1000, corrupt={7})
        out = ek.batch_verify(pubs, msgs, sigs)
        assert calls == [1024]  # padded to the pallas bucket
        assert not out[7] and out.sum() == 999

        # small batches stay on the XLA kernel even on TPU
        calls.clear()
        pubs, msgs, sigs = _batch(64)
        out = ek.batch_verify(pubs, msgs, sigs)
        assert calls == [] and out.all()
