import pytest

from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorAddress,
    ErrVoteUnexpectedStep,
    VoteSet,
)
from tests.helpers import (
    CHAIN_ID,
    byzantine_signed_vote,
    make_block_id,
    make_validators,
    signed_vote,
)


def new_set(n=4, height=1, round_=0, type_=VOTE_TYPE_PREVOTE, power=10):
    vs, privs = make_validators(n, power)
    return VoteSet(CHAIN_ID, height, round_, type_, vs), privs


def test_quorum_exact_two_thirds_plus_one():
    # 4 validators x 10 power; quorum needs > 26.67 => 3 votes (30)
    vote_set, privs = new_set()
    bid = make_block_id()
    for i in range(2):
        vote_set.add_vote(signed_vote(privs[i], i, 1, 0, VOTE_TYPE_PREVOTE, bid))
        assert not vote_set.has_two_thirds_majority()
    vote_set.add_vote(signed_vote(privs[2], 2, 1, 0, VOTE_TYPE_PREVOTE, bid))
    assert vote_set.has_two_thirds_majority()
    assert vote_set.two_thirds_majority() == bid


def test_nil_votes_count_toward_any_not_majority():
    vote_set, privs = new_set()
    nil = BlockID.zero()
    for i in range(3):
        vote_set.add_vote(signed_vote(privs[i], i, 1, 0, VOTE_TYPE_PREVOTE, nil))
    assert vote_set.has_two_thirds_any()
    assert vote_set.two_thirds_majority() == nil  # nil can also win a polka


def test_split_votes_no_majority():
    vote_set, privs = new_set()
    a, b = make_block_id(b"a"), make_block_id(b"b")
    vote_set.add_vote(signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, a))
    vote_set.add_vote(signed_vote(privs[1], 1, 1, 0, VOTE_TYPE_PREVOTE, a))
    vote_set.add_vote(signed_vote(privs[2], 2, 1, 0, VOTE_TYPE_PREVOTE, b))
    vote_set.add_vote(signed_vote(privs[3], 3, 1, 0, VOTE_TYPE_PREVOTE, b))
    assert vote_set.has_two_thirds_any()
    assert not vote_set.has_two_thirds_majority()


def test_duplicate_vote_not_added():
    vote_set, privs = new_set()
    bid = make_block_id()
    v = signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, bid, timestamp=123)
    assert vote_set.add_vote(v)
    assert not vote_set.add_vote(v)


def test_conflicting_vote_raises_evidence():
    vote_set, privs = new_set()
    a, b = make_block_id(b"a"), make_block_id(b"b")
    vote_set.add_vote(byzantine_signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, a))
    with pytest.raises(ErrVoteConflictingVotes) as ei:
        vote_set.add_vote(byzantine_signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, b))
    assert ei.value.vote_a.block_id == a
    assert ei.value.vote_b.block_id == b


def test_conflicting_vote_tracked_after_peer_maj23():
    vote_set, privs = new_set()
    a, b = make_block_id(b"a"), make_block_id(b"b")
    vote_set.add_vote(byzantine_signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, a))
    vote_set.set_peer_maj23("peer1", b)
    # conflict still raises evidence, but the vote lands in block b's tally
    with pytest.raises(ErrVoteConflictingVotes):
        vote_set.add_vote(byzantine_signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, b))
    ba = vote_set.bit_array_by_block_id(b)
    assert ba is not None and ba.get(0)


def test_wrong_height_round_type_rejected():
    vote_set, privs = new_set(height=5, round_=2)
    bid = make_block_id()
    with pytest.raises(ErrVoteUnexpectedStep):
        vote_set.add_vote(signed_vote(privs[0], 0, 4, 2, VOTE_TYPE_PREVOTE, bid))
    with pytest.raises(ErrVoteUnexpectedStep):
        vote_set.add_vote(signed_vote(privs[0], 0, 5, 1, VOTE_TYPE_PREVOTE, bid))
    with pytest.raises(ErrVoteUnexpectedStep):
        vote_set.add_vote(signed_vote(privs[0], 0, 5, 2, VOTE_TYPE_PRECOMMIT, bid))


def test_wrong_address_rejected():
    vote_set, privs = new_set()
    bid = make_block_id()
    v = signed_vote(privs[1], 0, 1, 0, VOTE_TYPE_PREVOTE, bid)  # wrong index
    with pytest.raises(ErrVoteInvalidValidatorAddress):
        vote_set.add_vote(v)


def test_bad_signature_rejected():
    vote_set, privs = new_set()
    bid = make_block_id()
    v = signed_vote(privs[0], 0, 1, 0, VOTE_TYPE_PREVOTE, bid)
    with pytest.raises(ErrVoteInvalidSignature):
        vote_set.add_vote(v.with_signature(bytes(64)))


def test_make_commit():
    vote_set, privs = new_set(type_=VOTE_TYPE_PRECOMMIT)
    bid = make_block_id()
    for i in range(3):
        vote_set.add_vote(signed_vote(privs[i], i, 1, 0, VOTE_TYPE_PRECOMMIT, bid))
    commit = vote_set.make_commit()
    assert commit.block_id == bid
    assert commit.size() == 4
    assert sum(1 for v in commit.precommits if v is not None) == 3
    commit.validate_basic()


def test_make_commit_requires_majority():
    vote_set, privs = new_set(type_=VOTE_TYPE_PRECOMMIT)
    with pytest.raises(Exception):
        vote_set.make_commit()


def test_66_percent_is_not_enough():
    # 3 validators of power 10, plus one of power 15: total 45.
    # Two tens + the 15 = 35 > 30 OK; but exactly 2/3 (30) must fail.
    from tendermint_tpu.types import Validator, ValidatorSet

    vs, privs_all = make_validators(3, power=10)
    # quorum needs > 20: two votes = 20 exactly -> NOT a majority
    vote_set = VoteSet(CHAIN_ID, 1, 0, VOTE_TYPE_PREVOTE, vs)
    bid = make_block_id()
    vote_set.add_vote(signed_vote(privs_all[0], 0, 1, 0, VOTE_TYPE_PREVOTE, bid))
    vote_set.add_vote(signed_vote(privs_all[1], 1, 1, 0, VOTE_TYPE_PREVOTE, bid))
    assert not vote_set.has_two_thirds_majority()
    vote_set.add_vote(signed_vote(privs_all[2], 2, 1, 0, VOTE_TYPE_PREVOTE, bid))
    assert vote_set.has_two_thirds_majority()
