from tendermint_tpu.crypto import PrivKey, PubKey, gen_priv_key
from tendermint_tpu.crypto.hashing import address_hash, ripemd160, sha256


def test_sign_verify_roundtrip():
    k = gen_priv_key(b"\x07" * 32)
    msg = b"consensus is fun"
    sig = k.sign(msg)
    assert len(sig) == 64
    assert k.pub_key.verify(msg, sig)
    assert not k.pub_key.verify(msg + b"!", sig)
    assert not k.pub_key.verify(msg, bytes(64))


def test_deterministic_keys():
    a = PrivKey(b"\x01" * 32)
    b = PrivKey(b"\x01" * 32)
    assert a.pub_key == b.pub_key
    assert a.sign(b"m") == b.sign(b"m")  # ed25519 is deterministic


def test_address():
    k = gen_priv_key(b"\x02" * 32)
    addr = k.pub_key.address
    assert len(addr) == 20
    assert addr == address_hash(k.pub_key.data)


def test_rfc8032_vector_1():
    # RFC 8032 §7.1 TEST 1: empty message
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    k = PrivKey(seed)
    assert k.pub_key.data == bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = k.sign(b"")
    assert sig == bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert k.pub_key.verify(b"", sig)


def test_hashes():
    assert sha256(b"abc").hex().startswith("ba7816bf")
    assert ripemd160(b"abc").hex() == "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"


def test_repr_does_not_leak_seed():
    k = PrivKey(b"\x03" * 32)
    assert "030303" not in repr(k)
