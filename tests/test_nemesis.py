"""Network chaos harness: invariants under partitions, crashes, WAL
damage, lossy links, and device faults.

The acceptance scenario for the resilience subsystem lives here:
`TestDeviceFaultDegradation` trips the verifier circuit breaker
MID-HEIGHT on every node of a running consensus network and proves the
chain keeps committing on the host fallback (no fork, height progress),
then clears the fault and proves the breaker re-closes.
"""

from __future__ import annotations

import time

import pytest

from tendermint_tpu.p2p.transport import (
    ChaosEndpoint,
    FuzzConfig,
    FuzzedEndpoint,
    LinkChaos,
    pipe_pair,
)
from tendermint_tpu.services.resilient import ResilientVerifier
from tendermint_tpu.services.verifier import HostBatchVerifier
from tendermint_tpu.testing import Nemesis
from tendermint_tpu.utils import fail
from tendermint_tpu.utils.circuit import CircuitBreaker


@pytest.fixture(autouse=True)
def _clean_faults():
    fail.clear_device_faults()
    yield
    fail.clear_device_faults()


class TestChaosTransport:
    def test_partition_black_holes_sends(self):
        a, b = pipe_pair()
        chaos = LinkChaos()
        ca = ChaosEndpoint(a, chaos)
        ca.send(b"before")
        assert b.recv(timeout=1) == b"before"
        chaos.partitioned = True
        assert ca.send(b"during")  # swallowed, not an error
        chaos.partitioned = False
        ca.send(b"after")
        assert b.recv(timeout=1) == b"after"  # 'during' is gone

    def test_duplicate_delivers_twice(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=1)
        chaos.dup_prob = 1.0
        ChaosEndpoint(a, chaos).send(b"x")
        assert b.recv(timeout=1) == b"x"
        assert b.recv(timeout=1) == b"x"

    def test_delay_defers_delivery(self):
        a, b = pipe_pair()
        chaos = LinkChaos()
        chaos.delay_s = 0.15
        ChaosEndpoint(a, chaos).send(b"later")
        with pytest.raises(TimeoutError):
            b.recv(timeout=0.05)
        assert b.recv(timeout=1) == b"later"

    def test_fuzz_dup_probability(self):
        a, b = pipe_pair()
        fa = FuzzedEndpoint(a, FuzzConfig(prob_dup=1.0, seed=3))
        fa.send(b"d")
        assert b.recv(timeout=1) == b"d"
        assert b.recv(timeout=1) == b"d"


def _resilient_factory(threshold=2, reset_s=0.5):
    def factory(_i):
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(
                failure_threshold=threshold, reset_timeout_s=reset_s
            ),
            max_retries=0,
        )

    return factory


class TestDeviceFaultDegradation:
    def test_breaker_trips_mid_height_chain_keeps_committing(self, tmp_path):
        """THE acceptance scenario: env-forced verifier device faults on
        a running network -> breakers trip OPEN -> blocks keep
        committing on the host fallback (no fork, height progress) ->
        fault clears -> breakers re-close. The degradation cycle is
        asserted through the EXPORTED telemetry (trip/recovery counters,
        fallback calls), not harness internals — what a dashboard would
        show is what the invariant checks."""
        with Nemesis(
            4, home=str(tmp_path), verifier_factory=_resilient_factory()
        ) as net:
            net.wait_height(2, timeout=60)
            base = net.breaker_baseline("verify")

            fail.set_device_fault("verify")  # device 'dies' mid-consensus
            net.wait_progress(delta=2, timeout=60)  # liveness on fallback
            # every node is degraded (open, or half_open between probes —
            # probes keep failing while the fault is armed)
            tripped = [n.cs.verifier.breaker.state for n in net.nodes]
            assert all(s != "closed" for s in tripped), tripped
            # ... and the degradation is observable from telemetry alone:
            # all 4 nodes' breakers tripped, fallbacks answered calls
            net.assert_breaker_tripped(base, min_trips=len(net.nodes))
            net.check_invariants()  # safety on fallback (no fork)

            fail.clear_device_faults()  # device 'recovers'
            net.wait_progress(delta=2, timeout=60)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(
                    n.cs.verifier.breaker.state == "closed" for n in net.nodes
                ):
                    break
                time.sleep(0.1)
            states = [n.cs.verifier.breaker.state for n in net.nodes]
            assert all(s == "closed" for s in states), states
            net.assert_breaker_recovered(base, min_recoveries=len(net.nodes))
            net.wait_progress(delta=1, timeout=60)  # still live re-upgraded


class TestRoundSkip:
    def test_starved_node_round_skips_and_rejoins(self, tmp_path):
        """The ROADMAP liveness gap, closed: a node cut off from all
        vote gossip (total starvation at PREVOTE/PRECOMMIT — no +2/3-any
        ever arrives to arm the *_wait timeouts) must keep cycling
        rounds via the round-skip timeout instead of wedging, and the
        skips are exported so chaos runs can assert on them."""
        from tendermint_tpu.testing.nemesis import NemesisNode

        cfg = NemesisNode.default_config()
        cfg.timeout_round_skip = 400  # fast skips for the test
        cfg.timeout_round_skip_delta = 50
        with Nemesis(4, home=str(tmp_path), config=cfg) as net:
            net.wait_height(2, timeout=60)
            skips_pv = net.telemetry_value(
                "tendermint_consensus_round_skips_total", phase="prevote"
            )
            skips_pc = net.telemetry_value(
                "tendermint_consensus_round_skips_total", phase="precommit"
            )
            net.partition({0, 1, 2}, {3})  # node 3 fully starved
            # the majority keeps committing; the starved node skips at
            # PREVOTE (precommit nil) and then at PRECOMMIT (next round)
            net.wait_telemetry_above(
                "tendermint_consensus_round_skips_total",
                skips_pv,
                timeout=30,
                phase="prevote",
            )
            net.wait_telemetry_above(
                "tendermint_consensus_round_skips_total",
                skips_pc,
                timeout=30,
                phase="precommit",
            )
            net.wait_progress(delta=1, nodes=[0, 1, 2], timeout=60)
            assert net.nodes[3].cs.round > 0  # it cycled rounds, not wedged
            net.heal()
            # safety held and the skipper rejoins the chain after heal
            target = max(net.heights()) + 1
            net.wait_height(target, timeout=60)


class TestPartitionHeal:
    def test_even_split_stalls_then_heals(self, tmp_path):
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            net.partition({0, 1}, {2, 3})  # no quorum on either side
            before = max(net.heights())
            time.sleep(1.5)
            assert max(net.heights()) <= before + 1  # at most in-flight height
            net.heal()
            net.wait_height(before + 2, timeout=60)  # progress resumes

    def test_minority_partition_keeps_majority_committing(self, tmp_path):
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            net.partition({0, 1, 2}, {3})  # 75% quorum keeps going
            net.wait_progress(delta=2, nodes=[0, 1, 2], timeout=60)
            net.heal()
            # the isolated node catches back up after heal
            target = max(net.heights())
            net.wait_height(target, nodes=[3], timeout=60)


class TestCrashRecovery:
    def test_crash_restart_resumes_and_catches_up(self, tmp_path):
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            net.crash(0)
            net.wait_progress(delta=1, nodes=[1, 2, 3], timeout=60)
            net.restart(0)
            target = max(net.heights()) + 1
            net.wait_height(target, timeout=60)

    def test_corrupt_wal_tail_is_tolerated_on_restart(self, tmp_path):
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            net.crash(0)
            net.corrupt_wal_tail(0, nbytes=32)  # torn-write garbage
            net.restart(0)
            net.wait_height(max(net.heights()) + 1, timeout=60)

    def test_truncated_wal_tail_is_tolerated_on_restart(self, tmp_path):
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            net.crash(2)
            net.truncate_wal_tail(2, nbytes=24)
            net.restart(2)
            net.wait_height(max(net.heights()) + 1, timeout=60)


@pytest.mark.slow
class TestChaosSoak:
    def test_lossy_duplicating_network_stays_consistent(self, tmp_path):
        """Background fuzz (drops + dups) on every link, a crash-restart
        and a partition cycle on top — invariants checked continuously."""
        fuzz = FuzzConfig(prob_drop_rw=0.05, prob_dup=0.10, seed=42)
        with Nemesis(4, home=str(tmp_path), fuzz=fuzz) as net:
            net.wait_height(3, timeout=120)
            net.partition({0, 3}, {1, 2})
            time.sleep(1.0)
            net.heal()
            net.wait_progress(delta=2, timeout=120)
            net.crash(1)
            net.restart(1)
            net.wait_height(max(net.heights()) + 2, timeout=120)

    def test_soft_fail_point_crashes_one_node_in_process(self, tmp_path):
        """FAIL_TEST_INDEX composition: the soft mode kills ONE node's
        consensus thread at a persistence step; restart + WAL replay
        recover it while the rest of the network keeps going."""
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            net.crash_at_fail_point(5)
            try:
                deadline = time.monotonic() + 30
                victim = None
                while time.monotonic() < deadline and victim is None:
                    for n in net.nodes:
                        t = n.cs._thread
                        if t is not None and not t.is_alive():
                            victim = n.index
                    time.sleep(0.1)
                assert victim is not None, "no consensus thread died at fail point"
            finally:
                net.clear_fail_point()
            net.crash(victim)
            net.restart(victim)
            net.wait_height(max(net.heights()) + 1, timeout=120)


class TestPipelinedApplyChaos:
    """ROADMAP item 3's chaos gate: a forged or faulted ABCI apply
    landing MID-PIPELINE (height H's apply in flight under H+1's
    voting) must drain at the join barrier and halt that node without
    any speculative state reaching disk or a committed block — the
    no-fork invariants run continuously and the whole suite runs under
    the lock-rank sanitizer."""

    def test_faulted_apply_mid_pipeline_drains_and_halts(self, tmp_path):
        from tendermint_tpu.state.state import load_state
        from tendermint_tpu.testing.nemesis import (
            FaultedApplyApp,
            one_bad_app_factory,
        )

        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(
                app_factory=one_bad_app_factory(
                    3, FaultedApplyApp, 4, fail_from_height=4
                )
            ),
        ) as net:
            # pipelining is the default config; the apply of height 4 on
            # node 3 raises on its worker — the join barrier surfaces it
            net.wait_height(6, nodes=[0, 1, 2], timeout=120)
            bad = net.nodes[3]
            deadline = time.time() + 30
            while bad.cs.fatal_error is None and time.time() < deadline:
                time.sleep(0.1)
            assert bad.cs.fatal_error is not None, "faulted apply did not halt"
            # the speculative H+1 never landed: persisted state stopped
            # at the last honestly-applied height
            st = load_state(bad.node.state_db)
            assert st.last_block_height == 3
            net.check_no_fork()

    def test_forged_apply_cannot_fork_the_chain(self, tmp_path):
        from tendermint_tpu.state.state import load_state
        from tendermint_tpu.testing.nemesis import (
            ForgedHashApp,
            one_bad_app_factory,
        )

        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(
                app_factory=one_bad_app_factory(
                    3, ForgedHashApp, 4, fail_from_height=3
                )
            ),
        ) as net:
            # node 3's local execution diverges at height 3; the honest
            # +2/3 keeps committing the honest chain
            net.wait_height(6, nodes=[0, 1, 2], timeout=120)
            bad = net.nodes[3]
            # the forged node halts when the honest block's apply fails
            # validation against its diverged state
            deadline = time.time() + 30
            while bad.cs.fatal_error is None and time.time() < deadline:
                time.sleep(0.1)
            assert bad.cs.fatal_error is not None, "diverged node kept running"
            st = load_state(bad.node.state_db)
            assert st.app_hash == b"\xde\xad\xbe\xef" * 5
            # no committed header ever carried the forged hash
            honest = net.nodes[0]
            for h in range(4, honest.store.height + 1):
                meta = honest.store.load_block_meta(h)
                assert meta.header.app_hash != b"\xde\xad\xbe\xef" * 5
            net.check_no_fork()


class TestFullNodeChaos:
    """The harness driving COMPLETE `node.Node` instances (fast-sync +
    mempool + RPC + state-sync reactors) instead of bare consensus
    cores — the open ROADMAP resilience item."""

    @staticmethod
    def _rpc(port, method, **params):
        import json
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.load(resp)
        if "error" in out:
            raise RuntimeError(out["error"])
        return out["result"]

    def test_statesync_joiner_mid_partition_converges(self, tmp_path):
        """THE state-sync chaos scenario: a 4-validator full-node
        network serves snapshots; one validator is partitioned away; a
        FRESH node joins mid-partition, state-syncs from the majority
        (store base > 1 proves no genesis replay), commits a tx fed
        through the RPC layer under the partition, then the partition
        heals and everyone — including the stale validator — converges.
        Invariants (no-fork, commit agreement) run continuously."""

        def serving(cfg):
            cfg.statesync.snapshot_interval = 3

        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(config_mutator=serving),
        ) as net:
            net.wait_height(5, timeout=90)
            assert net.nodes[0].node.snapshot_store.list_manifests()
            # isolate validator 3; 3/4 voting power keeps committing.
            # group 4 now so the joiner's links inherit correctly.
            net.partition({0, 1, 2, 4}, {3})
            stale_height = net.nodes[3].store.height
            # tx through the RPC layer while partitioned
            res = self._rpc(
                net.nodes[0].rpc_port, "broadcast_tx_sync", tx=b"chaos-k=chaos-v".hex()
            )
            assert res["code"] == 0

            from tendermint_tpu.testing.nemesis import FullNemesisNode

            def joining(cfg):
                cfg.statesync.enable = True

            joiner = FullNemesisNode(
                4,
                net.genesis,
                net.privs,
                net.home,
                net.chain_id,
                config_mutator=joining,
            )
            net.add_node(joiner)
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                if joiner.node.statesync_reactor.restored_state is not None:
                    break
                time.sleep(0.1)
            assert joiner.node.statesync_reactor.restored_state is not None
            assert joiner.store.base > 1  # snapshot-restored, not replayed
            # the joiner converges with the majority despite the partition
            net.wait_height(
                net.nodes[0].store.height + 2, nodes=[0, 1, 2, 4], timeout=60
            )
            # RPC on the JOINER serves the restored/synced chain
            status = self._rpc(joiner.rpc_port, "status")
            assert int(status["sync_info"]["latest_block_height"]) > 1
            assert joiner.app._data.get(b"chaos-k") == b"chaos-v"

            net.heal()
            # the stale validator fast-syncs back past its partition-era
            # height and the whole net (5 nodes) keeps agreeing
            net.wait_height(stale_height + 3, timeout=90)

    def test_full_node_crash_restart_under_chaos(self, tmp_path):
        """Crash/restart of a full node (WAL + handshake recovery) with
        per-link delay chaos active — the NemesisNode crash matrix
        promoted to whole-node scope."""
        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(),
        ) as net:
            net.wait_height(3, timeout=90)
            net.delay(0, 1, 0.05)
            net.crash(3)
            net.wait_progress(delta=2, nodes=[0, 1, 2], timeout=60)
            net.restart(3)
            net.wait_height(max(net.heights()) + 2, timeout=90)


class TestPipelineNoFork:
    """ISSUE 4 acceptance: no-fork while the async dispatch PIPELINE is
    active — a fresh full node fast-syncs into a live network through
    the pipelined reactor (overlapped window verifies) while injected
    device faults knock launches out mid-flight; the no-fork /
    commit-agreement invariants run continuously in the monitor."""

    def test_fastsync_pipeline_joiner_under_device_faults_no_fork(self, tmp_path):
        from tendermint_tpu.services.verifier import TableBatchVerifier
        from tendermint_tpu.telemetry import REGISTRY
        from tendermint_tpu.testing.nemesis import FullNemesisNode

        with Nemesis(
            3, home=str(tmp_path), node_factory=Nemesis.full_node_factory()
        ) as net:
            net.wait_height(4, timeout=90)
            overlap = REGISTRY.get("tendermint_dispatch_overlap_ratio")
            joins_before = overlap.labels(queue="fastsync").value["count"]

            # the joiner's window launches ride the breaker-guarded
            # async path; the first two fault in flight and must resolve
            # via host re-verify inside their handles
            verifier = ResilientVerifier(
                TableBatchVerifier(min_device_batch=10**6),
                breaker=CircuitBreaker(failure_threshold=100, reset_timeout_s=60),
                max_retries=0,
            )
            fail.set_device_fault("verify", 2)
            joiner = FullNemesisNode(
                3, net.genesis, net.privs, net.home, net.chain_id, verifier=verifier
            )
            net.add_node(joiner)
            # the joiner pipelines the whole chain and keeps up with head
            net.wait_height(max(net.heights()) + 2, timeout=90)
            net.check_invariants()  # no fork with the pipeline active
            # both injected faults degraded through handles, not raises
            assert verifier._dispatch.fallback_calls >= 1
            # the overlap histogram saw the joiner's windows: the
            # pipeline actually engaged (not the synchronous fallback)
            assert overlap.labels(queue="fastsync").value["count"] > joins_before


class TestIngressChaos:
    """ISSUE 8 acceptance: sustained load-generator traffic keeps
    flowing through the batched ingress pipeline (sharded lanes +
    verify windows) while the network partitions AND the verify breaker
    trips to host crypto — with ZERO loss from the admitted pool (every
    CheckTx that answered OK is eventually committed) and no fork."""

    def test_sustained_ingress_through_partition_heal_and_breaker_trip(
        self, tmp_path, monkeypatch
    ):
        import itertools
        import threading

        from tendermint_tpu.crypto.keys import gen_priv_key
        from tendermint_tpu.mempool import make_signed_tx

        monkeypatch.setenv("TENDERMINT_TPU_MEMPOOL_LANES", "4")
        priv = gen_priv_key(b"\x33" * 32)
        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(),
            verifier_factory=_resilient_factory(threshold=2, reset_s=0.5),
        ) as net:
            net.wait_height(2, timeout=90)
            # the full production mempool shape is active on every node
            assert net.nodes[0].node.mempool.n_lanes == 4
            assert net.nodes[0].node.mempool._ingress is not None

            admitted: list[bytes] = []
            adm_lock = threading.Lock()
            stop = threading.Event()
            seq = itertools.count()

            def pump():
                """Open-loop traffic: signed txs at a steady arrival
                rate into two intake nodes' ingress pipelines (the
                RPC-broadcast shape), regardless of admission progress."""
                for i in seq:
                    if stop.is_set() or i >= 1200:
                        return
                    tx = make_signed_tx(priv, b"ing-%d=%d" % (i, i))

                    def cb(res, tx=tx):
                        if res.is_ok:
                            with adm_lock:
                                admitted.append(tx)

                    net.nodes[i % 2].node.mempool.check_tx_async(tx, cb)
                    time.sleep(0.008)

            pump_thread = threading.Thread(target=pump, daemon=True)
            pump_thread.start()
            try:
                time.sleep(0.5)  # traffic established pre-fault
                base = net.breaker_baseline("verify")
                net.partition({0, 1, 2}, {3})  # minority isolated
                fail.set_device_fault("verify")  # device dies under load
                net.wait_progress(delta=2, nodes=[0, 1, 2], timeout=90)
                net.assert_breaker_tripped(base, min_trips=1)
                fail.clear_device_faults()
                net.heal()
                net.wait_progress(delta=2, timeout=90)
            finally:
                stop.set()
                pump_thread.join(10)
            with adm_lock:
                final_admitted = list(admitted)
            assert final_admitted, "no tx was admitted under chaos"

            # zero admitted-tx loss: every OK admission commits
            def committed_txs() -> set:
                store = net.nodes[0].store
                out = set()
                for h in range(max(1, store.base), store.height + 1):
                    blk = store.load_block(h)
                    if blk is not None:
                        out.update(bytes(t) for t in blk.data.txs)
                return out

            deadline = time.monotonic() + 120
            missing = set(final_admitted)
            while time.monotonic() < deadline and missing:
                missing = set(final_admitted) - committed_txs()
                if missing:
                    time.sleep(0.5)
            assert not missing, (
                f"{len(missing)}/{len(final_admitted)} admitted txs lost"
            )
            net.check_invariants()  # no fork through the whole episode
