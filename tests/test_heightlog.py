"""Finality observatory: HeightLedger ring/persistence, the per-peer
vote-arrival rollup, flight-dump embedding, the finality_report merge
tool, and THE acceptance scenario — a live 4-validator net where every
committed height carries a complete, self-consistent ledger record."""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.telemetry import heightlog
from tendermint_tpu.telemetry.heightlog import HeightLedger, VoteArrivalRollup
from tools.finality_report import build_report, load_records


def _rec(height, node="n0", finality=0.2, path="vote_gather", t=None):
    # t_commit defaults to NOW: the process-global ledger registry is
    # shared with earlier tests' live-net records, and recent_records()
    # keeps the newest-by-commit-time window — ancient synthetic stamps
    # would sort themselves out of it
    import time as _time

    if t is None:
        t = _time.time() + height * 1e-3
    return {
        "height": height,
        "node": node,
        "round": 0,
        "txs": 0,
        "t_start": t - 0.2,
        "t_commit": t,
        "height_s": 0.2,
        "finality_s": finality if height > 1 else None,
        "phases": {
            "new_height": {"s": 0.1, "work_s": 0.0, "wait_s": 0.1},
            "prevote": {"s": 0.1, "work_s": 0.02, "wait_s": 0.08},
        },
        "path": {"vote_gather": 0.1},
        "critical_path": path,
        "laggard": {"validator": "aabbcc", "index": 1, "delay_s": 0.01},
    }


class TestHeightLedger:
    def test_ring_bounds(self):
        led = HeightLedger(capacity=4)
        for h in range(1, 11):
            led.record(_rec(h))
        assert len(led) == 4
        assert [r["height"] for r in led.recent()] == [7, 8, 9, 10]
        assert led.last()["height"] == 10
        assert led.recent(height=9)[0]["height"] == 9

    def test_node_id_stamped(self):
        led = HeightLedger(node_id="nodeX")
        led.record({"height": 1, "t_commit": 1.0})
        assert led.last()["node"] == "nodeX"

    def test_jsonl_persistence_and_reload(self, tmp_path):
        path = str(tmp_path / "heights.jsonl")
        led = HeightLedger(path=path, node_id="n0")
        for h in range(1, 6):
            led.record(_rec(h))
        led.close()
        # torn final line from a crash must not poison the reload
        with open(path, "a") as f:
            f.write('{"height": 99, "trunc')
        led2 = HeightLedger(path=path, node_id="n0")
        assert [r["height"] for r in led2.recent()] == [1, 2, 3, 4, 5]
        led2.close()

    def test_compaction_keeps_newest(self, tmp_path):
        path = str(tmp_path / "heights.jsonl")
        led = HeightLedger(path=path, capacity=8)
        for h in range(1, 40):
            led.record(_rec(h))
        led.close()
        with open(path) as f:
            lines = [json.loads(line) for line in f if line.strip()]
        assert len(lines) <= 16  # 2x capacity compaction bound
        assert lines[-1]["height"] == 39

    def test_finality_window(self):
        led = HeightLedger()
        for h in range(1, 6):
            led.record(_rec(h, finality=0.1 * h))
        # height 1 has finality None and is excluded
        assert led.finality_window(10) == pytest.approx([0.2, 0.3, 0.4, 0.5])

    def test_record_never_raises_after_close(self):
        led = HeightLedger()
        led.close()
        led.record(_rec(1))  # no-op, no exception
        assert len(led) == 0

    def test_registry_recent_records_merges(self):
        import time as _time

        a = HeightLedger(node_id="a")
        b = HeightLedger(node_id="b")
        # stamped slightly in the future so earlier tests' live-net
        # records (shared process-global registry) can't crowd them out
        a.record(_rec(1, node="a", t=_time.time() + 50.0))
        b.record(_rec(1, node="b", t=_time.time() + 51.0))
        recs = heightlog.recent_records(64)
        mine = [r for r in recs if r.get("node") in ("a", "b")]
        assert len(mine) == 2
        assert mine[-1]["node"] == "b"  # commit-time ordered

    def test_dump_all_atomic_file(self, tmp_path):
        led = HeightLedger(node_id="dumper")
        led.record(_rec(3, node="dumper"))
        path = heightlog.dump_all(str(tmp_path), reason="unit test!")
        assert path is not None and os.path.exists(path)
        dump = json.load(open(path))
        nodes = {l["node"] for l in dump["ledgers"]}
        assert "dumper" in nodes
        assert dump["reason"] == "unit test!"

    def test_work_totals_keys(self):
        totals = heightlog.work_totals()
        assert set(totals) == {"verify", "hash", "coalescer", "dispatch"}
        assert all(v >= 0.0 for v in totals.values())


class TestVoteArrivalRollup:
    def test_rollup_stats(self):
        r = VoteArrivalRollup()
        r.observe("peerA", 0.010)
        r.observe("peerA", 0.030)
        r.observe("peerB", 0.005)
        snap = r.snapshot()
        assert snap["peerA"]["count"] == 2
        assert snap["peerA"]["max_ms"] == 30.0
        assert snap["peerA"]["mean_ms"] == 20.0
        assert r.max_delay() == pytest.approx(0.030)

    def test_peer_flood_bounded(self):
        r = VoteArrivalRollup()
        for i in range(2 * VoteArrivalRollup.MAX_PEERS):
            r.observe(f"peer{i}", 0.001)
        assert len(r.snapshot()) == VoteArrivalRollup.MAX_PEERS


class TestFlightDumpEmbedsLedger:
    def test_dump_carries_height_records(self, tmp_path):
        from tendermint_tpu.telemetry.flightrec import FLIGHT

        led = HeightLedger(node_id="flight-test")
        led.record(_rec(7, node="flight-test"))
        path = FLIGHT.dump(reason="heightlog-unit", dir=str(tmp_path))
        assert path is not None
        dump = json.load(open(path))
        assert "heights" in dump
        assert any(r.get("node") == "flight-test" for r in dump["heights"])


class TestFinalityReport:
    def test_merge_jsonl_and_dump_dedup(self, tmp_path):
        jl = tmp_path / "a.jsonl"
        with open(jl, "w") as f:
            for h in (1, 2, 3):
                f.write(json.dumps(_rec(h, node="n0")) + "\n")
        # a dump overlapping the jsonl (same node/heights) must dedupe
        dump = {
            "reason": "x",
            "ledgers": [
                {"node": "n1", "records": [_rec(2, node="n1"), _rec(3, node="n1")]}
            ],
        }
        dp = tmp_path / "heightledger-x-1.json"
        dp.write_text(json.dumps(dump))
        recs = load_records([str(jl), str(jl), str(dp)])
        assert len(recs) == 5  # 3 from n0 + 2 from n1, self-dedup
        report = build_report(recs)
        assert report["summary"]["nodes"] == ["n0", "n1"]
        assert report["summary"]["heights"] == 3
        assert report["summary"]["critical_path_counts"]["vote_gather"] == 5
        assert report["summary"]["laggard_counts"]["aabbcc"] == 5
        assert report["summary"]["finality_ms"]["p50"] is not None

    def test_height_and_last_filters(self):
        recs = [_rec(h) for h in range(1, 10)]
        assert list(build_report(recs, height=4)["heights"]) == [4]
        assert list(build_report(recs, last=2)["heights"]) == [8, 9]

    def test_render_and_cli(self, tmp_path, capsys):
        from tools import finality_report

        jl = tmp_path / "h.jsonl"
        with open(jl, "w") as f:
            for h in (1, 2):
                f.write(json.dumps(_rec(h)) + "\n")
        assert finality_report.main(["--ledgers", str(jl)]) == 0
        out = capsys.readouterr().out
        assert "height 2" in out and "laggard=aabbcc" in out


class TestLiveNetLedger:
    """THE acceptance scenario: a live 4-validator net where every
    committed height has a ledger record whose phase durations sum to
    within tolerance of its commit-to-commit gap and whose
    critical-path label is populated; the nodes' persisted ledgers
    merge into one finality waterfall."""

    def test_every_height_has_consistent_record(self, tmp_path):
        from tendermint_tpu.telemetry import REGISTRY
        from tendermint_tpu.testing.nemesis import Nemesis

        fin0 = REGISTRY.counter_value("tendermint_consensus_commits_total")
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(6, timeout=90)
            for node in net.nodes:
                top = node.store.height
                assert top >= 6
                # pipelined finalize: the newest height's record lands at
                # the apply join, a few ms after the store write — poll
                deadline = time.time() + 5
                while time.time() < deadline:
                    recs = {r["height"]: r for r in node.height_ledger.recent()}
                    if top in recs:
                        break
                    time.sleep(0.02)
                # every height this node committed via consensus has a
                # record (fast-sync'd heights are out of ledger scope)
                for h in range(1, top + 1):
                    assert h in recs, f"node{node.index} missing record for {h}"
                for h in range(2, top + 1):
                    r = recs[h]
                    assert r["critical_path"], r
                    assert r["finality_s"] is not None
                    phase_sum = sum(p["s"] for p in r["phases"].values())
                    if r.get("pipelined"):
                        # overlapped apply ran under the NEXT height's
                        # clock — it did not extend this height's gap
                        phase_sum -= r.get("apply_overlap_s") or 0.0
                    gap = r["finality_s"]
                    tol = max(0.30 * gap, 0.1)
                    assert abs(phase_sum - gap) <= tol, (
                        f"node{node.index} h={h}: phases sum {phase_sum:.3f} "
                        f"vs gap {gap:.3f}"
                    )
                    # wait + work decompose each phase (fields rounded
                    # independently to 6dp, so allow a few ulps)
                    for p in r["phases"].values():
                        assert p["wait_s"] + p["work_s"] == pytest.approx(
                            p["s"], abs=5e-6
                        )
                # peers' votes were tracked: laggard attribution present
                assert any(
                    r.get("laggard") for r in recs.values()
                ), f"node{node.index} never attributed a laggard"
                assert node.cs.vote_arrivals.snapshot()
            # the exported finality histogram moved with the commits
            fam = REGISTRY.get("tendermint_finality_seconds")
            assert fam.value["count"] > 0
            assert (
                REGISTRY.counter_value("tendermint_consensus_commits_total")
                > fin0
            )
            ledger_glob = os.path.join(str(tmp_path), "node*", "heights.jsonl")
            report = build_report(load_records([ledger_glob]))
        assert len(report["summary"]["nodes"]) == 4
        assert report["summary"]["finality_ms"]["p50"] is not None
        assert report["summary"]["critical_path_counts"]
