"""Shared test fixtures: deterministic validators, votes, commits.

Mirrors the role of the reference's `consensus/common_test.go` +
`types/vote_set_test.go` fixture helpers.
"""

from __future__ import annotations

import time

from tendermint_tpu.crypto import PrivKey
from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    BlockID,
    Commit,
    PartSetHeader,
    PrivValidator,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
)

CHAIN_ID = "test-chain"


def det_priv_keys(n: int) -> list[PrivKey]:
    return [PrivKey(i.to_bytes(32, "little")) for i in range(1, n + 1)]


def make_validators(n: int, power: int = 10) -> tuple[ValidatorSet, list[PrivValidator]]:
    """N deterministic validators with equal power; privs index-aligned with
    the sorted validator set."""
    privs = [PrivValidator(k) for k in det_priv_keys(n)]
    vals = [
        Validator(address=p.address, pub_key=p.pub_key, voting_power=power) for p in privs
    ]
    vs = ValidatorSet(vals)
    privs_by_addr = {p.address: p for p in privs}
    ordered = [privs_by_addr[v.address] for v in vs.validators]
    return vs, ordered


def make_block_id(seed: bytes = b"blk") -> BlockID:
    import hashlib

    h = hashlib.sha256(seed).digest()
    return BlockID(hash=h, parts_header=PartSetHeader(total=1, hash=h[:20]))


def signed_vote(
    priv: PrivValidator,
    index: int,
    height: int,
    round_: int,
    type_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    timestamp: int | None = None,
) -> Vote:
    vote = Vote(
        validator_address=priv.address,
        validator_index=index,
        height=height,
        round=round_,
        timestamp=timestamp if timestamp is not None else time.time_ns(),
        type=type_,
        block_id=block_id,
    )
    return priv.sign_vote(chain_id, vote)


def byzantine_signed_vote(
    priv: PrivValidator,
    index: int,
    height: int,
    round_: int,
    type_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    timestamp: int = 1000,
) -> Vote:
    """Sign bypassing the double-sign guard (Byzantine test behavior —
    the reference's ByzantinePrivValidator role)."""
    vote = Vote(
        validator_address=priv.address,
        validator_index=index,
        height=height,
        round=round_,
        timestamp=timestamp,
        type=type_,
        block_id=block_id,
    )
    sig = priv._signer.sign(vote.sign_bytes(chain_id))
    return vote.with_signature(sig)


def make_commit(
    val_set: ValidatorSet,
    privs: list[PrivValidator],
    height: int,
    round_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    n_sign: int | None = None,
) -> Commit:
    """Build a commit by running the real VoteSet quorum machinery."""
    vote_set = VoteSet(chain_id, height, round_, VOTE_TYPE_PRECOMMIT, val_set)
    n = n_sign if n_sign is not None else len(privs)
    for i in range(n):
        vote_set.add_vote(
            signed_vote(privs[i], i, height, round_, VOTE_TYPE_PRECOMMIT, block_id, chain_id)
        )
    return vote_set.make_commit()
