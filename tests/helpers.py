"""Shared test fixtures: deterministic validators, votes, commits.

Mirrors the role of the reference's `consensus/common_test.go` +
`types/vote_set_test.go` fixture helpers.
"""

from __future__ import annotations

import time

from tendermint_tpu.crypto import PrivKey
from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    BlockID,
    Commit,
    PartSetHeader,
    PrivValidator,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
)

CHAIN_ID = "test-chain"


def det_priv_keys(n: int) -> list[PrivKey]:
    return [PrivKey(i.to_bytes(32, "little")) for i in range(1, n + 1)]


def make_validators(n: int, power: int = 10) -> tuple[ValidatorSet, list[PrivValidator]]:
    """N deterministic validators with equal power; privs index-aligned with
    the sorted validator set."""
    privs = [PrivValidator(k) for k in det_priv_keys(n)]
    vals = [
        Validator(address=p.address, pub_key=p.pub_key, voting_power=power) for p in privs
    ]
    vs = ValidatorSet(vals)
    privs_by_addr = {p.address: p for p in privs}
    ordered = [privs_by_addr[v.address] for v in vs.validators]
    return vs, ordered


def make_block_id(seed: bytes = b"blk") -> BlockID:
    import hashlib

    h = hashlib.sha256(seed).digest()
    return BlockID(hash=h, parts_header=PartSetHeader(total=1, hash=h[:20]))


def signed_vote(
    priv: PrivValidator,
    index: int,
    height: int,
    round_: int,
    type_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    timestamp: int | None = None,
) -> Vote:
    vote = Vote(
        validator_address=priv.address,
        validator_index=index,
        height=height,
        round=round_,
        timestamp=timestamp if timestamp is not None else time.time_ns(),
        type=type_,
        block_id=block_id,
    )
    return priv.sign_vote(chain_id, vote)


def byzantine_signed_vote(
    priv: PrivValidator,
    index: int,
    height: int,
    round_: int,
    type_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    timestamp: int = 1000,
) -> Vote:
    """Sign bypassing the double-sign guard (Byzantine test behavior —
    the reference's ByzantinePrivValidator role)."""
    vote = Vote(
        validator_address=priv.address,
        validator_index=index,
        height=height,
        round=round_,
        timestamp=timestamp,
        type=type_,
        block_id=block_id,
    )
    sig = priv._signer.sign(vote.sign_bytes(chain_id))
    return vote.with_signature(sig)


def make_commit(
    val_set: ValidatorSet,
    privs: list[PrivValidator],
    height: int,
    round_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    n_sign: int | None = None,
) -> Commit:
    """Build a commit by running the real VoteSet quorum machinery."""
    vote_set = VoteSet(chain_id, height, round_, VOTE_TYPE_PRECOMMIT, val_set)
    n = n_sign if n_sign is not None else len(privs)
    for i in range(n):
        vote_set.add_vote(
            signed_vote(privs[i], i, height, round_, VOTE_TYPE_PRECOMMIT, block_id, chain_id)
        )
    return vote_set.make_commit()


def make_genesis(n_vals: int = 4, power: int = 10, chain_id: str = CHAIN_ID):
    """GenesisDoc + index-aligned priv validators."""
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    vs, privs = make_validators(n_vals, power)
    gen = GenesisDoc(
        chain_id=chain_id,
        genesis_time=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(pub_key=v.pub_key, power=v.voting_power) for v in vs.validators
        ],
    )
    return gen, privs


class ChainSim:
    """Drive a real State + app through heights with real commits.

    The make-block -> sign-precommits -> apply_block loop every
    storage/sync/consensus test needs (role of the reference's
    `state/execution_test.go` + `consensus/common_test.go` chain makers).
    """

    def __init__(
        self, n_vals: int = 4, app=None, db=None, chain_id: str = CHAIN_ID, hasher=None
    ):
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.db.kv import MemDB
        from tendermint_tpu.state import make_genesis_state

        self.chain_id = chain_id
        self.hasher = hasher
        self.db = db if db is not None else MemDB()
        self.genesis, self.privs = make_genesis(n_vals, chain_id=chain_id)
        self.state = make_genesis_state(self.db, self.genesis)
        self.state.save()  # node startup persists genesis state (validators@1)
        self.app = app if app is not None else KVStoreApp()
        self.conns = local_client_creator(self.app)()
        self.blocks = []
        self.commits = []

    def _commit_for(self, block, part_set):
        from tendermint_tpu.types import BlockID

        block_id = BlockID(block.hash(), part_set.header)
        return make_commit(
            self.state.validators,
            self._privs_in_valset_order(),
            block.header.height,
            0,
            block_id,
            self.chain_id,
        )

    def _privs_in_valset_order(self):
        by_addr = {p.address: p for p in self.privs}
        return [by_addr[v.address] for v in self.state.validators.validators]

    def make_next_block(self, txs=None, evidence=None):
        from tendermint_tpu.types import Commit, Txs
        from tendermint_tpu.types.block import Block

        height = self.state.last_block_height + 1
        last_commit = self.commits[-1] if self.commits else Commit.empty()
        block = Block.make_block(
            height=height,
            chain_id=self.chain_id,
            txs=Txs(txs or []),
            last_commit=last_commit,
            last_block_id=self.state.last_block_id,
            time=self.genesis.genesis_time + height * 1_000_000_000,
            validators_hash=self.state.validators.hash(),
            app_hash=self.state.app_hash,
            hasher=self.hasher,
            evidence=evidence,
        )
        return block, block.make_part_set(hasher=self.hasher)

    def advance(self, txs=None, **apply_kwargs):
        """Build, commit-sign, and apply one block; returns the block."""
        from tendermint_tpu.state import apply_block

        block, part_set = self.make_next_block(txs)
        commit = self._commit_for(block, part_set)
        apply_kwargs.setdefault("hasher", self.hasher)
        apply_block(self.state, block, part_set.header, self.conns.consensus, **apply_kwargs)
        self.blocks.append(block)
        self.commits.append(commit)
        return block
