"""Replay console: step a recorded WAL through a fresh state machine
(reference `consensus/replay_file.go`, `commands/replay.go`)."""

import pytest

from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import Config
from tendermint_tpu.consensus.replay_console import (
    Playback,
    make_replay_cs_factory,
)
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.node import Node

pytestmark = pytest.mark.slow


@pytest.fixture()
def recorded_home(tmp_path):
    """A solo-validator home whose WAL records >=3 committed heights."""
    home = str(tmp_path / "rec")
    cli_main(["init", "--home", home, "--chain-id", "replay-test"])
    cfg = Config.test_config(home)
    cfg.base.fast_sync = False
    node = Node(cfg)
    node.start()
    try:
        node.wait_height(3)
    finally:
        node.stop()
    return cfg


def _factory(cfg):
    # fresh in-memory stores per reset: the replay reconstructs the
    # chain from the WAL alone, leaving the recorded home untouched
    return make_replay_cs_factory(cfg, db_provider=lambda name: MemDB())


class TestPlayback:
    def test_run_all_reconstructs_chain_from_wal(self, recorded_home):
        pb = Playback(_factory(recorded_home), recorded_home.wal_path())
        assert len(pb.records) > 0
        applied = pb.run_all()
        assert applied == len(pb.records)
        # every height the recorder committed was rebuilt purely from
        # WAL records (votes, proposals, block parts, timeouts)
        assert pb.cs.state.last_block_height >= 3
        assert pb.cs.block_store.height >= 3

    def test_step_and_back(self, recorded_home):
        pb = Playback(_factory(recorded_home), recorded_home.wal_path())
        total = len(pb.records)
        assert pb.step(5) == 5
        assert pb.count == 5
        h5 = pb.cs.get_round_state().height
        pb.back(2)
        assert pb.count == 3
        # stepping forward again reconverges deterministically
        pb.step(2)
        assert pb.count == 5
        assert pb.cs.get_round_state().height == h5
        assert pb.step(total) == total - 5  # clamped at EOF
        assert pb.done()

    def test_console_commands(self, recorded_home):
        out: list[str] = []
        pb = Playback(
            _factory(recorded_home), recorded_home.wal_path(), out=out.append
        )
        script = iter(
            ["next", "next 3", "n", "rs short", "back 1", "rs", "bogus", "quit"]
        )
        pb.console(input_fn=lambda _prompt: next(script))
        assert pb.count == 3  # 1 + 3 - 1
        assert any("unknown command" in line for line in out)
        assert any("/" in line for line in out)  # rs short prints h/r/step

    def test_cli_reset_and_gen_validator(self, recorded_home, capsys):
        import json
        import os

        from tendermint_tpu.types.priv_validator import PrivValidatorFS

        cfg = recorded_home
        pv_before = PrivValidatorFS.load(cfg.priv_validator_path())
        assert pv_before._last.height > 0  # the recorder signed blocks

        assert cli_main(["reset_priv_validator", "--home", cfg.home]) == 0
        pv = PrivValidatorFS.load(cfg.priv_validator_path())
        assert pv._last.height == 0
        assert pv.pub_key.data == pv_before.pub_key.data  # key survives

        assert cli_main(["reset_all", "--home", cfg.home]) == 0
        assert not os.path.exists(cfg.db_path("state"))
        assert os.path.exists(cfg.priv_validator_path())

        capsys.readouterr()
        assert cli_main(["gen_validator"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(bytes.fromhex(doc["pub_key"])) == 32
        assert doc["last_height"] == 0

    def test_cli_replay_command(self, recorded_home, capsys):
        # CLI batch replay over a COPY of the home (replay writes through
        # the real stores, same as the reference console)
        import shutil

        copy = recorded_home.home + "-copy"
        shutil.copytree(recorded_home.home, copy)
        # wipe the copy's data dir so replay rebuilds from genesis
        shutil.rmtree(Config.test_config(copy).home + "/data")
        rc = cli_main(["replay", "--home", copy])
        assert rc == 0
        assert "replayed" in capsys.readouterr().out
