"""POL lock/unlock safety cases + halt + WAL-prefix crash recovery.

Ports the reference's consensus safety proofs: TestLockPOLSafety1/2
(`consensus/state_test.go:701,822`), conflicting-vote tolerance
(`:917`), TestHalt1 (`:997`), and replay-from-every-WAL-prefix
(`consensus/replay_test.go:55-63`).
"""

import os
import struct
import time

import pytest

from tendermint_tpu.blockchain import BlockStore
from tendermint_tpu.consensus.config import ConsensusConfig
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.ticker import TimeoutTicker
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.types import events as ev
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.tx import Txs
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE

from tests.test_consensus import CHAIN, Fixture

NIL = BlockID(b"", PartSetHeader.zero())


def wait_round(f, round_, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if f.cs.get_round_state().round >= round_:
            return
        time.sleep(0.01)
    raise AssertionError(f"never reached round {round_}")


def inject_late_votes(f, type_, block_id, indices, round_):
    """Inject votes for an OLD round: signed via the raw signer since
    the honest double-sign guard (correctly) refuses round regressions."""
    from tests.helpers import byzantine_signed_vote

    for i in indices:
        vote = byzantine_signed_vote(
            f.privs[i], i, f.cs.height, round_, type_, block_id, CHAIN
        )
        f.cs.add_vote(vote, peer_id=f"late{i}")


def wait_own_prevote(f, round_, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pvs = f.cs.votes.prevotes(round_)
        pv = pvs.get_by_address(f.privs[0].address) if pvs else None
        if pv is not None:
            return pv
        time.sleep(0.01)
    raise AssertionError(f"no own prevote in round {round_}")


def make_alt_block(f, txs=(b"alt-tx",)):
    """A valid-but-different block for the fixture's current height."""
    st = f.cs.state
    return Block.make_block(
        height=st.last_block_height + 1,
        chain_id=CHAIN,
        txs=Txs(list(txs)),
        last_commit=Commit.empty(),
        last_block_id=st.last_block_id,
        time=time.time_ns(),
        validators_hash=st.validators.hash(),
        app_hash=st.app_hash,
    )


def inject_proposal(f, block, round_, pol_round=-1):
    """Craft + inject a proposal signed by the CURRENT proposer."""
    parts = block.make_part_set()
    prop = Proposal(
        height=block.header.height,
        round=round_,
        block_parts_header=parts.header,
        pol_round=pol_round,
        pol_block_id=NIL if pol_round < 0 else BlockID.zero(),
        timestamp=time.time_ns(),
    )
    proposer_addr = f.cs.validators.proposer.address
    priv = next(p for p in f.privs if p.address == proposer_addr)
    sig = priv._signer.sign(prop.sign_bytes(CHAIN))
    f.cs.set_proposal(prop.with_signature(sig), peer_id="test")
    for i in range(parts.total):
        f.cs.add_proposal_block_part(
            block.header.height, round_, parts.get_part(i), peer_id="test"
        )
    return BlockID(block.hash(), parts.header)


class TestPOLSafety:
    def test_old_polka_cannot_steal_newer_lock(self):
        """TestLockPOLSafety2 essence: locked at round 1, a round-2
        proposal carrying a round-0 POL for a DIFFERENT block must not
        unlock us — we keep prevoting the round-1 lock."""
        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            # round 0: our proposal B1 exists but we see NO polka for it;
            # everyone precommits nil -> round 1
            b1_id = f.proposal_block_id()
            f.inject_votes(VOTE_TYPE_PRECOMMIT, NIL, [1, 2, 3])
            wait_round(f, 1)

            # round 1: we are proposer again (accum math keeps the first
            # address for r0 AND r1) and propose a fresh block B2; polka
            # for B2 -> we lock B2 at round 1
            b2_id = f.proposal_block_id()
            assert b2_id.hash != b1_id.hash
            f.inject_votes(VOTE_TYPE_PREVOTE, b2_id, [1, 2, 3], round_=1)
            f.wait_event(ev.EVENT_LOCK)
            rs = f.cs.get_round_state()
            assert rs.locked_round == 1
            assert rs.locked_block.hash() == b2_id.hash

            # drive to round 2
            f.inject_votes(VOTE_TYPE_PRECOMMIT, NIL, [1, 2, 3], round_=1)
            wait_round(f, 2)

            # round 2: the adversary reveals an OLD round-0 polka for B1
            # and proposes a competing block claiming that stale POL
            inject_late_votes(f, VOTE_TYPE_PREVOTE, b1_id, [1, 2, 3], round_=0)
            alt = make_alt_block(f, txs=(b"other-branch",))
            inject_proposal(f, alt, round_=2, pol_round=0)

            # our round-2 prevote must be the LOCKED block, not the
            # proposal with the stale POL
            pv = wait_own_prevote(f, 2)
            assert pv.block_id.hash == b2_id.hash, "lock was stolen by old POL"
            rs = f.cs.get_round_state()
            assert rs.locked_round == 1
            assert rs.locked_block.hash() == b2_id.hash
        finally:
            f.stop()

    def test_late_old_polka_does_not_create_lock(self):
        """TestLockPOLSafety1 essence: we never saw the round-0 polka and
        precommitted nil; when those round-0 prevotes arrive AFTER we
        moved to round 1, no retroactive lock may form."""
        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            b1_id = f.proposal_block_id()
            # round 0 passes with nil precommits (polka withheld from us)
            f.inject_votes(VOTE_TYPE_PRECOMMIT, NIL, [1, 2, 3])
            wait_round(f, 1)
            assert f.cs.get_round_state().locked_block is None

            # the old round-0 polka for B1 arrives late
            inject_late_votes(f, VOTE_TYPE_PREVOTE, b1_id, [1, 2, 3], round_=0)
            time.sleep(0.3)  # give the loop time to (wrongly) react
            rs = f.cs.get_round_state()
            assert rs.locked_block is None and rs.locked_round == -1
        finally:
            f.stop()

    def test_conflicting_votes_tolerated_first_vote_wins(self):
        """Slashing-detection setup (`state_test.go:917`): equivocating
        prevotes from one validator must not crash consensus; the first
        vote is retained."""
        from tests.helpers import byzantine_signed_vote, make_block_id

        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            bid = f.proposal_block_id()
            other = make_block_id(b"equivocation-target")
            v1 = byzantine_signed_vote(
                f.privs[1], 1, 1, 0, VOTE_TYPE_PREVOTE, bid, CHAIN
            )
            v2 = byzantine_signed_vote(
                f.privs[1], 1, 1, 0, VOTE_TYPE_PREVOTE, other, CHAIN
            )
            f.cs.add_vote(v1, peer_id="byz")
            f.cs.add_vote(v2, peer_id="byz")
            time.sleep(0.3)
            assert f.cs.fatal_error is None  # bad peer input never halts
            kept = f.cs.votes.prevotes(0).get_by_address(f.privs[1].address)
            assert kept is not None and kept.block_id.hash == bid.hash
        finally:
            f.stop()

    def test_halt_recovers_via_late_round0_precommit(self):
        """TestHalt1 essence: round 0 ends without visible quorum (2 B,
        1 nil, 1 withheld); after we move to round 1, the withheld
        round-0 precommit for B arrives -> +2/3 at round 0 -> commit."""
        f = Fixture(n_vals=4, real_ticker=True)
        try:
            f.cs.start()
            bid = f.proposal_block_id()
            # polka: we lock + precommit B
            f.inject_votes(VOTE_TYPE_PREVOTE, bid, [1, 2, 3])
            f.wait_event(ev.EVENT_LOCK)
            # only val1 precommits B with us; val2 nil; val3 withheld
            f.inject_votes(VOTE_TYPE_PRECOMMIT, bid, [1])
            f.inject_votes(VOTE_TYPE_PRECOMMIT, NIL, [2])
            wait_round(f, 1)  # precommit-wait timeout fires
            assert f.cs.get_round_state().height == 1
            # withheld round-0 precommit arrives late -> commit height 1
            inject_late_votes(f, VOTE_TYPE_PRECOMMIT, bid, [3], round_=0)
            blk = f.wait_height(1)
            assert blk.header.height == 1
        finally:
            f.stop()


def _wal_record_offsets(path: str) -> list[int]:
    """Byte offsets of every record boundary (after each record)."""
    with open(path, "rb") as fh:
        data = fh.read()
    offsets, off = [], 0
    while off + 8 <= len(data):
        _, length = struct.unpack_from(">II", data, off)
        if off + 8 + length > len(data):
            break
        off += 8 + length
        offsets.append(off)
    return offsets


def _snapshot_db(db: MemDB) -> dict:
    return dict(db._data)


def _restore_db(snapshot: dict) -> MemDB:
    db = MemDB()
    db._data.update(snapshot)
    return db


@pytest.mark.slow
class TestWALPrefixReplay:
    def test_restart_from_every_wal_prefix(self, tmp_path):
        """Reference `consensus/replay_test.go:55-63`: a node must
        recover from a crash at ANY WAL position. Run a solo validator
        a few heights, then restart from the state/store/WAL as they
        were, with the WAL truncated at every record boundary."""
        wal_path = str(tmp_path / "cs.wal")
        db, store_db = MemDB(), MemDB()
        f = Fixture(
            n_vals=1, wal_path=wal_path, db=db, store_db=store_db, real_ticker=True
        )
        f.cs.start()
        f.wait_height(3)
        f.stop()

        with open(wal_path, "rb") as fh:
            wal_bytes = fh.read()
        offsets = _wal_record_offsets(wal_path)
        assert len(offsets) > 10
        db_snap, store_snap = _snapshot_db(db), _snapshot_db(store_db)
        base_height = BlockStore(_restore_db(store_snap)).height

        # every record boundary + a mid-record torn write
        cuts = offsets + [offsets[-1] - 3]
        for cut in cuts:
            trunc = str(tmp_path / f"wal-{cut}.wal")
            with open(trunc, "wb") as fh:
                fh.write(wal_bytes[:cut])
            f2 = Fixture(
                n_vals=1,
                wal_path=trunc,
                db=_restore_db(db_snap),
                store_db=_restore_db(store_snap),
                real_ticker=True,
            )
            try:
                f2.cs.start()
                assert f2.cs.fatal_error is None
                f2.wait_height(base_height + 1, timeout=20)
            finally:
                f2.stop()
